"""Aggregate a service trace log into a phase-attributed latency report.

Reads the crash-tolerant JSONL trace log the optimization server writes
(``hyperopt_tpu.tracing``, one CRC-checked record per sampled request)
and emits ``TRACE_SERVE.json``:

- **phase breakdown** — p50/p95/p99 and total attributed seconds per
  named span (queue wait, batch coalesce, prepare, fused device
  dispatch, readback, finish, journal fsync, store insert, ...), so a
  slow suggest decomposes into named milliseconds instead of one opaque
  number;
- **coverage** — per trace, the fraction of the request's server
  wall-time accounted for by the TILING spans (the phase spans designed
  to partition the root interval).  The acceptance gate: every sampled
  fresh suggest ≥ 90% covered — no dark time;
- **top-N slowest traces** with each one's dominant phase — the p99
  explained, request by request;
- **compile attribution** — every XLA compile event observed during the
  run, with the (trial-bucket, family) key and the trace id that paid
  for it (the ROADMAP's compile-storm hypothesis as a measured fact).

Usage::

    python scripts/trace_report.py <trace.jsonl> [--out TRACE_SERVE.json]
        [--top 10] [--min-coverage 0.9]

Exit code 0 iff the coverage gate holds and every compile event is
attributed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The spans that PARTITION a suggest's server wall-time (each request's
# root interval tiles into these, by construction in
# service/core.py::SuggestScheduler).  Nested detail spans
# (journal.fsync inside suggest.finish, store.write_doc inside
# store.insert, ...) are reported as phases but excluded from the
# coverage sum — they would double-count their parents.
TILING_SPANS = frozenset({
    "suggest.admit",
    "suggest.queue_wait",
    "suggest.coalesce",
    "batch.peer_wait",
    "suggest.draw",
    "suggest.prepare",
    "device.dispatch",
    "device.readback",
    "suggest.finish",
    "suggest.wake",
    "suggest.inline",
})


def _percentile(sorted_vals, q):
    """Nearest-rank-interpolated percentile over a pre-sorted list."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _quantiles_ms(values):
    vals = sorted(values)
    return {
        "p50_ms": (
            round(_percentile(vals, 0.50) * 1e3, 3) if vals else None
        ),
        "p95_ms": (
            round(_percentile(vals, 0.95) * 1e3, 3) if vals else None
        ),
        "p99_ms": (
            round(_percentile(vals, 0.99) * 1e3, 3) if vals else None
        ),
    }


def trace_coverage(record) -> float:
    """Fraction of this trace's root wall-time accounted for by the
    tiling phase spans (clamped to 1.0 — boundary timestamps may
    overlap by a clock quantum)."""
    dur = record.get("duration_s")
    if not dur or dur <= 0:
        return 1.0  # zero-length root: nothing to attribute
    covered = sum(
        s["dur_s"] for s in record.get("spans", ())
        if s["name"] in TILING_SPANS
    )
    return min(1.0, covered / dur)


def dominant_span(record):
    """(name, dur_s) of the largest tiling span (None for a replay or
    span-less trace)."""
    best = None
    for s in record.get("spans", ()):
        if s["name"] not in TILING_SPANS:
            continue
        if best is None or s["dur_s"] > best["dur_s"]:
            best = s
    if best is None:
        return None
    return {"name": best["name"], "dur_s": round(best["dur_s"], 6)}


def analyze(records, top_n=10, min_coverage=0.9) -> dict:
    """The TRACE_SERVE.json payload for a list of trace records."""
    suggests = [r for r in records if r.get("root") == "service.suggest"]
    fresh = [
        r for r in suggests
        if not (r.get("root_attrs") or {}).get("replay")
    ]
    replays = len(suggests) - len(fresh)

    # -- per-phase aggregation over fresh suggest traces ---------------
    phase_durs = {}
    for r in fresh:
        for s in r.get("spans", ()):
            phase_durs.setdefault(s["name"], []).append(s["dur_s"])
    total_root_s = sum(r.get("duration_s") or 0.0 for r in fresh)
    phases = {}
    for name, durs in sorted(phase_durs.items()):
        total = sum(durs)
        phases[name] = {
            "count": len(durs),
            "total_s": round(total, 6),
            "share_of_wall": (
                round(total / total_root_s, 4) if total_root_s else None
            ),
            "tiling": name in TILING_SPANS,
            **_quantiles_ms(durs),
        }

    # -- coverage gate -------------------------------------------------
    coverages = [trace_coverage(r) for r in fresh]
    coverage = {
        "min": round(min(coverages), 4) if coverages else None,
        "mean": (
            round(sum(coverages) / len(coverages), 4) if coverages else None
        ),
        "n_below_gate": sum(1 for c in coverages if c < min_coverage),
        "gate": min_coverage,
    }

    # -- top-N slowest, each with its dominant phase -------------------
    slowest = sorted(
        fresh, key=lambda r: r.get("duration_s") or 0.0, reverse=True
    )[:top_n]
    top = [
        {
            "trace_id": r["trace_id"],
            "duration_ms": round((r.get("duration_s") or 0.0) * 1e3, 3),
            "study": (r.get("root_attrs") or {}).get("study"),
            "dominant": dominant_span(r),
            "coverage": round(trace_coverage(r), 4),
            "n_compiles": sum(
                1 for s in r.get("spans", ()) if s["name"] == "compile"
            ),
        }
        for r in slowest
    ]

    # -- compile attribution (over ALL records, not just suggests) -----
    compiles = []
    for r in records:
        for s in r.get("spans", ()):
            if s["name"] != "compile":
                continue
            attrs = s.get("attrs") or {}
            compiles.append({
                "trace_id": r["trace_id"],
                "root": r.get("root"),
                "bucket": attrs.get("bucket"),
                "families": attrs.get("families"),
            })
    compiles_attributed = all(
        c["trace_id"] and c["bucket"] is not None and c["families"]
        for c in compiles
    )
    by_key = {}
    for c in compiles:
        key = f"{c['bucket']}/{c['families']}"
        by_key[key] = by_key.get(key, 0) + 1

    ok = (
        bool(fresh)
        and coverage["n_below_gate"] == 0
        and compiles_attributed
    )
    return {
        "metric": "trace_serve",
        "ok": ok,
        "n_traces": len(records),
        "n_suggest_traces": len(suggests),
        "n_replay_traces": replays,
        "suggest_latency": _quantiles_ms(
            [r.get("duration_s") or 0.0 for r in fresh]
        ),
        "coverage": coverage,
        "phases": phases,
        "top_slowest": top,
        "compile_events": {
            "n": len(compiles),
            "attributed": compiles_attributed,
            "by_key": dict(sorted(by_key.items())),
            "events": compiles,
        },
    }


def report_for_log(path, top_n=10, min_coverage=0.9) -> dict:
    from hyperopt_tpu.tracing import read_trace_log

    records, torn = read_trace_log(path)
    out = analyze(records, top_n=top_n, min_coverage=min_coverage)
    out["trace_log"] = path
    out["torn_records"] = torn
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_log", help="path to the server's trace JSONL")
    ap.add_argument("--out", default=None,
                    help="also write the report JSON here")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--min-coverage", type=float, default=0.9,
                    dest="min_coverage")
    options = ap.parse_args(argv)
    report = report_for_log(
        options.trace_log, top_n=options.top,
        min_coverage=options.min_coverage,
    )
    print(json.dumps(report, indent=1))
    if options.out:
        with open(options.out, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
