"""Wall-clock-to-target benchmark for the pipelined suggest engine.

BENCH_r05 showed the driver loop adding suggest time to objective time
(0.203 suggests/s at a 10k history).  The pipelined engine
(``hyperopt_tpu.pipeline``) overlaps the two; this benchmark measures
what that buys on the metric that matters to a user: **wall-clock to a
fixed regret target**, on the QUALITY.md domain zoo with a synthetic
objective of >=50 ms per evaluation (60 ms here).

The engine's lands-above hypothesis fit makes the k=1 run reproduce the
serial trajectory **trial-for-trial** (every consumed speculation equals
the post-completion serial suggestion bit-for-bit; every invalidation
re-issues against the complete history — see ``hyperopt_tpu.pipeline``).
The benchmark asserts that equivalence per cell
(``k1_trial_for_trial_matches_serial``), which makes the comparison
clean: both runs cross every quality level at the SAME trial index, so
time-to-target ratios measure pure wall-clock cadence — no seed luck, no
censoring, and "speedup at equal final quality" is exact rather than
statistical.  (The earlier stale-consume engine paid a ~1.3x geomean
trial-efficiency penalty for 1-deep staleness on these domains, which
ate most of the cadence gain; the hypothesis fit removes it.)

Each run is **warm-started from a seeded 400-trial random history**
(identical across arms; the standard trials-continuation pattern), so
the measured 200-trial budget runs entirely in the large-history regime
the pipeline exists for: the Parzen mixture carries one component per
observation, so at a 400-600 observation history the fused suggest
program costs about as much as the 60 ms objective for the WHOLE run —
a fresh history would instead spend half the budget on near-free
suggests that leave nothing to hide (and BASELINE's driver-level target
is the 10k-history regime, where BENCH_r05 measured ~4.9 s/suggest on
CPU).  For each (domain, seed) cell the same seeded ``fmin`` runs at
``max_speculation`` k=0 (the strictly serial pre-pipeline loop), k=1 and
k=4, and reports

- HEADLINE: per cell, ``serial_total_s / k1_total_s`` — the wall-clock
  to complete the SAME 200-trial budget, reaching exactly the same
  regret at every trial (trajectory identity is asserted per cell).
  Geomean over the domain x seed cells.  This is time-to-identical-
  result: every regret level the serial run ever reaches — including
  its final one — is reached by k=1 in that much less wall-clock.
- ``t_serial / t_k1`` to a LADDER of intermediate fixed-regret targets
  (serial best-so-far at 25/50/75/100% of budget) for transparency.
  On domains that keep improving through the run these show the same
  cadence ratio; on domains the warm-started TPE solves in the first
  few measured trials the rungs collapse onto one trivially-early
  target and the ratio degenerates to ~1x — there was nothing left to
  accelerate, which is why the headline times the full equal-quality
  budget instead of a single crossing.
- ``k=4`` on the same ladder: speculations deeper than the in-flight
  window miss intermediate results (bounded staleness), so its
  trajectory DIVERGES from serial; runs that never reach a target are
  censored at total wall time and counted.  It demonstrates why the
  default stays ``max_speculation=1``.
- per-run overlap accounting from ``SpeculationStats`` (suggest time
  hidden behind the objective vs exposed on the critical path, and how
  many dispatches used the hypothesis fit).

``n_EI_candidates`` is set PER DOMAIN so the suggest program's cost
(measured on the CI host at the 500-observation mid-run history) sits
at ~45-70 ms across the run's 400->600 observation span, crossing the
60 ms objective mid-run — maximum overlap headroom at either end.  A
toy config whose suggest costs 2 ms against a 60 ms objective would
measure nothing but sleep.  Candidate scale is not a quality cheat
here: every k shares the identical per-domain config, and k=1 quality
is trial-for-trial IDENTICAL to serial by construction.

The ``serial_reference_vals`` harness re-implements the pre-pipeline
driver protocol from ``Trials``/``Domain`` primitives — no ``FMinIter``
— and the bench asserts the k=0 path reproduces it trial-for-trial
(same sampled points, same order), which is the "k=0 is bit-for-bit the
old serial loop" guarantee of ISSUE 1.

Run (CPU, deterministic seeds; ~25 min):
  JAX_PLATFORMS=cpu python scripts/bench_walltime.py            # writes BENCH_WALLCLOCK.json
  python scripts/bench_walltime.py --quick                      # CI smoke config, no file
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

DOMAINS = ("quadratic1", "branin", "gauss_wave2", "hartmann6")
SEEDS = (0, 1, 2, 3, 4)
KS = (0, 1, 4)
MAX_EVALS = 200
SLEEP_S = 0.06
# seeded random warm-start history each run continues from (identical
# across arms) — puts the whole measured budget in the large-history
# regime; see module docstring
N_PRESEED = 400
# intermediate quality-target ladder: serial best-so-far at these
# budget fractions (the headline times the full equal-quality budget)
LADDER_FRACS = (0.25, 0.5, 0.75, 1.0)
# per-domain candidate counts putting the CPU suggest cost at ~45-70 ms
# across the 400->600 observation span (cost scales with labels x
# candidates x observations, hence fewer candidates for hartmann6's 6
# labels than quadratic1's 1) — see module docstring
N_CAND = {
    "quadratic1": 24576,
    "branin": 10240,
    "gauss_wave2": 8192,
    "hartmann6": 2560,
}
# n_startup_jobs is far below the warm-start size, so TPE (and the
# suggest program worth hiding) is active from the first measured trial
N_STARTUP = 10


def _n_cand_for(n_cand, dname):
    return n_cand[dname] if isinstance(n_cand, dict) else int(n_cand)


def _timed_objective(d, sleep_s, completions):
    """The domain's objective plus a synthetic >=sleep_s evaluation cost;
    appends (perf_counter, loss) at each completion."""

    def objective(cfg):
        loss = d.fn(cfg)
        time.sleep(sleep_s)
        completions.append((time.perf_counter(), float(loss)))
        return loss

    return objective


def _preseed(d, trials, n_preseed, seed):
    """Insert the seeded ``n_preseed``-trial random warm-start history
    (state DONE, losses from the domain's real objective, no synthetic
    sleep, untimed) — deterministic in ``seed``, so every arm of a cell
    continues from the identical history."""
    from hyperopt_tpu.algos import rand
    from hyperopt_tpu.base import Ctrl, Domain, JOB_STATE_DONE, spec_from_misc

    if not n_preseed:
        return
    domain = Domain(d.fn, d.space)
    rstate = np.random.default_rng(seed + 10 ** 6)
    ids = trials.new_trial_ids(n_preseed)
    trials.refresh()
    docs = rand.suggest(
        ids, domain, trials, int(rstate.integers(2 ** 31 - 1))
    )
    trials.insert_trial_docs(docs)
    trials.refresh()
    for tr in trials._dynamic_trials[-n_preseed:]:
        spec = spec_from_misc(tr["misc"])
        tr["result"] = domain.evaluate(spec, Ctrl(trials, current_trial=tr))
        tr["state"] = JOB_STATE_DONE
    trials.refresh()


def run_one(dname, k, seed, max_evals=MAX_EVALS, sleep_s=SLEEP_S,
            n_cand=N_CAND, n_startup=None, n_preseed=N_PRESEED):
    """One seeded fmin run at speculation depth k, continuing from the
    seeded warm-start history; returns the measured-trial trajectory +
    overlap stats + the per-trial sampled points (for equivalence checks)."""
    from functools import partial

    from hyperopt_tpu import Trials
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.base import Domain
    from hyperopt_tpu.fmin import FMinIter
    from hyperopt_tpu.models import domains as zoo

    d = zoo.get(dname)
    completions = []
    domain = Domain(_timed_objective(d, sleep_s, completions), d.space)
    trials = Trials()
    _preseed(d, trials, n_preseed, seed)
    kw = {"n_EI_candidates": _n_cand_for(n_cand, dname)}
    if n_startup is not None:
        kw["n_startup_jobs"] = n_startup
    algo = partial(tpe.suggest, **kw)
    rval = FMinIter(
        algo, domain, trials, rstate=np.random.default_rng(seed),
        max_evals=n_preseed + max_evals, show_progressbar=False,
        verbose=False, max_speculation=k,
    )
    rval.catch_eval_exceptions = False
    t0 = time.perf_counter()
    rval.exhaust()
    total_s = time.perf_counter() - t0

    # completion-order best-so-far trajectory, timestamps relative to t0
    traj, best = [], float("inf")
    for t, loss in completions:
        if np.isfinite(loss):
            best = min(best, loss)
        traj.append((t - t0, best))
    vals = [t["misc"]["vals"] for t in trials.trials]
    return {
        "domain": dname, "k": k, "seed": seed,
        "total_s": total_s, "traj": traj, "vals": vals,
        "final_best": best,
        "fmin": float(d.fmin), "threshold": float(d.quality_threshold),
        "speculation": rval.speculation_stats.summary(),
    }


def serial_reference_vals(dname, seed, max_evals, n_cand=N_CAND,
                          n_startup=None, n_preseed=N_PRESEED):
    """The PRE-PIPELINE serial driver protocol, from primitives: enqueue
    one trial (fresh ids -> refresh -> one rstate seed draw -> algo),
    evaluate it to completion, repeat — continuing from the same seeded
    warm-start history as the timed runs.  No FMinIter, no engine — the
    independent reference the k=0 path must reproduce trial-for-trial."""
    from functools import partial

    from hyperopt_tpu import Trials
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.base import (
        Ctrl, Domain, JOB_STATE_DONE, spec_from_misc,
    )
    from hyperopt_tpu.models import domains as zoo

    d = zoo.get(dname)
    domain = Domain(d.fn, d.space)
    trials = Trials()
    _preseed(d, trials, n_preseed, seed)
    kw = {"n_EI_candidates": _n_cand_for(n_cand, dname)}
    if n_startup is not None:
        kw["n_startup_jobs"] = n_startup
    algo = partial(tpe.suggest, **kw)
    rstate = np.random.default_rng(seed)
    for _ in range(max_evals):
        new_ids = trials.new_trial_ids(1)
        trials.refresh()
        docs = algo(new_ids, domain, trials, rstate.integers(2 ** 31 - 1))
        trials.insert_trial_docs(docs)
        trials.refresh()
        trial = trials._dynamic_trials[-1]
        spec = spec_from_misc(trial["misc"])
        result = domain.evaluate(spec, Ctrl(trials, current_trial=trial))
        trial["state"] = JOB_STATE_DONE
        trial["result"] = result
        trials.refresh()
    return [t["misc"]["vals"] for t in trials.trials]


def _time_to(traj, total_s, target_loss):
    """First timestamp at which best-so-far <= target_loss; censored at
    total_s when never reached.  Returns (seconds, reached)."""
    for t, best in traj:
        if best <= target_loss:
            return t, True
    return total_s, False


def _geomean(xs):
    xs = [x for x in xs if x > 0 and np.isfinite(x)]
    return float(np.exp(np.mean(np.log(xs)))) if xs else None


def _regret(run):
    base = run["fmin"] if np.isfinite(run["fmin"]) else 0.0
    return run["final_best"] - base


def run_bench(domains=DOMAINS, seeds=SEEDS, ks=KS, max_evals=MAX_EVALS,
              sleep_s=SLEEP_S, n_cand=N_CAND, n_startup=N_STARTUP,
              n_preseed=N_PRESEED, check_equivalence=True, log=print):
    """Full benchmark; returns the BENCH_WALLCLOCK.json payload."""
    assert 0 in ks, "the serial baseline (k=0) must be among ks"
    runs, cells = [], []
    for dname in domains:
        # untimed warmup: the jit cache is global, so whichever run goes
        # first would otherwise pay every XLA compile (the bucket-growth
        # recompiles along the 0..max_evals history) and the timed cells
        # would compare a cold serial run against warm pipelined ones.
        # A zero-sleep serial run over the same trial schedule populates
        # the cache for every timed run of this domain (the k>0 runs
        # additionally touch the hypothetical-append programs: warm
        # those with a short k=1 run).
        t0 = time.perf_counter()
        run_one(dname, 0, seeds[0], max_evals, 0.0, n_cand, n_startup,
                n_preseed)
        run_one(dname, 1, seeds[0], max_evals, 0.0, n_cand, n_startup,
                n_preseed)
        log(f"  {dname}: jit warmup {time.perf_counter() - t0:.2f}s")
        for seed in seeds:
            cell = {}
            for k in ks:
                r = run_one(dname, k, seed, max_evals, sleep_s, n_cand,
                            n_startup, n_preseed)
                cell[k] = r
                runs.append(r)
                log(
                    f"  {dname} seed={seed} k={k}: {r['total_s']:.2f}s total, "
                    f"final_best={r['final_best']:.4f}, "
                    f"hidden={r['speculation']['hidden_s']}s"
                )
            cells.append((dname, seed, cell))

    # k=0 must reproduce the pre-pipeline serial protocol trial-for-trial
    k0_matches_serial = None
    if check_equivalence:
        k0_matches_serial = True
        for dname in domains:
            ref = serial_reference_vals(dname, seeds[0], max_evals, n_cand,
                                        n_startup, n_preseed)
            got = [
                c[0]["vals"] for dn, sd, c in cells
                if dn == dname and sd == seeds[0]
            ][0]
            if not _vals_equal(ref, got):
                k0_matches_serial = False
                log(f"  EQUIVALENCE FAILURE: k=0 != serial reference on "
                    f"{dname} seed={seeds[0]}")

    # k=1 must reproduce the k=0 trajectory trial-for-trial (the
    # hypothesis-exact guarantee) — checked on EVERY cell
    k1_matches_serial = None
    if 1 in ks:
        k1_matches_serial = True
        for dname, seed, cell in cells:
            if not _vals_equal(cell[0]["vals"], cell[1]["vals"]):
                k1_matches_serial = False
                log(f"  EQUIVALENCE FAILURE: k=1 != k=0 trajectory on "
                    f"{dname} seed={seed}")

    speedups = {
        k: {f: [] for f in LADDER_FRACS} for k in ks if k
    }
    n_censored = {k: 0 for k in ks if k}
    cell_rows = []
    for dname, seed, cell in cells:
        serial = cell[0]
        traj0 = serial["traj"]
        fmin_v = serial["fmin"] if np.isfinite(serial["fmin"]) else 0.0
        # the target ladder: serial best-so-far at each budget fraction
        ladder = {}
        for f in LADDER_FRACS:
            i = min(len(traj0) - 1, max(0, int(round(f * max_evals)) - 1))
            ladder[f] = traj0[i][1]
        row = {
            "domain": dname, "seed": seed,
            "targets": {
                str(f): {
                    "loss": float(ladder[f]),
                    "regret": float(ladder[f] - fmin_v),
                }
                for f in LADDER_FRACS
            },
            "serial_total_s": round(serial["total_s"], 3),
            "serial_final_best": serial["final_best"],
        }
        for f in LADDER_FRACS:
            t0_f, _ = _time_to(traj0, serial["total_s"], ladder[f])
            row[f"serial_time_to_{f}"] = round(t0_f, 3)
        for k in ks:
            if k == 0:
                continue
            for f in LADDER_FRACS:
                t0_f, _ = _time_to(traj0, serial["total_s"], ladder[f])
                tk_f, rk = _time_to(cell[k]["traj"], cell[k]["total_s"],
                                    ladder[f])
                if not rk:
                    n_censored[k] += 1
                speedups[k][f].append(t0_f / tk_f)
                row[f"k{k}_time_to_{f}"] = round(tk_f, 3)
                row[f"k{k}_speedup_{f}"] = round(t0_f / tk_f, 3)
                if not rk:
                    row[f"k{k}_censored_{f}"] = True
            row[f"k{k}_total_s"] = round(cell[k]["total_s"], 3)
            row[f"k{k}_final_best"] = cell[k]["final_best"]
        cell_rows.append(row)

    import jax

    completion = [
        cell[0]["total_s"] / cell[1]["total_s"]
        for _, _, cell in cells
        if 1 in cell
    ]
    headline = _geomean(completion)
    out = {
        "metric": "wallclock_equal_quality_speedup_k1",
        "value": round(headline, 3) if headline else None,
        "unit": (
            "x (geomean over domain x seed cells of serial_total_s / "
            "k1_total_s for the same 200-trial budget; the k=1 run "
            "reproduces the serial trajectory trial-for-trial — asserted "
            "per cell — so it reaches every regret level the serial run "
            "ever reaches, including its final one, in that much less "
            "wall-clock)"
        ),
        "platform": jax.devices()[0].platform,
        "config": {
            "domains": list(domains), "seeds": list(seeds), "ks": list(ks),
            "max_evals": max_evals, "objective_sleep_ms": sleep_s * 1e3,
            "n_EI_candidates": (
                dict(n_cand) if isinstance(n_cand, dict) else n_cand
            ),
            "ladder_fracs": list(LADDER_FRACS),
            "n_startup_jobs": n_startup,
            "n_preseed": n_preseed,
        },
        "speedups": {
            f"k{k}": dict(
                {
                    f"to_{f}_geomean": round(_geomean(v[f]), 3)
                    for f in LADDER_FRACS
                },
                completion_geomean=round(
                    _geomean(
                        [
                            cell[0]["total_s"] / cell[k]["total_s"]
                            for _, _, cell in cells
                            if k in cell
                        ]
                    ),
                    3,
                ),
            )
            for k, v in speedups.items()
        },
        "throughput": {
            f"k{k}": {
                "total_s_sum": round(
                    sum(r["total_s"] for r in runs if r["k"] == k), 2
                ),
                "mean_final_regret": round(
                    float(np.mean([_regret(r) for r in runs if r["k"] == k])),
                    4,
                ),
            }
            for k in ks
        },
        "overlap": {
            f"k{k}": _sum_speculation(
                [r["speculation"] for r in runs if r["k"] == k]
            )
            for k in ks
            if k
        },
        "n_censored_at_budget": {f"k{k}": v for k, v in n_censored.items()},
        "k0_trial_for_trial_matches_pre_pipeline_serial": k0_matches_serial,
        "k1_trial_for_trial_matches_serial": k1_matches_serial,
        "cells": cell_rows,
    }
    return out


def _vals_equal(a, b):
    if len(a) != len(b):
        return False
    for va, vb in zip(a, b):
        if set(va) != set(vb):
            return False
        for lb in va:
            if not np.allclose(va[lb], vb[lb], rtol=0, atol=0):
                return False
    return True


def _sum_speculation(summaries):
    hidden = sum(s["hidden_s"] for s in summaries)
    exposed = sum(s["exposed_s"] for s in summaries)
    return {
        "hidden_s": round(hidden, 3),
        "exposed_s": round(exposed, 3),
        "hidden_frac": round(hidden / (hidden + exposed), 4)
        if hidden + exposed
        else None,
        "n_dispatched": sum(s["n_dispatched"] for s in summaries),
        "n_hypothesis": sum(s.get("n_hypothesis", 0) for s in summaries),
        "n_used": sum(s["n_used"] for s in summaries),
        "n_invalidated": sum(s["n_invalidated"] for s in summaries),
        "n_sync": sum(s["n_sync"] for s in summaries),
    }


QUICK = dict(
    domains=("quadratic1", "gauss_wave2"), seeds=(0,), ks=(0, 1),
    max_evals=12, sleep_s=0.003, n_cand=64, n_startup=5, n_preseed=20,
)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="tiny CI smoke config; does not write the artifact")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_WALLCLOCK.json",
    ))
    args = ap.parse_args(argv)

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

    out = run_bench(**QUICK) if args.quick else run_bench()
    print(json.dumps(out, indent=1))
    if not args.quick:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")
        print(f"wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
