"""Optimization-quality vs n_EI_candidates study (VERDICT r4 #4).

The rebuild's thesis (SURVEY.md §7) is that the TPU port makes
``n_EI_candidates`` cheap to raise by orders of magnitude.  This study
measures whether candidate scale buys *optimization quality* — best loss
after N trials — not just scorer throughput: seeded ``fmin`` runs on zoo
domains at n_EI_candidates ∈ {24, 1024, 65536}, through both the
single-device path and the mesh path (8-virtual-device CPU mesh), and
writes ``QUALITY.json`` + the ``QUALITY.md`` table.

Run:  python scripts/quality_study.py [--quick]
(CPU; wall clock dominated by the 65536-candidate scoring.)
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

DOMAINS = ("quadratic1", "branin", "gauss_wave2", "hartmann6")
CAND_SIZES = (24, 1024, 65536)
SEEDS = (0, 1, 2, 3)
MAX_EVALS = 60


def run_one(dname, n_cand, seed, mesh):
    from functools import partial

    from hyperopt_tpu import Trials, fmin
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.models import domains as zoo

    d = zoo.get(dname)
    trials = Trials()
    algo = partial(tpe.suggest, n_EI_candidates=n_cand, mesh=mesh)
    t0 = time.time()
    fmin(
        d.fn, d.space, algo=algo, max_evals=MAX_EVALS, trials=trials,
        rstate=np.random.default_rng(seed), show_progressbar=False,
        verbose=False,
    )
    # NaN losses are legitimate diverged trials (gauss_wave2 emits them);
    # they must not poison the min
    best = min(l for l in trials.losses() if l is not None and not np.isnan(l))
    # regret vs the domain's known optimum where available (BenchDomain
    # encodes "unknown" as NaN), else raw best loss
    known = d.fmin is not None and np.isfinite(d.fmin)
    regret = best - d.fmin if known else best
    return float(regret), time.time() - t0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out-json", default="QUALITY.json")
    ap.add_argument("--out-md", default="QUALITY.md")
    ap.add_argument(
        "--from-json", action="store_true",
        help="skip the runs; regenerate the markdown from --out-json",
    )
    args = ap.parse_args(argv)

    if args.from_json:
        # pure report regeneration: no jax, no runs
        with open(args.out_json) as f:
            blob = json.load(f)
        results = blob["results"]
        meta = blob["meta"]
        domains_ = meta["domains"]
        seeds = meta["seeds"]
        cands = meta["cand_sizes"]
    else:
        import jax

        try:
            # the axon sitecustomize registers the (tunnel) TPU platform
            # at interpreter start, before this script's env guards run —
            # force CPU the way __graft_entry__.dryrun_multichip does
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        if jax.default_backend() != "cpu":
            # refuse to time fmin over the flaky TPU tunnel while the
            # artifact below would claim CPU
            raise SystemExit(
                f"quality_study must run on CPU, got {jax.default_backend()!r}"
            )

        from hyperopt_tpu.parallel.sharding import default_mesh

        domains_ = DOMAINS[:2] if args.quick else DOMAINS
        seeds = SEEDS[:2] if args.quick else SEEDS
        cands = CAND_SIZES[:2] if args.quick else CAND_SIZES

        mesh = default_mesh()
        results = {}  # (mode, domain, n_cand) -> [regret per seed]
        for mode, m in (("device", None), ("mesh", mesh)):
            for dname in domains_:
                for n_cand in cands:
                    key = f"{mode}/{dname}/c{n_cand}"
                    rs, secs = [], 0.0
                    for seed in seeds:
                        r, s = run_one(dname, n_cand, seed, m)
                        rs.append(r)
                        secs += s
                    results[key] = {
                        "mean_regret": float(np.mean(rs)),
                        "median_regret": float(np.median(rs)),
                        "per_seed": rs,
                        "wall_s": round(secs, 1),
                    }
                    print(f"{key}: mean_regret={np.mean(rs):.4g} ({secs:.0f}s)",
                          flush=True)

        meta = {
            "max_evals": MAX_EVALS,
            "seeds": list(seeds),
            "domains": list(domains_),
            "cand_sizes": list(cands),
            "platform": (
                f"{jax.default_backend()} "
                f"({len(jax.devices())}-device mesh for the mesh rows)"
            ),
        }
        with open(args.out_json, "w") as f:
            json.dump(
                {"meta": meta, "results": results}, f, indent=1, sort_keys=True
            )

    lines = [
        "# Quality vs candidate scale",
        "",
        "Does raising `n_EI_candidates` (cheap on TPU — see BENCH_TPU.json) buy",
        "*optimization quality*, or only scorer throughput?  Mean regret (best",
        f"loss after {MAX_EVALS} trials minus the domain optimum) over seeds",
        f"{list(seeds)}, generated by `scripts/quality_study.py` (seeded,",
        "CPU).  Raw per-seed numbers: `QUALITY.json`.",
        "",
        "| path | domain | " + " | ".join(f"c={c}" for c in cands) + " |",
        "|---|---|" + "---|" * len(cands),
    ]
    for mode in ("device", "mesh"):
        for dname in domains_:
            row = [mode, dname]
            for c in cands:
                row.append(f"{results[f'{mode}/{dname}/c{c}']['mean_regret']:.4g}")
            lines.append("| " + " | ".join(row) + " |")
    lines.append("")

    # data-driven verdict per domain: does the largest candidate count
    # beat the smallest by more than noise (10% of the per-seed spread)?
    lines.append("## Verdict")
    lines.append("")
    c_lo, c_hi = cands[0], cands[-1]
    verdicts = {}
    for dname in domains_:
        lo = results[f"device/{dname}/c{c_lo}"]
        hi = results[f"device/{dname}/c{c_hi}"]
        spread = float(np.std(lo["per_seed"])) + 1e-12
        delta = hi["mean_regret"] - lo["mean_regret"]
        if delta < -0.1 * spread:
            v = "improves"
        elif delta > 0.1 * spread:
            v = "worsens"
        else:
            v = "flat"
        verdicts[dname] = v
        lines.append(
            f"- `{dname}`: c={c_hi} vs c={c_lo} → mean-regret delta "
            f"{delta:+.4g} (seed spread {spread:.3g}) — **{v}**"
        )
    lines.append("")
    by_class = {
        v: sorted(d for d, vv in verdicts.items() if vv == v)
        for v in ("improves", "flat", "worsens")
    }

    def _names(v):
        return ", ".join(f"`{d}`" for d in by_class[v]) or "none in this run"

    lines.append(
        "Candidate scale is a free knob on TPU (BENCH_TPU.json measures the "
        "throughput headroom); this table measures what it buys in final "
        "quality at a 60-trial budget.  The honest summary: **it depends on "
        "the objective's structure, and the default should stay modest.**  "
        f"Where the verdict is `flat` ({_names('flat')}), quality saturates "
        "at small candidate counts and the TPU payoff is "
        "wall-clock-to-equal-quality, not a better optimum.  Where it "
        f"`improves` ({_names('improves')} — typically multimodal "
        "objectives with narrow deep modes), the EI argmax over a much "
        "larger l(x) sample finds modes 24 draws miss, and scale buys a "
        f"better optimum outright.  Where it `worsens` ({_names('worsens')} "
        "— typically smooth low-dimensional objectives), a larger sample "
        "over-exploits: the argmax lands deeper inside the incumbent l(x) "
        "mode, trading exploration away — the classic reason the "
        "reference's default is 24 candidates, and the reason this "
        "framework keeps that default while making scale available per "
        "call.  The `device` and `mesh` rows agree because the unified "
        "path makes the mesh a scoring layout, not an algorithm fork "
        "(tests/test_parallel.py::test_mesh_and_device_paths_agree)."
    )
    lines.append("")
    with open(args.out_md, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {args.out_json} and {args.out_md}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
