"""Batched-suggest scaling sweep on the live backend.

Measures end-to-end ``tpe.suggest`` throughput (trials/sec) at a
10k-trial history for several batch sizes k in ONE process, quantifying
how batching amortizes the per-dispatch overhead (here dominated by the
bench tunnel's ~80-95 ms RTT; ~100 us on a normal TPU host).  This is
the production mode of ``JaxTrials(parallelism=k)``: one suggest call
produces k trials.

Per-k **limiter attribution** (VERDICT "weak" #2 — where does batched
throughput saturate, and on what): a
:class:`hyperopt_tpu.profiling.DeviceProfiler` observes every fused
dispatch in the timed window, splitting each call into

- ``dispatch_ms`` — host launch of the fused program (jit-cache lookup
  + argument marshal + async dispatch; includes the tunnel round trip
  when the chip is remote),
- ``readback_ms`` — the blocking device readback (device compute not
  hidden by the launch, plus the output transfer),
- ``host_ms`` — everything else in ``tpe.suggest`` (history sync,
  request build, winner->doc finish),

and ``limiter`` names the largest share.  The decade where
``suggests_per_sec`` flattens while ``readback_ms`` grows is the point
where the device itself — not per-call overhead — becomes the
bottleneck.

Writes one JSON line (commit as BENCH_TPU_batched.json when captured on
hardware):
  {"platform": "tpu", "n_history": 10000, "rows":
    [{"k": 32, "suggests_per_sec": ..., "ms_per_suggest_call": ...,
      "dispatch_ms": ..., "readback_ms": ..., "host_ms": ...,
      "limiter": "..."}, ...]}

Run:  python scripts/batched_suggest_sweep.py            (TPU via tunnel)
      BENCH_SWEEP_KS=8,32 python scripts/batched_suggest_sweep.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KS = tuple(
    int(x) for x in os.environ.get(
        "BENCH_SWEEP_KS", "8,32,128,512,1024,2048"
    ).split(",")
)
REPS = int(os.environ.get("BENCH_SWEEP_REPS", 5))


def main():
    import jax

    import bench
    from hyperopt_tpu import profiling
    from hyperopt_tpu.observability import DeviceStats

    platform = jax.devices()[0].platform
    domain, trials = bench.build_history_trials()
    from hyperopt_tpu.algos import tpe

    n_cand = bench.N_EI_CANDIDATES
    rows = []
    next_id = bench.N_HISTORY
    for k in KS:
        # warm: compile the k-sized batch program outside the timed
        # window (and outside the profiler — the timed stats must hold
        # steady-state dispatches only)
        ids = list(range(next_id, next_id + k))
        next_id += k
        tpe.suggest(ids, domain, trials, 0, n_EI_candidates=n_cand, verbose=False)
        stats = DeviceStats()
        with profiling.DeviceProfiler(stats=stats):
            t0 = time.perf_counter()
            for r in range(REPS):
                ids = list(range(next_id, next_id + k))
                next_id += k
                tpe.suggest(
                    ids, domain, trials, r + 1, n_EI_candidates=n_cand,
                    verbose=False,
                )
            per_call = (time.perf_counter() - t0) / REPS
        s = stats.summary()
        n = max(s["n_dispatches"], 1)
        dispatch_ms = s["launch_s"] / n * 1e3
        readback_ms = s["readback_s"] / n * 1e3
        host_ms = max(per_call * 1e3 - dispatch_ms - readback_ms, 0.0)
        shares = {
            "dispatch": dispatch_ms,
            "device_readback": readback_ms,
            "host": host_ms,
        }
        rows.append(
            {
                "k": k,
                "suggests_per_sec": round(k / per_call, 2),
                "ms_per_suggest_call": round(per_call * 1e3, 2),
                "dispatch_ms": round(dispatch_ms, 2),
                "readback_ms": round(readback_ms, 2),
                "host_ms": round(host_ms, 2),
                "limiter": max(shares, key=shares.get),
                "n_dispatches_observed": s["n_dispatches"],
                "binding_ceiling": (
                    s["signatures"][0]["binding_ceiling"]
                    if s["signatures"] else None
                ),
            }
        )
        print(
            f"# k={k}: {rows[-1]['suggests_per_sec']}/s "
            f"limiter={rows[-1]['limiter']} "
            f"(dispatch {rows[-1]['dispatch_ms']}ms / readback "
            f"{rows[-1]['readback_ms']}ms / host {rows[-1]['host_ms']}ms)",
            file=sys.stderr,
        )

    out = {
        "metric": f"tpe_batched_suggests_per_sec_{bench.N_HISTORY}_history",
        "platform": platform,
        "n_history": bench.N_HISTORY,
        "n_EI_candidates": n_cand,
        "reps_per_k": REPS,
        "rows": rows,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
