"""Batched-suggest scaling sweep on the live backend.

Measures end-to-end ``tpe.suggest`` throughput (trials/sec) at a
10k-trial history for several batch sizes k in ONE process, quantifying
how batching amortizes the per-dispatch overhead (here dominated by the
bench tunnel's ~80-95 ms RTT; ~100 us on a normal TPU host).  This is
the production mode of ``JaxTrials(parallelism=k)``: one suggest call
produces k trials.

Writes one JSON line (commit as BENCH_TPU_batched.json when captured on
hardware):
  {"platform": "tpu", "n_history": 10000, "rows":
    [{"k": 32, "suggests_per_sec": ..., "ms_per_suggest_call": ...}, ...]}

Run:  python scripts/batched_suggest_sweep.py            (TPU via tunnel)
      BENCH_SWEEP_KS=8,32 python scripts/batched_suggest_sweep.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KS = tuple(
    int(x) for x in os.environ.get("BENCH_SWEEP_KS", "8,32,128,512").split(",")
)
REPS = int(os.environ.get("BENCH_SWEEP_REPS", 5))


def main():
    import jax

    import bench

    platform = jax.devices()[0].platform
    domain, trials = bench.build_history_trials()
    from hyperopt_tpu.algos import tpe

    n_cand = bench.N_EI_CANDIDATES
    rows = []
    next_id = bench.N_HISTORY
    for k in KS:
        # warm: compile the k-sized batch program outside the timed window
        ids = list(range(next_id, next_id + k))
        next_id += k
        tpe.suggest(ids, domain, trials, 0, n_EI_candidates=n_cand, verbose=False)
        t0 = time.perf_counter()
        for r in range(REPS):
            ids = list(range(next_id, next_id + k))
            next_id += k
            tpe.suggest(
                ids, domain, trials, r + 1, n_EI_candidates=n_cand, verbose=False
            )
        per_call = (time.perf_counter() - t0) / REPS
        rows.append(
            {
                "k": k,
                "suggests_per_sec": round(k / per_call, 2),
                "ms_per_suggest_call": round(per_call * 1e3, 2),
            }
        )
        print(f"# k={k}: {rows[-1]['suggests_per_sec']}/s", file=sys.stderr)

    out = {
        "metric": f"tpe_batched_suggests_per_sec_{bench.N_HISTORY}_history",
        "platform": platform,
        "n_history": bench.N_HISTORY,
        "n_EI_candidates": n_cand,
        "reps_per_k": REPS,
        "rows": rows,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
