"""Batched-suggest scaling sweep on the live backend.

Measures end-to-end ``tpe.suggest`` throughput (trials/sec) at a
10k-trial history for several batch sizes k in ONE process, quantifying
how batching amortizes the per-dispatch overhead (here dominated by the
bench tunnel's ~80-95 ms RTT; ~100 us on a normal TPU host).  This is
the production mode of ``JaxTrials(parallelism=k)``: one suggest call
produces k trials.

Per-k **limiter attribution** (VERDICT "weak" #2 — where does batched
throughput saturate, and on what): a
:class:`hyperopt_tpu.profiling.DeviceProfiler` observes every fused
dispatch in the timed window, splitting each call into

- ``dispatch_ms`` — host launch of the fused program (jit-cache lookup
  + argument marshal + async dispatch; includes the tunnel round trip
  when the chip is remote),
- ``readback_ms`` — the blocking device readback (device compute not
  hidden by the launch, plus the output transfer),
- ``host_ms`` — everything else in ``tpe.suggest`` (history sync,
  request build, winner->doc finish),

and ``limiter`` names the largest share.  The decade where
``suggests_per_sec`` flattens while ``readback_ms`` grows is the point
where the device itself — not per-call overhead — becomes the
bottleneck.

**Mesh arms** (``--mesh auto`` / ``--mesh DPxSP``, ISSUE 11): each k is
additionally timed with the fused program sharded across the mesh
(candidates over dp, Parzen components over sp — trial-for-trial
identical suggestions, see docs/sharding.md), and every row carries
**per-device limiter attribution**: each participating chip's dispatch
count, busy-ms mean, and duty cycle over the timed window, so a skewed
shard shows up as one hot chip.  Off-TPU, force a virtual mesh with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the CI proof).

Writes one JSON line.  Without ``--mesh`` the output keeps the
BENCH_TPU_batched.json shape (single-arm rows); with mesh arms it is
the BENCH_TPU_sharded.json shape: ``rows`` carry a ``"mesh"`` field
("off" | "DPxSP") per (k, arm) and ``"per_device"`` maps.

Run:  python scripts/batched_suggest_sweep.py              (single-chip)
      python scripts/batched_suggest_sweep.py --mesh auto  (off + mesh arms)
      BENCH_SWEEP_KS=8,32 python scripts/batched_suggest_sweep.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KS = tuple(
    int(x) for x in os.environ.get(
        "BENCH_SWEEP_KS", "8,32,128,512,1024,2048"
    ).split(",")
)
REPS = int(os.environ.get("BENCH_SWEEP_REPS", 5))


def _arm_label(mesh):
    from hyperopt_tpu.parallel.sharding import mesh_shape_str

    return mesh_shape_str(mesh)


def run_sweep(ks=KS, reps=REPS, mesh_arms=(None,), n_history=None,
              n_cand=None):
    """The sweep body: one process, one warm history, rows per
    (k, mesh arm).  ``mesh_arms`` entries are anything
    ``tpe.suggest(mesh=...)`` accepts (None = single-chip)."""
    import jax

    import bench
    from hyperopt_tpu import profiling
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.observability import DeviceStats
    from hyperopt_tpu.parallel.sharding import resolve_mesh

    platform = jax.devices()[0].platform
    n_history = bench.N_HISTORY if n_history is None else int(n_history)
    n_cand = bench.N_EI_CANDIDATES if n_cand is None else int(n_cand)
    domain, trials = bench.build_history_trials(n_history=n_history)

    arms = [resolve_mesh(m) for m in mesh_arms]
    rows = []
    next_id = n_history
    for mesh in arms:
        label = _arm_label(mesh)
        for k in ks:
            # warm: compile the (k, mesh) batch program outside the
            # timed window (and outside the profiler — the timed stats
            # must hold steady-state dispatches only)
            ids = list(range(next_id, next_id + k))
            next_id += k
            tpe.suggest(ids, domain, trials, 0, n_EI_candidates=n_cand,
                        mesh=mesh, verbose=False)
            stats = DeviceStats()
            with profiling.DeviceProfiler(stats=stats):
                t0 = time.perf_counter()
                for r in range(reps):
                    ids = list(range(next_id, next_id + k))
                    next_id += k
                    tpe.suggest(
                        ids, domain, trials, r + 1, n_EI_candidates=n_cand,
                        mesh=mesh, verbose=False,
                    )
                per_call = (time.perf_counter() - t0) / reps
            s = stats.summary()
            n = max(s["n_dispatches"], 1)
            dispatch_ms = s["launch_s"] / n * 1e3
            readback_ms = s["readback_s"] / n * 1e3
            host_ms = max(per_call * 1e3 - dispatch_ms - readback_ms, 0.0)
            shares = {
                "dispatch": dispatch_ms,
                "device_readback": readback_ms,
                "host": host_ms,
            }
            per_device = {
                dev: {
                    "n_dispatches": row["n_dispatches"],
                    "busy_ms_mean": round(
                        row["busy_s"] / max(row["n_dispatches"], 1) * 1e3, 3
                    ),
                    "duty_cycle": row["duty_cycle"],
                }
                for dev, row in s["per_device"].items()
            }
            rows.append(
                {
                    "k": k,
                    "mesh": label,
                    "suggests_per_sec": round(k / per_call, 2),
                    "ms_per_suggest_call": round(per_call * 1e3, 2),
                    "dispatch_ms": round(dispatch_ms, 2),
                    "readback_ms": round(readback_ms, 2),
                    "host_ms": round(host_ms, 2),
                    "limiter": max(shares, key=shares.get),
                    "n_dispatches_observed": s["n_dispatches"],
                    "per_device": per_device,
                    "binding_ceiling": (
                        s["signatures"][0]["binding_ceiling"]
                        if s["signatures"] else None
                    ),
                }
            )
            print(
                f"# mesh={label} k={k}: {rows[-1]['suggests_per_sec']}/s "
                f"limiter={rows[-1]['limiter']} "
                f"(dispatch {rows[-1]['dispatch_ms']}ms / readback "
                f"{rows[-1]['readback_ms']}ms / host "
                f"{rows[-1]['host_ms']}ms, "
                f"{len(per_device)} device(s))",
                file=sys.stderr,
            )

    sharded = any(m is not None for m in arms)
    return {
        "metric": (
            f"tpe_sharded_suggests_per_sec_{n_history}_history" if sharded
            else f"tpe_batched_suggests_per_sec_{n_history}_history"
        ),
        "platform": platform,
        "n_devices": int(jax.device_count()),
        "mesh_arms": [_arm_label(m) for m in arms],
        "n_history": n_history,
        "n_EI_candidates": n_cand,
        "reps_per_k": reps,
        "rows": rows,
    }


def main():
    argv = sys.argv[1:]
    mesh_arms = [None]
    if "--mesh" in argv:
        spec = argv[argv.index("--mesh") + 1]
        # the sharded artifact always carries the single-chip arm too:
        # the headline IS the off-vs-mesh ratio at each k
        mesh_arms = [None, spec]
    out = run_sweep(mesh_arms=mesh_arms)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
