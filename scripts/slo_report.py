"""The ISSUE-9 acceptance run → ``SLO_SERVE.json``.

Four sections, each a gate:

1. **healthy** — the full seeded loadgen campaign (durable root,
   idempotent clients) with the SL6xx catalog evaluated at the end:
   every rule must be ``ok`` or ``no_data`` (nothing breaching), the
   warm/cold latency split must attribute the tail, and the
   storage-plane counters must RECONCILE against trial counts (one
   segment append per trial-state transition, one journal append per
   keyed mutation, zero per-doc writes and ZERO directory scans
   anywhere on the segmented default backend).
2. **fixtures** — one seeded forced-breach fixture per rule: synthetic
   stats driven through a real :class:`hyperopt_tpu.slo.SloEngine` +
   :class:`~hyperopt_tpu.slo.FlightRecorder` (deterministic clock),
   each proving its intended id fires — and ONLY its intended id —
   and that the breach dumped a parseable flight-recorder bundle
   containing the breaching trace ids.
3. **recorder round-trip** — every fixture bundle re-read through
   ``slo.validate_bundle`` (manifest first, end count matches, zero
   torn lines).
4. **overhead** — suggest p50 with the guardrails fully on (store
   instrumentation + recorder retention + engine ticker) vs fully off
   (``slo_enabled=False``), interleaved min-of-pairs, gate < 5%.

Usage::

    JAX_PLATFORMS=cpu python scripts/slo_report.py [--quick] [--out SLO_SERVE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))
if _SCRIPTS_DIR not in sys.path:
    sys.path.insert(0, _SCRIPTS_DIR)

OVERHEAD_GATE = 0.05
RULE_IDS = ("SL601", "SL602", "SL603", "SL604", "SL605", "SL606")


# ---------------------------------------------------------------------
# section 1+4 helpers: the loadgen campaigns
# ---------------------------------------------------------------------


def _loadgen(n_studies, n_trials, seed, slo_gate=False, root=None,
             collect=None, service_kwargs=None):
    # re-ensure at CALL time: bench.py's _import_script pops the
    # scripts dir from sys.path right after importing this module
    if _SCRIPTS_DIR not in sys.path:
        sys.path.insert(0, _SCRIPTS_DIR)
    import serve_loadgen

    return serve_loadgen.run_loadgen(
        n_studies=n_studies, n_trials=n_trials, seed=seed,
        root=root, slo_gate=slo_gate, on_service=collect,
        service_kwargs=service_kwargs,
    )


def healthy_section(n_studies, n_trials, seed):
    """The SLO-gated campaign + storage reconciliation."""
    grabbed = {}

    def collect(service):
        grabbed["store"] = service.store_stats.summary()
        grabbed["stats"] = service.stats.summary()
        grabbed["recorder"] = service.flight_recorder.summary()

    with tempfile.TemporaryDirectory(prefix="hyperopt-slo-") as root:
        bench = _loadgen(
            n_studies, n_trials, seed, slo_gate=True, root=root,
            collect=collect,
        )
    store = grabbed["store"]
    total_trials = n_studies * n_trials
    # the reconciliation table: every fsync/doc-write/scan on the
    # loadgen path accounted against trial counts.  The run is
    # hermetic (no transport faults, no chaos), so these are EXACT.
    expected = {
        # segmented store (the default backend): NO per-doc writes and
        # NO O(N) directory scans anywhere — every trial-state
        # transition is one segment append (one record each on this
        # unbatched path: insert per suggest + result write per report)
        "doc_writes": 0,
        "scans": 0,
        "segment_appends": 2 * total_trials,
        "segment_records": 2 * total_trials,
        # one journaled response per keyed mutation:
        # create(1/study) + suggest(1/trial) + report(1/trial)
        "journal_appends": n_studies + 2 * total_trials,
        # derived Trials-view recomputes: one per insert + one per
        # report, all local
        "refresh_local": 2 * total_trials,
        "refresh_full": n_studies,
        # fsync ledger per kind
        "fsync_doc": 0,
        # manifest publish per study create + one per segment append
        "fsync_segment": n_studies + 2 * total_trials,
        "fsync_journal": n_studies + 2 * total_trials,
        "fsync_counter": total_trials,          # one id draw per suggest
        # config blob per create + seed-cursor per suggest commit
        "fsync_attachment": n_studies + total_trials,
    }
    observed = {
        "doc_writes": store["doc_writes"],
        "scans": store["scans"],
        "segment_appends": store["segment_appends"],
        "segment_records": store["segment_records"],
        "journal_appends": store["journal_appends"],
        "refresh_local": store["refresh_local"],
        "refresh_full": store["refresh_full"],
        "fsync_doc": store["fsyncs"].get("doc", 0),
        "fsync_segment": store["fsyncs"].get("segment", 0),
        "fsync_journal": store["fsyncs"].get("journal", 0),
        "fsync_counter": store["fsyncs"].get("counter", 0),
        "fsync_attachment": store["fsyncs"].get("attachment", 0),
    }
    mismatches = {
        k: {"expected": expected[k], "observed": observed[k]}
        for k in expected if expected[k] != observed[k]
    }
    rules = bench.get("slo") or []
    warm_cold_ok = (
        bench["n_warm_suggests"] + bench["n_cold_suggests"]
        == total_trials
        and bench["n_warm_suggests"] > bench["n_cold_suggests"]
    )
    section = {
        "ok": bool(
            bench["ok"]
            and rules
            and all(r["status"] != "breach" for r in rules)
            and {r["rule"] for r in rules} == set(RULE_IDS)
            and not mismatches
            and warm_cold_ok
        ),
        "bench_ok": bench["ok"],
        "rules": rules,
        "suggest_p50_ms": bench["suggest_p50_ms"],
        "suggest_p99_ms": bench["suggest_p99_ms"],
        "warm_cold_split": {
            "warm_p50_ms": bench["suggest_warm_p50_ms"],
            "warm_p99_ms": bench["suggest_warm_p99_ms"],
            "cold_p50_ms": bench["suggest_cold_p50_ms"],
            "cold_p99_ms": bench["suggest_cold_p99_ms"],
            "n_warm": bench["n_warm_suggests"],
            "n_cold": bench["n_cold_suggests"],
            "ok": warm_cold_ok,
        },
        "store": store,
        "reconciliation": {
            "ok": not mismatches,
            "expected": expected,
            "observed": observed,
            "mismatches": mismatches,
        },
        "fsync_p99_ms": store["fsync_p99_ms"],
        "refresh_local_hit_rate": store["refresh_local_hit_rate"],
    }
    return section, bench


# ---------------------------------------------------------------------
# section 2: forced-breach fixtures (one per rule)
# ---------------------------------------------------------------------


def _fixture_env(bundle_dir):
    """Fresh stats + recorder + deterministic-clock engine for one
    fixture.  Returns (env dict)."""
    from hyperopt_tpu import slo
    from hyperopt_tpu.observability import (
        DeviceStats,
        ServiceStats,
        StoreStats,
    )

    clock = {"t": 0.0}
    service_stats = ServiceStats()
    device_stats = DeviceStats()
    store_stats = StoreStats()
    recorder = slo.FlightRecorder(bundle_dir=bundle_dir)
    recorder.set_provider("dispatch", device_stats.recent_records)
    recorder.set_provider("store_op", store_stats.recent_ops)
    engine = slo.SloEngine(
        service_stats=service_stats,
        device_stats=device_stats,
        store_stats=store_stats,
        recorder=recorder,
        time_fn=lambda: clock["t"],
        snapshot_interval=1.0,
    )
    return {
        "clock": clock, "service": service_stats, "device": device_stats,
        "store": store_stats, "recorder": recorder, "engine": engine,
    }


def _seed_baseline(env, warm_latency=0.02, device=True):
    """Healthy background traffic so non-target rules have data and
    read OK (a fixture must prove its rule fires ALONE — breaching
    must equal exactly the intended id).  ``warm_latency`` lets a
    fixture shape its healthy traffic (SL602 needs a slow-but-uniform
    baseline so the ratio rule stays quiet); ``device=False`` leaves
    the device plane to the injection (SL604)."""
    for _ in range(40):
        env["service"].record_request(
            "suggest", seconds=warm_latency, study="s"
        )
        env["store"].record_fsync(0.001, kind="journal", nbytes=128)
    if device:
        # enough busy time that duty stays over the floor across the
        # fixture's whole 110 s window (10 x 1 s over 110 s ≈ 0.09)
        for _ in range(10):
            env["device"].record_dispatch({
                "sig": "fx", "device_s": 1.0, "n_requests": 1,
                "binding_ceiling": "hbm_bw", "roofline_pct": 10.0,
                "hbm_bytes": 1e6, "flops": 1e6, "live_bytes": 1024,
                "compiled": False,
            })


# per-rule injection: drive EXACTLY the degenerate signal the rule
# watches, leaving every other objective healthy
def _inject_sl601(env):
    # bimodal steady-state latency: tiny p50, 45 ms p99 → ratio ~50x
    # over the 25x objective, while nothing crosses the 2.5 s SL602 bound
    for _ in range(90):
        env["service"].record_request("suggest", seconds=0.0008, study="s")
    for _ in range(10):
        env["service"].record_request("suggest", seconds=0.045, study="s")


def _inject_sl602(env):
    # uniformly slow steady state: half the suggests over the 2.5 s
    # bound against a 0.9 s baseline — p99/p50 ≈ 5x keeps SL601 quiet
    for _ in range(40):
        env["service"].record_request("suggest", seconds=5.0, study="s")


def _inject_sl603(env):
    # a backpressure storm: as many 429s as served requests
    for _ in range(40):
        env["service"].record_rejection("suggest")


def _inject_sl604(env):
    # dispatches flowing while the device sits idle: 10 more dispatches
    # carrying ~zero busy time over a 100 s window → duty ≈ 0.008
    for _ in range(10):
        env["device"].record_dispatch({
            "sig": "fx", "device_s": 0.0001, "n_requests": 1,
            "binding_ceiling": "hbm_bw", "roofline_pct": 0.1,
            "hbm_bytes": 1e3, "flops": 1e3, "live_bytes": 64,
            "compiled": False,
        })


def _inject_sl605(env):
    # crash damage on the storage plane: torn journal lines observed
    env["store"].record_journal_torn(2)
    env["store"].record_quarantine(1)


def _inject_sl606(env):
    # an NFS mount gone slow: every fsync takes 1 s (bound 0.25 s)
    for _ in range(40):
        env["store"].record_fsync(1.0, kind="doc", nbytes=4096)


FIXTURES = (
    ("SL601", "latency_ratio_breach", _inject_sl601, {}),
    ("SL602", "latency_absolute_breach", _inject_sl602,
     {"warm_latency": 0.9}),
    ("SL603", "backpressure_storm", _inject_sl603, {}),
    ("SL604", "idle_device_under_load", _inject_sl604,
     {"device": False}),
    ("SL605", "torn_store", _inject_sl605, {}),
    ("SL606", "slow_fsync", _inject_sl606, {}),
)


def run_fixture(rule_id, name, inject, bundle_dir, baseline_kwargs=None):
    """One forced breach: healthy baseline, the injection, a tick —
    asserts the intended rule (and only it) transitions to breach and
    the dump round-trips with the breaching trace ids."""
    from hyperopt_tpu import slo

    env = _fixture_env(bundle_dir)
    # traces the recorder must carry into the bundle: the "requests
    # that paid" — ids are deterministic per fixture
    trace_ids = [f"{rule_id.lower()}-victim-{i}" for i in range(3)]
    for tid in trace_ids:
        env["recorder"].record_trace({
            "trace_id": tid, "root": "service.suggest",
            "duration_s": 5.0, "spans": [],
        })
    _seed_baseline(env, **(baseline_kwargs or {}))
    env["clock"]["t"] = 10.0
    env["engine"].tick()  # healthy snapshot: nothing breaching
    pre_breaching = env["engine"].current_breaching()
    inject(env)
    env["clock"]["t"] = 110.0
    env["engine"].tick()
    breaching = env["engine"].current_breaching()
    bundle_path = env["recorder"].summary()["last_bundle"]
    bundle = (
        slo.validate_bundle(bundle_path) if bundle_path else
        {"ok": False, "trace_ids": []}
    )
    traces_present = all(t in bundle.get("trace_ids", []) for t in trace_ids)
    ok = (
        pre_breaching == []
        and breaching == [rule_id]
        and bundle["ok"]
        and traces_present
        and rule_id in str(bundle.get("reason"))
    )
    return {
        "intended_rule": rule_id,
        "name": name,
        "ok": bool(ok),
        "pre_breaching": pre_breaching,
        "breaching": breaching,
        "rule": breaching[0] if len(breaching) == 1 else None,
        "bundle": {
            "path": os.path.basename(bundle_path) if bundle_path else None,
            "ok": bundle["ok"],
            "reason": bundle.get("reason"),
            "n_records": bundle.get("n_records"),
            "kinds": bundle.get("kinds"),
            "breaching_trace_ids_present": traces_present,
        },
    }


# ---------------------------------------------------------------------
# section 4: overhead A/B
# ---------------------------------------------------------------------


def overhead_section(n_studies, n_trials, seed, pairs=2):
    """Suggest p50 with the guardrails fully ON (store instrumentation
    + recorder retention + engine ticker at 1 s — a cadence 5x the
    default, so the measurement leans against us) vs fully OFF.
    Interleaved pairs, min-of-runs (host jitter only ever adds)."""
    on_p50s, off_p50s = [], []
    for _ in range(pairs):
        with tempfile.TemporaryDirectory(prefix="hyperopt-slo-on-") as r:
            on = _loadgen(
                n_studies, n_trials, seed, root=r,
                service_kwargs={"slo_tick": 1.0},
            )
        on_p50s.append(on["suggest_p50_exact_ms"])
        with tempfile.TemporaryDirectory(prefix="hyperopt-slo-off-") as r:
            off = _loadgen(
                n_studies, n_trials, seed, root=r,
                service_kwargs={"slo_enabled": False},
            )
        off_p50s.append(off["suggest_p50_exact_ms"])
    p50_on, p50_off = min(on_p50s), min(off_p50s)
    frac = (p50_on / p50_off - 1.0) if p50_off else None
    return {
        "ok": frac is not None and frac < OVERHEAD_GATE,
        "p50_guardrails_on_ms": p50_on,
        "p50_guardrails_off_ms": p50_off,
        "p50_on_runs_ms": on_p50s,
        "p50_off_runs_ms": off_p50s,
        "p50_regression_frac": round(frac, 4) if frac is not None else None,
        "gate_frac": OVERHEAD_GATE,
    }


# ---------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------


def run_report(quick=False, seed=0, overhead=True):
    import jax

    n_studies = 8
    n_trials = 6 if quick else 20
    t0 = time.time()
    healthy, _bench = healthy_section(n_studies, n_trials, seed)
    fixtures = {}
    with tempfile.TemporaryDirectory(prefix="hyperopt-slo-fix-") as fd:
        for rule_id, name, inject, baseline_kwargs in FIXTURES:
            fixtures[name] = run_fixture(
                rule_id, name, inject, os.path.join(fd, rule_id),
                baseline_kwargs=baseline_kwargs,
            )
    roundtrip_ok = all(f["bundle"]["ok"] for f in fixtures.values())
    over = None
    if overhead:
        over = overhead_section(
            n_studies, n_trials, seed, pairs=1 if quick else 2
        )
    ok = (
        healthy["ok"]
        and all(f["ok"] for f in fixtures.values())
        and roundtrip_ok
        and (over is None or over["ok"])
    )
    return {
        "metric": "slo_serve",
        "ok": bool(ok),
        "quick": bool(quick),
        "platform": jax.devices()[0].platform,
        "n_studies": n_studies,
        "n_trials_per_study": n_trials,
        "seed": seed,
        "healthy": healthy,
        "fixtures": fixtures,
        "recorder_roundtrip": {"ok": roundtrip_ok},
        "overhead": over,
        "elapsed_s": round(time.time() - t0, 2),
    }


def write_report(report, out_path):
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-overhead", action="store_true",
                    dest="no_overhead")
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "SLO_SERVE.json",
        ),
    )
    options = ap.parse_args(argv)
    report = run_report(
        quick=options.quick, seed=options.seed,
        overhead=not options.no_overhead,
    )
    print(json.dumps({
        "metric": report["metric"], "ok": report["ok"],
        "healthy_ok": report["healthy"]["ok"],
        "fixtures_ok": {
            k: v["ok"] for k, v in report["fixtures"].items()
        },
        "overhead": (
            report["overhead"]["p50_regression_frac"]
            if report["overhead"] else None
        ),
        "elapsed_s": report["elapsed_s"],
    }, indent=1))
    if options.out:
        write_report(report, options.out)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
