"""Store-plane A/B: the per-doc layout vs the segmented trial log.

The PR 16 acceptance artifact (``BENCH_STORE.json``): for each scale,
drive the SAME trial lifecycle — B-sized insert batches, then a result
transition per trial — through both backends with a fresh
:class:`~hyperopt_tpu.observability.StoreStats` installed, and report
the counter evidence:

- **fsyncs per state transition** — the group-commit win.  The per-doc
  layout pays one ``fsync`` per transition (atomic tmp+replace per
  doc); the segment log folds a B-record batch into ONE ``O_APPEND``
  write + ONE ``fsync``.  The headline gate is the ratio ``doc /
  segment >= 10`` at every scale.
- **refresh ∝ delta** — after the store is loaded, appending a small
  delta and refreshing a warm reader replays exactly the delta's
  records (``segment_replay_records`` == delta), with zero O(N)
  directory scans on the segmented path.
- **recovery = replay** — a cold open replays the full log
  (``replayed records == total records``), and compaction folds the
  2-records-per-trial history down to one latest doc per tid.

Every committed guard is a RATIO or COUNT — never absolute
milliseconds (sandbox wall-clock swings ~30x between sessions).
Wall-clock fields are informational only.

Usage::

    python scripts/store_bench.py [--quick] [--out BENCH_STORE.json]
    python bench.py --store [--quick]     # the bench.py section
"""

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

BATCH = 64
FULL_SCALES = (10_000, 100_000)
QUICK_SCALES = (2_000,)


def _doc(tid):
    return {
        "tid": tid, "state": 0, "spec": None,
        "result": {"status": "new"},
        "misc": {"tid": tid, "cmd": None, "idxs": {"x": [tid]},
                 "vals": {"x": [0.5]}},
        "exp_key": None, "owner": None, "version": 0,
        "book_time": None, "refresh_time": None,
    }


def _fresh_stats():
    from hyperopt_tpu.observability import StoreStats
    from hyperopt_tpu.parallel import file_trials

    stats = StoreStats()
    file_trials.set_store_stats(stats)
    return stats


def _store_fsyncs(summary) -> int:
    """fsyncs attributable to trial-state durability (doc + segment),
    excluding counter/attachment/journal traffic both arms share."""
    fsyncs = summary["fsyncs"]
    return fsyncs.get("doc", 0) + fsyncs.get("segment", 0)


def bench_backend(root, backend, n_trials, batch=BATCH) -> dict:
    """One arm: insert ``n_trials`` in ``batch``-sized groups, then a
    result transition per trial (also batched through the group-commit
    path on the segmented backend), then delta refresh, cold-open
    recovery, and (segmented) compaction — all counter-measured."""
    from hyperopt_tpu.parallel.file_trials import FileJobs, FileTrials

    qdir = os.path.join(root, f"{backend}-{n_trials}")
    row = {"backend": backend, "n_trials": n_trials, "batch": batch,
           "transitions": 2 * n_trials}

    # -- write path: create + complete every trial ---------------------
    stats = _fresh_stats()
    t0 = time.time()
    jobs = FileJobs(qdir, backend=backend)
    for base in range(0, n_trials, batch):
        docs = [_doc(t) for t in range(base, min(base + batch, n_trials))]
        jobs.insert_many(docs)
    for base in range(0, n_trials, batch):
        done = []
        for t in range(base, min(base + batch, n_trials)):
            d = _doc(t)
            d["state"] = 2
            d["result"] = {"status": "ok", "loss": float(t)}
            done.append(d)
        if jobs.segments is not None:
            jobs.segments.append_many(done)
        else:
            for d in done:
                jobs.write(d)
    write_s = time.time() - t0
    s = stats.summary()
    fsyncs = _store_fsyncs(s)
    row["write"] = {
        "elapsed_s_informational": round(write_s, 3),
        "fsyncs_store": fsyncs,
        "fsyncs_per_transition": round(fsyncs / (2 * n_trials), 6),
        "doc_writes": s["doc_writes"],
        "segment_appends": s["segment_appends"],
        "segment_records": s["segment_records"],
        "scans": s["scans"],
    }

    # -- recovery = replay-in-order on a cold open ---------------------
    stats = _fresh_stats()
    reader = FileTrials(qdir, backend=backend)
    reader.refresh()
    cold = stats.summary()
    row["cold_open"] = {
        "replayed_records": cold["segment_replay_records"],
        "full_replays": cold["segment_replays_full"],
        "scans": cold["scans"],
        "scan_entries": cold["scan_entries"],
        "n_docs_recovered": len(reader._dynamic_trials),
    }

    # -- refresh ∝ delta: the warm reader pays only the tail a SIBLING
    # writer appended (its own inserts never need replay) --------------
    stats = _fresh_stats()
    delta = [_doc(n_trials + i) for i in range(batch)]
    jobs.insert_many(delta)
    reader.refresh()
    warm = stats.summary()
    row["delta_refresh"] = {
        "delta_docs": len(delta),
        "replayed_records": warm["segment_replay_records"],
        "full_replays": warm["segment_replays_full"],
        "scans": warm["scans"],
        "scan_entries": warm["scan_entries"],
    }

    # -- compaction: 2 records/trial fold to latest-per-tid ------------
    if jobs.segments is not None:
        stats = _fresh_stats()
        segs = jobs.segments
        # one record per append: n inserts + n results + the delta batch
        records_before = 2 * n_trials + batch
        t0 = time.time()
        segs.seal_active()
        segs.compact()
        s = stats.summary()
        stats2 = _fresh_stats()
        reopened = FileJobs(qdir, backend=backend)
        n_after = len(reopened.all_docs())
        after = stats2.summary()
        row["compaction"] = {
            "elapsed_s_informational": round(time.time() - t0, 3),
            "records_before": records_before,
            "replay_records_after": after["segment_replay_records"],
            "n_docs_after": n_after,
            "segments_retired": s["segments_retired"],
        }
    return row


def run_campaign(quick=False) -> dict:
    os.environ.setdefault("HYPEROPT_TPU_STORE_BACKEND", "segment")
    scales = QUICK_SCALES if quick else FULL_SCALES
    report = {
        "campaign": "store_bench",
        "quick": bool(quick),
        "batch": BATCH,
        "scales": list(scales),
        "rows": [],
        "headline": {"fsync_ratio_doc_over_segment": {}},
        "errors": [],
    }
    with tempfile.TemporaryDirectory(prefix="store-bench-") as root:
        for n in scales:
            by_backend = {}
            for backend in ("doc", "segment"):
                row = bench_backend(root, backend, n)
                report["rows"].append(row)
                by_backend[backend] = row
            doc_f = by_backend["doc"]["write"]["fsyncs_per_transition"]
            seg_f = by_backend["segment"]["write"][
                "fsyncs_per_transition"
            ]
            ratio = round(doc_f / seg_f, 2) if seg_f else None
            report["headline"]["fsync_ratio_doc_over_segment"][
                str(n)
            ] = ratio
            if ratio is None or ratio < 10.0:
                report["errors"].append(
                    f"fsync ratio at n={n} is {ratio} (< 10x)"
                )
            seg = by_backend["segment"]
            if seg["write"]["scans"] != 0:
                report["errors"].append(
                    f"segmented write path did {seg['write']['scans']} "
                    f"O(N) scans at n={n}"
                )
            if seg["delta_refresh"]["scans"] != 0:
                report["errors"].append(
                    f"segmented delta refresh scanned at n={n}"
                )
            if seg["delta_refresh"]["full_replays"] != 0:
                report["errors"].append(
                    f"segmented delta refresh fell back to a full "
                    f"replay at n={n}"
                )
            if (seg["delta_refresh"]["replayed_records"]
                    != seg["delta_refresh"]["delta_docs"]):
                report["errors"].append(
                    f"delta refresh replayed "
                    f"{seg['delta_refresh']['replayed_records']} records "
                    f"for a {seg['delta_refresh']['delta_docs']}-doc "
                    f"delta at n={n}"
                )
            if seg["cold_open"]["n_docs_recovered"] != n:
                report["errors"].append(
                    f"cold open recovered "
                    f"{seg['cold_open']['n_docs_recovered']}/{n} docs"
                )
            if seg["cold_open"]["replayed_records"] != 2 * n:
                report["errors"].append(
                    f"cold open replayed "
                    f"{seg['cold_open']['replayed_records']} records, "
                    f"expected the full {2 * n}-record log"
                )
            comp = seg.get("compaction", {})
            if comp and comp["n_docs_after"] != n + BATCH:
                report["errors"].append(
                    f"compaction lost docs at n={n}: "
                    f"{comp['n_docs_after']} != {n + BATCH}"
                )
    report["ok"] = not report["errors"]
    return report


def write_report(report, path):
    with open(path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    out = args.out or (
        "BENCH_STORE.quick.json" if args.quick else "BENCH_STORE.json"
    )
    report = run_campaign(quick=args.quick)
    write_report(report, out)
    print(json.dumps({
        "campaign": report["campaign"],
        "ok": report["ok"],
        "fsync_ratio_doc_over_segment":
            report["headline"]["fsync_ratio_doc_over_segment"],
        "errors": report["errors"],
        "artifact": out,
    }))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
