"""Seeded chaos campaign: inject faults, prove recovery, emit a report.

The ISSUE-3 acceptance run: under a seeded chaos schedule (worker kills,
torn locks, delayed/duplicated results, objective errors and hangs,
synthetic device failures) a CPU ``fmin`` run must **complete**, with
**zero stranded reservations**, **every injected fault accounted for**
in ``FaultStats``, and the **best trial equal to the fault-free run's
best** on the same seed.  This script runs that campaign in two phases
and writes a JSON report of injected faults vs. recoveries:

- **queue phase** — a FileTrials queue with restartable in-process
  worker threads (a killed worker respawns, like a supervised process)
  under ``rand.suggest``: exercises the lease/reaper/retry planes.
  Suggestions don't read results, so the chaos run's parameter stream is
  identical to the fault-free run's and best-trial equality is exact.
- **device phase** — a serial in-process ``fmin`` under ``tpe.suggest``
  with synthetic device errors injected at suggest dispatch: exercises
  the DeviceRecovery re-init plane and the speculative engine's
  seed-transparent re-issue (failed launches park their (ids, seed) for
  the synchronous recompute, so the recovered trajectory equals the
  fault-free one trial-for-trial).

Usage::

    python scripts/chaos_campaign.py [--trials 100] [--seed 0]
        [--workers 3] [--quick] [--out chaos_report.json]

Exit code 0 iff every phase completed, reconciled its fault accounting,
and matched its fault-free twin.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _flush_chaos_modules():
    """Ensure chaos hooks see a clean slate (idempotent)."""
    from hyperopt_tpu.resilience import chaos

    assert chaos.get_active() is None, "campaign started with chaos active"


# Module-level objective: FileTrials pickles the Domain by reference, so
# worker threads must be able to re-import this function — a closure
# wrapped by the monkey would not unpickle.  It consults the
# process-wide active monkey itself instead.
def campaign_objective(cfg):
    from hyperopt_tpu.resilience import chaos

    monkey = chaos.get_active()
    if monkey is not None:
        fault = monkey.objective_fault(chaos.stable_key(cfg))
        if fault is not None:
            return fault  # an injected NaN loss
    x = cfg["x"]
    y = cfg.get("y", 0.0)
    return (x - 3.0) ** 2 + 0.1 * (y + 1.0) ** 2


def _space():
    from hyperopt_tpu import hp

    return {
        "x": hp.uniform("x", -5.0, 5.0),
        "y": hp.normal("y", 0.0, 2.0),
    }


def _best(trials):
    """(tid, loss, vals) of the best OK trial."""
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

    best = None
    for t in trials.trials:
        if t["state"] != JOB_STATE_DONE:
            continue
        r = t["result"]
        loss = r.get("loss")
        if r.get("status") != STATUS_OK or loss is None or loss != loss:
            continue
        if best is None or loss < best[1]:
            best = (t["tid"], float(loss), t["misc"]["vals"])
    return best


# ---------------------------------------------------------------------
# queue phase
# ---------------------------------------------------------------------

def _run_queue_fmin(qdir, n_trials, seed, n_workers, lease_ttl, policy,
                    stats, kill_counter=None):
    """One FileTrials fmin with restartable worker threads; returns
    (best, trials)."""
    from hyperopt_tpu import fmin
    from hyperopt_tpu.algos import rand
    from hyperopt_tpu.parallel.file_trials import FileTrials
    from hyperopt_tpu.parallel.worker import FileWorker, ReserveTimeout
    from hyperopt_tpu.resilience.chaos import WorkerKilled

    trials = FileTrials(qdir, lease_ttl=lease_ttl)
    stop = threading.Event()

    def supervise(slot):
        # a supervised worker slot: the worker "process" dies on
        # WorkerKilled and a fresh one respawns in its place
        while not stop.is_set():
            worker = FileWorker(
                qdir, poll_interval=0.02, lease_ttl=lease_ttl, stats=stats
            )
            try:
                while not stop.is_set():
                    try:
                        worker.run_one(reserve_timeout=0.3)
                    except ReserveTimeout:
                        continue
            except WorkerKilled:
                if kill_counter is not None:
                    kill_counter.append(slot)
                continue  # respawn
            except Exception:
                time.sleep(0.05)  # queue hiccup; keep the slot alive

    threads = [
        threading.Thread(target=supervise, args=(i,), daemon=True)
        for i in range(n_workers)
    ]
    for t in threads:
        t.start()
    try:
        fmin(
            campaign_objective,
            _space(),
            algo=rand.suggest,
            max_evals=n_trials,
            trials=trials,
            rstate=np.random.default_rng(seed),
            retry_policy=policy,
            fault_stats=stats,
            show_progressbar=False,
            verbose=False,
        )
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
    trials.refresh()
    return _best(trials), trials


def run_queue_phase(n_trials, seed, n_workers, chaos_cfg):
    from hyperopt_tpu.base import JOB_STATE_ERROR, JOB_STATE_RUNNING
    from hyperopt_tpu.observability import FaultStats
    from hyperopt_tpu.resilience import RetryPolicy
    from hyperopt_tpu.resilience.chaos import ChaosMonkey, active

    lease_ttl = 0.6
    policy = RetryPolicy(
        max_attempts=4,
        backoff_base=0.02,
        backoff_max=0.2,
        trial_timeout=0.35,
        lease_ttl=lease_ttl,
        seed=seed,
    )

    # fault-free twin first (same seed, chaos off)
    ff_dir = tempfile.mkdtemp(prefix="chaos_ff_")
    try:
        ff_stats = FaultStats()
        ff_best, _ = _run_queue_fmin(
            ff_dir, n_trials, seed, n_workers, lease_ttl, policy, ff_stats
        )
    finally:
        shutil.rmtree(ff_dir, ignore_errors=True)

    # chaos run
    ch_dir = tempfile.mkdtemp(prefix="chaos_run_")
    t0 = time.time()
    try:
        stats = FaultStats()
        monkey = ChaosMonkey(chaos_cfg, stats=stats)
        kills = []
        with active(monkey):
            best, trials = _run_queue_fmin(
                ch_dir, n_trials, seed, n_workers, lease_ttl, policy,
                stats, kill_counter=kills,
            )
        jobs = trials.jobs
        stranded_running = sum(
            1 for d in jobs.all_docs() if d["state"] == JOB_STATE_RUNNING
        )
        stranded_locks = len(jobs.locked_tids())
        quarantined = sum(
            1 for d in jobs.all_docs() if d["state"] == JOB_STATE_ERROR
        )
    finally:
        shutil.rmtree(ch_dir, ignore_errors=True)

    counts = stats.summary()
    injected = stats.injected()
    # accounting invariants: every fault class reconciles with a
    # recovery counter (completion itself proves the rest — fmin's
    # block_until_done cannot return with an unrecovered trial)
    reconciliation = {
        # every kill leaves a RUNNING doc whose lease must expire and be
        # reclaimed (or quarantined) for the run to have completed
        "kills_reclaimed": (
            counts.get("lease_reclaimed", 0)
            + counts.get("lease_quarantined", 0)
            >= injected.get("worker_kill", 0)
        ),
        # every torn lock blocks its NEW trial until the reaper GC'd it
        "torn_locks_cleared": (
            counts.get("stale_lock_cleared", 0)
            >= injected.get("torn_lock", 0)
        ),
        # objective errors/hangs surface as retry-policy failures
        "objective_faults_retried": (
            counts.get("trial_failure", 0)
            + counts.get("stale_result_dropped", 0)
            >= injected.get("objective_error", 0)
        ),
        # a delayed (frozen-worker) result past the TTL must be dropped
        # by the ownership/expiry re-check, never written over the retry
        "delayed_results_dropped": (
            counts.get("stale_result_dropped", 0)
            >= injected.get("result_delay", 0)
        ),
        "zero_stranded": stranded_running == 0 and stranded_locks == 0,
    }
    best_match = (
        best is not None
        and ff_best is not None
        and best[0] == ff_best[0]
        and abs(best[1] - ff_best[1]) < 1e-12
    )
    return {
        "phase": "queue",
        "n_trials": n_trials,
        "seed": seed,
        "n_workers": n_workers,
        "elapsed_s": round(time.time() - t0, 2),
        "injected": injected,
        "counters": counts,
        "worker_respawns": len(kills),
        "quarantined": quarantined,
        "stranded_running": stranded_running,
        "stranded_locks": stranded_locks,
        "best": {"tid": best[0], "loss": best[1]} if best else None,
        "fault_free_best": (
            {"tid": ff_best[0], "loss": ff_best[1]} if ff_best else None
        ),
        "best_matches_fault_free": best_match,
        "reconciliation": reconciliation,
        "ok": best_match and all(reconciliation.values()),
    }


# ---------------------------------------------------------------------
# device phase
# ---------------------------------------------------------------------

def _run_device_fmin(n_trials, seed, policy, stats):
    from hyperopt_tpu import Trials, fmin
    from hyperopt_tpu.algos import tpe

    trials = Trials()
    fmin(
        campaign_objective,
        _space(),
        algo=tpe.suggest,
        max_evals=n_trials,
        trials=trials,
        rstate=np.random.default_rng(seed),
        retry_policy=policy,
        fault_stats=stats,
        show_progressbar=False,
        verbose=False,
    )
    return _best(trials), trials


def run_device_phase(n_trials, seed, chaos_cfg):
    from hyperopt_tpu.observability import FaultStats
    from hyperopt_tpu.resilience import RetryPolicy
    from hyperopt_tpu.resilience.chaos import ChaosConfig, ChaosMonkey, active

    policy = RetryPolicy(
        max_attempts=4, backoff_base=0.01, backoff_max=0.1, seed=seed
    )

    ff_stats = FaultStats()
    ff_best, ff_trials = _run_device_fmin(n_trials, seed, policy, ff_stats)

    # device-plane chaos only: suggest-dispatch faults + objective errors
    dev_cfg = ChaosConfig(
        seed=chaos_cfg.seed,
        p_device_error=chaos_cfg.p_device_error,
        p_objective_error=chaos_cfg.p_objective_error,
    )
    t0 = time.time()
    stats = FaultStats()
    monkey = ChaosMonkey(dev_cfg, stats=stats)
    with active(monkey):
        best, trials = _run_device_fmin(n_trials, seed, policy, stats)

    counts = stats.summary()
    injected = stats.injected()
    # trajectory identity: the recovered run's parameter stream equals
    # the fault-free run's trial-for-trial (seed-transparent re-issue)
    vals_equal = len(trials.trials) == len(ff_trials.trials) and all(
        a["misc"]["vals"] == b["misc"]["vals"]
        for a, b in zip(trials.trials, ff_trials.trials)
    )
    best_match = (
        best is not None
        and ff_best is not None
        and best[0] == ff_best[0]
        and abs(best[1] - ff_best[1]) < 1e-12
    )
    reconciliation = {
        # every injected device fault was observed by the recovery layer
        # (counted at absorb/run) and answered with a re-init or CPU
        # fallback while the budget lasted
        "device_faults_recovered": (
            counts.get("device_error", 0)
            >= injected.get("device_error", 0)
            and counts.get("device_reinit", 0)
            + counts.get("cpu_fallback", 0)
            >= min(injected.get("device_error", 0), 1)
        ),
        "objective_faults_retried": (
            counts.get("trial_failure", 0)
            >= injected.get("objective_error", 0)
        ),
    }
    return {
        "phase": "device",
        "n_trials": n_trials,
        "seed": seed,
        "elapsed_s": round(time.time() - t0, 2),
        "injected": injected,
        "counters": counts,
        "trajectory_matches_fault_free": vals_equal,
        "best": {"tid": best[0], "loss": best[1]} if best else None,
        "fault_free_best": (
            {"tid": ff_best[0], "loss": ff_best[1]} if ff_best else None
        ),
        "best_matches_fault_free": best_match,
        "reconciliation": reconciliation,
        "ok": best_match and vals_equal and all(reconciliation.values()),
    }


# ---------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------

def run_campaign(n_trials=100, seed=0, n_workers=3, quick=False,
                 device_trials=None):
    from hyperopt_tpu.resilience.chaos import ChaosConfig

    _flush_chaos_modules()
    if quick:
        n_trials = min(n_trials, 30)
        n_workers = min(n_workers, 2)
    if device_trials is None:
        # must clear TPE's n_startup_jobs=20 so device programs dispatch
        device_trials = 30 if quick else 40

    cfg = ChaosConfig(
        seed=seed,
        p_worker_kill=0.06,
        p_torn_lock=0.05,
        p_result_delay=0.03,
        p_result_duplicate=0.05,
        p_objective_error=0.06,
        p_objective_hang=0.02,
        hang_seconds=0.8,  # > trial_timeout: observable as a timeout
        delay_seconds=1.0,  # > lease_ttl: observable as a stale result
        p_device_error=0.15,
    )
    report = {
        "campaign": "chaos",
        "seed": seed,
        "config": {
            k: getattr(cfg, k) for k in cfg.__dataclass_fields__
        },
        "phases": [
            run_queue_phase(n_trials, seed, n_workers, cfg),
            run_device_phase(device_trials, seed, cfg),
        ],
    }
    report["ok"] = all(p["ok"] for p in report["phases"])
    report["total_injected"] = sum(
        sum(p["injected"].values()) for p in report["phases"]
    )
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trials", type=int, default=100)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    report = run_campaign(
        n_trials=args.trials,
        seed=args.seed,
        n_workers=args.workers,
        quick=args.quick,
    )
    print(json.dumps(report, indent=1, default=str))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, default=str)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
