"""Extended out-of-suite fuzz campaign over the space fuzzers.

The committed suite runs each fuzzer over a handful of seeds (bounded CI
time); this script loops the same four properties over hundreds of
FRESH seeds — compiled-vs-interpreted sampler agreement, fmin
end-to-end survival on arbitrary generated spaces, mesh-vs-device
TPE agreement, and durable-queue concurrency invariants (random worker
counts/latencies/failure rates; exactly-once, no lost docs).  A failure
of the first three properties is a real bug with a deterministically
reproducing seed; the queue property races real worker threads, so its
seed fixes the workload but not the interleaving — treat a queue
failure as a real finding to chase with the logs it printed, even if
the seed passes on replay.

Run (virtual CPU mesh, like the suite):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/fuzz_campaign.py [N_SEEDS] [SEED_BASE]
"""

import os
import signal
import sys
import time
import traceback

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
))

N = int(sys.argv[1]) if len(sys.argv) > 1 else 200
BASE = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000


def main():
    import jax

    # the axon sitecustomize clobbers JAX_PLATFORMS in every process
    # (see tests/conftest.py); update the config back before any op
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    assert jax.devices()[0].platform == "cpu" and len(jax.devices()) >= 8

    from test_file_trials import test_fuzzed_filetrials_concurrency as t_queue
    from test_space_fuzz import (
        PERM_RESAMPLE_SKIPS,
        test_compiled_matches_interpreted_on_random_space as t_sampler,
        test_fuzzed_space_fmin_end_to_end as t_fmin,
        test_fuzzed_space_mesh_device_tpe_agree as t_mesh,
    )

    checks = [
        ("sampler", t_sampler),
        ("fmin", t_fmin),
        ("mesh", t_mesh),
        ("queue", t_queue),
    ]

    def run_with_watchdog(fn, seed, limit=600):
        """A wedged check (the queue property's primary failure mode is
        an fmin poll-loop deadlock) must surface as a recorded FAIL, not
        stall the campaign silently.  SIGALRM only interrupts the main
        thread at a bytecode boundary — enough for sleep/poll loops,
        which is exactly the deadlock shape being guarded against.

        The ``done`` flag closes the alarm's delivery race: the signal
        can arrive BETWEEN ``fn(seed)`` returning and ``signal.alarm(0)``
        disarming it, which would record a passing check as a deadlock
        FAIL.  ``done`` is set immediately after ``fn`` returns and
        ``on_alarm`` ignores a late signal when it is set (ADVICE r5).
        The flag alone still leaves the one-bytecode window between
        ``fn(seed)`` returning and the ``done = True`` store, so the
        handler grants ONE tiny grace re-arm: if the store was next in
        line it lands within the grace period and the second firing sees
        it; a genuine deadlock just raises 50 ms later."""
        done = False
        grace_used = False

        def on_alarm(signum, frame):
            nonlocal grace_used
            if done:
                return  # fn already returned; late delivery, not a hang
            if not grace_used:
                grace_used = True
                signal.setitimer(signal.ITIMER_REAL, 0.05)
                return
            raise TimeoutError(f"check exceeded {limit}s (deadlock?)")

        old = signal.signal(signal.SIGALRM, on_alarm)
        signal.alarm(limit)
        try:
            fn(seed)
            done = True
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    failures = []
    t0 = time.time()
    for i in range(N):
        seed = BASE + i
        for name, fn in checks:
            try:
                run_with_watchdog(fn, seed)
            except Exception:
                failures.append((name, seed))
                print(f"FAIL {name} seed={seed}", flush=True)
                traceback.print_exc()
        # every seed compiles fresh programs (new space shapes); clear
        # the in-process executable caches so a long campaign's memory
        # stays bounded
        jax.clear_caches()
        if (i + 1) % 20 == 0:
            print(
                f"[{time.time() - t0:.0f}s] {i + 1}/{N} seeds, "
                f"{len(failures)} failures",
                flush=True,
            )
    # dropped coverage is part of the campaign record: every sampler
    # check whose scale-agreement permutation was skipped (degenerate-std
    # filter ate the resamples) would otherwise read as a full pass
    if PERM_RESAMPLE_SKIPS:
        print(
            f"coverage: {len(PERM_RESAMPLE_SKIPS)} scale-agreement "
            f"permutation check(s) SKIPPED (fewer than 100/300 resamples "
            f"survived the degenerate-std filter): "
            f"{PERM_RESAMPLE_SKIPS[:10]}",
            flush=True,
        )
    print(
        f"done: {N} seeds x {len(checks)} properties, "
        f"{len(failures)} failures {failures[:10]}, "
        f"{len(PERM_RESAMPLE_SKIPS)} permutation-coverage skips",
        flush=True,
    )
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
