"""BENCH_TPU_fused.json / BENCH_TPU_fused.quick.json generator.

The ISSUE-14 acceptance artifact for the fused Pallas mega-kernel
(sampling → scoring → top-k in one launch, ``ops/pallas_fused.py``):

- **parity**: the fused kernel against the unfused reference chain
  (``gmm_sample`` → ``pair_score`` → argmax) across the
  broken-space-adjacent shape grid — ``k_below`` edges,
  single-component mixtures, NEG_BIG padding rows, bounded/unbounded,
  log-scale, and a 100k-history tiled case — asserting BITWISE winner
  identity in the default exact-draw mode and recording the EI-diag
  deltas;
- **trajectory**: ``fmin`` with the fused tier forced vs the default
  unfused path, same seeds, asserted trial-for-trial identical;
- **recompilation**: the fused tier holds the one-trace-per-(bucket,
  family) budget over a growing-history CPU run
  (``RecompilationAuditor``);
- **tiling**: the 100k-history shape's tile decomposition on record
  (component tiles, candidate tiles, VMEM residency of the parameter
  block) — the structural proof the mega-kernel covers the shape that
  ``BENCH_TPU_100k.json`` still reports a null headline for;
- **headline** (full runs on TPU hardware only): fused vs unfused
  EI-evals/s at the 10k/100k shapes; quick/CPU runs stamp the PR 7
  null-with-reason contract instead.

Every quick-artifact guard is STRUCTURAL (bitwise-equality flags,
counts, coverage) — never absolute milliseconds (sandbox latency
swings ~30x between sessions; see tests/test_bench_artifacts.py).
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402


# (name, kb_real, ka_real, k, n_cand, log_scale, lo, hi) — the
# broken-space-adjacent grid of the ISSUE-14 test satellite; the 100k
# case uses the real 100k-history bucket size (ka = 2**17 + 1 with the
# +1 prior component) at a small candidate count so interpret mode
# stays tractable
SHAPE_GRID = [
    ("kb_edge_prior_only", 0, 40, 1, 24, False, -2.0, 2.0),
    ("kb_edge_one_obs", 1, 7, 2, 100, False, -2.0, 2.0),
    ("single_component_above", 6, 1, 1, 64, False, -2.0, 2.0),
    ("unbounded_normal", 5, 40, 2, 50, False, -np.inf, np.inf),
    ("log_scale_bounded", 25, 300, 4, 33, True, -3.0, 1.0),
    ("padding_heavy", 3, 17, 1, 24, False, -4.0, 4.0),
    ("tiled_100k", 25, 2 ** 17, 1, 256, False, -2.0, 2.0),
]


def _mk_mixture(rng, k_real, pad):
    """A mixture with ``k_real`` live components and ``pad`` NEG_BIG
    padding slots (weight exactly 0), prior-style: k_real counts the
    observation components, +1 prior is always live."""
    import jax.numpy as jnp

    n = k_real + 1 + pad  # +1: the prior component is always present
    w = rng.uniform(0.1, 1.0, n).astype(np.float32)
    if pad:
        w[-pad:] = 0.0
    w = w / w.sum()
    mu = rng.normal(0, 2, n).astype(np.float32)
    s = rng.uniform(0.3, 2.0, n).astype(np.float32)
    return jnp.asarray(w), jnp.asarray(mu), jnp.asarray(s)


def _parity_case(name, kb_real, ka_real, k, n_cand, log_scale, lo, hi,
                 seed=0, L=2, draw_in_kernel=False):
    """One shape-grid case: fused kernel vs the unfused reference chain.
    Returns the per-case record (bitwise flags, diag deltas, tiling)."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.algos.tpe_device import _ei_diag
    from hyperopt_tpu.ops import gmm as gmm_ops
    from hyperopt_tpu.ops.pallas_fused import (
        draw_param_rows,
        ei_from_partials,
        fused_suggest_pallas,
    )
    from hyperopt_tpu.ops.score import pair_params, pair_score

    rng = np.random.default_rng(seed)
    lo = np.float32(lo)
    hi = np.float32(hi)
    C = k * n_cand
    keys = jax.random.split(jax.random.PRNGKey(seed), L)
    wins_ref, cands, u1s, u2s, dps, Ps, scores = [], [], [], [], [], [], []
    for li in range(L):
        below = _mk_mixture(rng, kb_real, pad=3)
        above = _mk_mixture(rng, ka_real, pad=5)
        key = keys[li]
        cand = gmm_ops.gmm_sample(
            key, *below, lo, hi, np.float32(0.0), C, log_scale
        )
        z = jnp.log(jnp.maximum(cand, 1e-12)) if log_scale else cand
        P = pair_params(*below, *above)
        kb = below[0].shape[0]
        sc = np.asarray(pair_score(z, P, kb))
        cd = np.asarray(cand).reshape(k, n_cand)
        idx = np.argmax(sc.reshape(k, n_cand), axis=1)
        wins_ref.append(cd[np.arange(k), idx])
        scores.append(sc)
        k_comp, k_val = jax.random.split(key)
        u1s.append(jax.random.uniform(k_comp, (C,), jnp.float32))
        u2s.append(jax.random.uniform(k_val, (C,), jnp.float32))
        dps.append(draw_param_rows(*below, lo, hi))
        Ps.append(P)
        cands.append(cand)
    kb = kb_real + 1 + 3
    if draw_in_kernel:
        a0, a1, a2 = jnp.stack(u1s), jnp.stack(u2s), jnp.stack(dps)
    else:
        a0 = jnp.stack(cands)
        a1 = jnp.zeros_like(a0)
        a2 = jnp.zeros((L, 7, kb), jnp.float32)
    win, _idx, seg_m, seg_s, seg_top = fused_suggest_pallas(
        a0, a1, a2, jnp.stack(Ps), k_below=kb, k=k, log_scale=log_scale,
        draw_in_kernel=draw_in_kernel,
    )
    wins_ref = np.stack(wins_ref).astype(np.float32)
    win = np.asarray(win)
    r_max, r_lme, r_mass = (
        np.asarray(v) for v in _ei_diag(jnp.asarray(np.stack(scores)))
    )
    n_top = min(16, C)
    g_max, g_lme, g_mass = (
        np.asarray(v)
        for v in ei_from_partials(seg_m, seg_s, seg_top, C, n_top)
    )
    diag_err = float(max(
        np.max(np.abs(r_max - g_max)),
        np.max(np.abs(r_lme - g_lme)),
        np.max(np.abs(r_mass - g_mass)),
    ))
    return {
        "case": name,
        "k_below": int(kb),
        "k_total": int(np.stack(Ps).shape[-1]),
        "k": int(k),
        "n_cand": int(n_cand),
        "log_scale": bool(log_scale),
        "draw_in_kernel": bool(draw_in_kernel),
        "winner_bitwise_match": bool(np.array_equal(wins_ref, win)),
        "winner_max_abs_err": float(np.max(np.abs(wins_ref - win))),
        "diag_max_abs_err": diag_err,
    }


def _trajectory_check(n_trials=40, seed=7):
    """fmin with the fused tier forced vs the default unfused path:
    identical trial docs, trial for trial, at the same seeds.  Runs in
    subprocesses so the scorer env force cannot leak into this
    process's jit caches."""
    import subprocess

    code = """
import os, json, sys
os.environ["JAX_PLATFORMS"] = "cpu"
scorer = sys.argv[1]
if scorer != "default":
    os.environ["HYPEROPT_TPU_SCORER"] = scorer
import numpy as np
from functools import partial
from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import tpe
space = {
    "u": hp.uniform("u", -2.0, 2.0),
    "lu": hp.loguniform("lu", -4.0, 2.0),
    "n": hp.normal("n", 0.0, 1.0),
    "c": hp.choice("c", [0, 1, 2]),
}
trials = Trials()
fmin(lambda c: float(c["u"]**2 + c["n"]**2 + 0.1*c["c"] + 0.01*c["lu"]),
     space, algo=partial(tpe.suggest, n_EI_candidates=24),
     max_evals=int(sys.argv[2]), trials=trials,
     rstate=np.random.default_rng(int(sys.argv[3])),
     show_progressbar=False, verbose=False, max_speculation=0)
out = [
    {k: [float(x) for x in v] for k, v in t["misc"]["vals"].items()}
    for t in trials.trials
]
print(json.dumps(out))
"""

    def run(scorer):
        r = subprocess.run(
            [sys.executable, "-c", code, scorer, str(n_trials), str(seed)],
            capture_output=True, text=True,
        )
        if r.returncode != 0:
            raise RuntimeError(
                f"trajectory arm {scorer!r} failed:\n{r.stderr[-2000:]}"
            )
        return json.loads(r.stdout.strip().splitlines()[-1])

    ref = run("default")
    fused = run("fused")
    return {
        "n_trials": n_trials,
        "seed": seed,
        "identical": ref == fused,
        "first_divergence": next(
            (i for i, (a, b) in enumerate(zip(ref, fused)) if a != b), None
        ),
    }


def _recompile_check(n_trials=80):
    """The fused tier under the one-trace-per-(bucket, family) budget."""
    from hyperopt_tpu.analysis.program_lint import audit_tpe_run

    prev = os.environ.get("HYPEROPT_TPU_SCORER")
    os.environ["HYPEROPT_TPU_SCORER"] = "fused"
    try:
        aud = audit_tpe_run(n_trials=n_trials)
    finally:
        if prev is None:
            os.environ.pop("HYPEROPT_TPU_SCORER", None)
        else:
            os.environ["HYPEROPT_TPU_SCORER"] = prev
    return {
        "n_trials": n_trials,
        "n_traces": aud.n_traces,
        "n_programs": aud.n_programs,
        "buckets": [[int(b), int(n)] for b, n in aud.bucket_summary()],
        "violations": [str(d) for d in aud.diagnostics()],
        "one_trace_per_bucket": not aud.diagnostics(),
    }


def _tiling_100k():
    """The 100k-history shape's tile decomposition — structural proof
    the mega-kernel's grid covers the shape, plus the VMEM residency
    of the parameter block."""
    from hyperopt_tpu.ops import parzen as parzen_ops
    from hyperopt_tpu.ops.pallas_gmm import _region_tile

    n_history = 100_000
    cap = parzen_ops.bucket(n_history)          # 131072
    lf = 25
    cap_b = parzen_ops.bucket(lf)               # 32
    kb = cap_b + 1
    ka = cap + 1
    tk = 512
    tkb = _region_tile(kb, tk)
    tka = _region_tile(ka, tk)
    KB = kb + (-kb) % tkb
    KA = ka + (-ka) % tka
    n_cand, tc = 8192, 512
    return {
        "n_history": n_history,
        "capt_bucket": cap,
        "k_below": kb,
        "k_above": ka,
        "k_total": kb + ka,
        "region_tiles": {"below": tkb, "above": tka},
        "component_tiles": {"below": KB // tkb, "above": KA // tka},
        "n_cand": n_cand,
        "candidate_tile": tc,
        "candidate_tiles": -(-n_cand // tc),
        "params_vmem_bytes": 3 * (KB + KA) * 4,
        "params_vmem_frac_of_16mb": round(
            3 * (KB + KA) * 4 / (16 * 2 ** 20), 4
        ),
        "covered": True,
    }


def _headline(platform: str):
    """The PR 7 null contract: the fused-vs-unfused EI-evals/s headline
    is measured only on TPU hardware (Mosaic lowering); quick/CPU runs
    stamp null with the reason."""
    if platform == "tpu":  # pragma: no cover - capture host only
        return _measure_headline_tpu()
    return {
        "value": None,
        "unit": "EI_evals/s",
        "vs_unfused": None,
        "unmeasured_reason": (
            "fused-kernel throughput is unavailable off-TPU (Mosaic "
            "lowering requires real hardware; this artifact was "
            "captured interpret-mode on CPU) — parity/trajectory/"
            "tiling guards above are the CPU-checkable contract; "
            "capture on the TPU host re-stamps this field (target: "
            ">=10x the 230.7 G EI-evals/s BENCH_TPU.json headline, "
            "non-null double-digit-MFU BENCH_TPU_100k.json headline)"
        ),
    }


def _measure_headline_tpu():  # pragma: no cover - capture host only
    """In-graph fused vs unfused A/B at the BENCH_TPU shapes (10k
    history, 8192 candidates)."""
    import time as _t

    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.ops import pallas_fused
    from hyperopt_tpu.ops import parzen as parzen_ops
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas_batched
    from hyperopt_tpu.ops.score import pair_params

    rng = np.random.default_rng(0)
    out = {}
    best = 0.0
    for n_hist in (10_000, 100_000):
        cap = parzen_ops.bucket(n_hist)
        obs = jnp.asarray(rng.normal(size=cap).astype(np.float32))
        wa, ma, sa = parzen_ops.adaptive_parzen_normal_padded(
            obs, n_hist, jnp.float32(1.0), jnp.float32(0.0),
            jnp.float32(10.0), 25,
        )
        wb, mb, sb = parzen_ops.adaptive_parzen_normal_padded(
            obs[:32], 25, jnp.float32(1.0), jnp.float32(0.0),
            jnp.float32(10.0), 25,
        )
        params = pair_params(wb, mb, sb, wa, ma, sa)[None]
        kb = int(wb.shape[0])
        k_real = (25 + 1) + (n_hist + 1)
        n_cand = 8192
        z = jnp.asarray(
            rng.normal(size=(1, n_cand)).astype(np.float32)
        )
        rows = jnp.zeros((1, 7, kb), jnp.float32)

        def timed(fused, iters=8):
            @jax.jit
            def chain(z0):
                def body(_, c):
                    zc = z0 + c * jnp.float32(1e-7)
                    if fused:
                        win = pallas_fused._fused_suggest_pallas(
                            zc, jnp.zeros_like(zc), rows, params, kb, 1,
                            16, 512, 512, False, False, False,
                            pallas_fused.resolve_fma("batched"),
                        )[0]
                        return win[0, 0] * jnp.float32(1e-7)
                    s = pair_score_pallas_batched(zc, params, kb)
                    idx = jnp.argmax(s, axis=1)
                    return jnp.take_along_axis(zc, idx[:, None], 1)[
                        0, 0
                    ] * jnp.float32(1e-7)

                return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

            jax.block_until_ready(chain(z))
            t0 = _t.perf_counter()
            jax.block_until_ready(chain(z))
            return (_t.perf_counter() - t0) / iters

        per_unfused = timed(False)
        per_fused = timed(True)
        rate = n_cand * k_real / per_fused
        out[f"fused_h{n_hist}_gei_s"] = round(rate / 1e9, 2)
        out[f"unfused_h{n_hist}_gei_s"] = round(
            n_cand * k_real / per_unfused / 1e9, 2
        )
        best = max(best, rate)
    out["value"] = round(best, 1)
    out["unit"] = "EI_evals/s"
    out["vs_unfused"] = round(
        out["fused_h10000_gei_s"] / out["unfused_h10000_gei_s"], 3
    )
    out["unmeasured_reason"] = None
    return out


def run_fused(quick: bool = True) -> dict:
    import jax

    platform = jax.devices()[0].platform
    t0 = time.time()
    errors = []

    parity = []
    for case in SHAPE_GRID:
        try:
            parity.append(_parity_case(*case))
        except Exception as e:  # pragma: no cover - diagnosed via report
            errors.append(f"parity[{case[0]}]: {e!r}")
    # the opt-in in-kernel-draw mode rides the grid once: tolerance
    # class (ulp-level), never asserted bitwise
    try:
        parity.append(_parity_case(*SHAPE_GRID[1], draw_in_kernel=True))
    except Exception as e:  # pragma: no cover
        errors.append(f"parity[draw_in_kernel]: {e!r}")

    exact = [p for p in parity if not p["draw_in_kernel"]]
    trajectory = _trajectory_check(n_trials=30 if quick else 60)
    recompile = _recompile_check(n_trials=60 if quick else 120)
    tiling = _tiling_100k()
    # a crashed tiled case lands in errors[], not exact — report it as
    # a failure instead of raising out of the report generator
    tiled_case = next(
        (p for p in exact if p["case"] == "tiled_100k"), None
    )

    ok = (
        not errors
        and all(p["winner_bitwise_match"] for p in exact)
        and all(p["diag_max_abs_err"] < 1e-3 for p in parity)
        and trajectory["identical"]
        and recompile["one_trace_per_bucket"]
        and tiled_case is not None
        and tiled_case["winner_bitwise_match"]
    )
    return {
        "metric": "fused_suggest_kernel",
        "quick": bool(quick),
        "ok": bool(ok),
        "platform": platform,
        "interpret": platform != "tpu",
        "n_parity_cases": len(parity),
        "parity": parity,
        "trajectory": trajectory,
        "recompilation": recompile,
        "tiling_100k": tiling,
        "headline": _headline(platform),
        "errors": errors,
        "elapsed_s": round(time.time() - t0, 2),
    }


def write_report(report: dict, out_path: str) -> None:
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    quick = "--quick" in argv
    out_path = (
        "BENCH_TPU_fused.quick.json" if quick else "BENCH_TPU_fused.json"
    )
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    report = run_fused(quick=quick)
    write_report(report, out_path)
    print(json.dumps({
        "metric": report["metric"], "ok": report["ok"],
        "artifact": out_path, "errors": report["errors"],
    }))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
