"""Service-plane chaos campaign: kill -9 the server, prove exactly-once.

The ISSUE-5 acceptance run: N studies (default 8) drive the HTTP
optimization server through their full suggest → evaluate → report
loops while the campaign injects service-plane faults:

- **server SIGKILL** — a supervisor kills -9 the server process at
  deterministic points (guaranteed kills at fixed progress fractions
  plus seeded extras) and restarts it on the same root+port, waiting
  for ``/readyz`` to go green (startup fsck + journal replay + seed
  cursor re-verification);
- **connection resets** — the server's chaos hook drops connections
  before or after the response commit (seeded, per route/study);
- **torn doc / torn journal writes** — trial docs are truncated in
  place after their atomic write and the response journal loses its
  tail, exercising the CRC trailer + fsck + journal-replay repairs;
- **slow-loris clients** — parked sockets trickling partial requests,
  bounded by the handler's read timeout.

Clients ride through all of it on the retrying ``ServiceClient``
(idempotency keys + deterministic backoff + circuit breaker).  The
campaign then asserts the exactly-once contract end to end:

1. zero lost or duplicated trials (every study: exactly ``--trials``
   docs, all DONE, distinct tids);
2. every study's ``vals`` trajectory identical to a fault-free twin
   run with the same seeds (no chaos, no HTTP);
3. a final ``fsck`` pass reports the store clean;
4. replaying a ``suggest``/``report`` with its original idempotency key
   returns the byte-identical response and provably consumes no seed
   (the seed-cursor attachment is unchanged).

Usage::

    JAX_PLATFORMS=cpu python scripts/chaos_serve_campaign.py \
        [--studies 8] [--trials 15] [--seed 0] [--kills 3] [--quick] \
        [--out CHAOS_SERVE.json]

Exit code 0 iff every assertion held.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALGO_PARAMS = {"n_startup_jobs": 3, "n_EI_candidates": 32}


def _space():
    from hyperopt_tpu import hp

    return {
        "x": hp.uniform("x", -5, 5),
        "lr": hp.loguniform("lr", -5, 0),
        "c": hp.choice("c", ["a", "b", "d"]),
    }


def _objective(point):
    """Pure function of the point — the chaos run and the fault-free
    twin must compute identical losses for identical suggestions."""
    return (
        (point["x"] - 1.0) ** 2
        + (np.log(point["lr"]) + 2.0) ** 2
        + (0.5 if point["c"] == "b" else 0.0)
    )


def _study_seed(seed, idx):
    return seed * 1000 + idx


# ---------------------------------------------------------------------
# fault-free twin (in-process, no HTTP, no chaos)
# ---------------------------------------------------------------------

def run_twin(n_studies, n_trials, seed):
    """Per-study vals trajectories of the uninterrupted run."""
    from hyperopt_tpu.fmin import space_eval
    from hyperopt_tpu.service import OptimizationService

    space = _space()
    svc = OptimizationService(root=None, batch_window=0.001)
    out = {}
    try:
        for i in range(n_studies):
            sid = f"chaos-{i}"
            svc.create_study(sid, space, seed=_study_seed(seed, i),
                             algo="tpe", algo_params=ALGO_PARAMS)
            traj = []
            for _ in range(n_trials):
                (t,) = svc.suggest(sid)
                traj.append(t["vals"])
                point = space_eval(space, t["vals"])
                svc.report(sid, t["tid"], loss=_objective(point))
            out[sid] = traj
    finally:
        svc.close()
    return out


# ---------------------------------------------------------------------
# server process management
# ---------------------------------------------------------------------

class ServerSupervisor:
    """Owns the server subprocess: spawn, SIGKILL, restart, readiness."""

    def __init__(self, root, port, chaos_config_json, log_dir):
        self.root = root
        self.port = port
        self.chaos_config_json = chaos_config_json
        self.log_dir = log_dir
        self.proc = None
        self.n_kills = 0
        self.n_tear_deaths = 0  # server SIGKILL'd itself mid-torn-write
        self.n_starts = 0
        self._lock = threading.Lock()

    def _env(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [REPO] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        )
        env.setdefault("JAX_PLATFORMS", "cpu")
        return env

    def start(self, wait_ready_timeout=180.0):
        from hyperopt_tpu.service import ServiceClient

        with self._lock:
            self.n_starts += 1
            log = open(os.path.join(
                self.log_dir, f"server.{self.n_starts}.log"), "wb")
            self.proc = subprocess.Popen(
                [
                    sys.executable, "-m", "hyperopt_tpu.service",
                    "--root", self.root,
                    "--port", str(self.port),
                    "--batch-window", "0.002",
                    "--chaos-config", self.chaos_config_json,
                    "--log-level", "INFO",
                ],
                env=self._env(), cwd=REPO,
                stdout=subprocess.DEVNULL, stderr=log,
            )
        client = ServiceClient(self.url, timeout=30)
        ready = client.wait_ready(timeout=wait_ready_timeout)
        return ready

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def kill9(self):
        with self._lock:
            if self.proc is None or self.proc.poll() is not None:
                return False
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=30)
            self.n_kills += 1
        return True

    def ensure_alive(self):
        """Restart after a chaos tear-kill (the server SIGKILLs itself
        mid-torn-write).  Returns True when a restart happened."""
        with self._lock:
            dead = self.proc is not None and self.proc.poll() is not None
            if dead:
                self.n_tear_deaths += 1
        if dead:
            self.start()
        return dead

    def stop(self, timeout=60.0):
        with self._lock:
            proc = self.proc
        if proc is None or proc.poll() is not None:
            return
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def slow_loris(host, port, hold_s=5.0):
    """Park one connection that trickles a partial request: the server
    must bound it with its read timeout, not hang a batch."""
    try:
        s = socket.create_connection((host, port), timeout=5)
        s.sendall(b"POST /v1/studies/loris/suggest HTTP/1.1\r\nHost: x\r\n")
        time.sleep(hold_s)
        s.close()
        return True
    except OSError:
        return False


# ---------------------------------------------------------------------
# the campaign
# ---------------------------------------------------------------------

def run_campaign(n_studies=8, n_trials=15, seed=0, min_kills=3,
                 root=None, quick=False):
    from hyperopt_tpu.fmin import space_eval
    from hyperopt_tpu.resilience.chaos import ChaosConfig, ChaosMonkey
    from hyperopt_tpu.resilience.fsck import fsck_path
    from hyperopt_tpu.service import ServiceClient, free_port

    if quick:
        n_trials = min(n_trials, 8)
    space = _space()
    t0 = time.time()

    twin = run_twin(n_studies, n_trials, seed)

    if root is None:
        root = tempfile.mkdtemp(prefix="chaos_serve_")
    os.makedirs(root, exist_ok=True)
    injection_log = os.path.join(root, "injections.jsonl")
    server_cfg = ChaosConfig(
        seed=seed,
        p_conn_reset_pre=0.06,
        p_conn_reset_post=0.06,
        # crash-consistent tears: each hit tears the write AND SIGKILLs
        # the server mid-write (tear_kills_process default), so every
        # tear is also an unscheduled server crash — keep them rarer
        # than the connection resets
        p_torn_doc=0.012,
        p_torn_journal=0.012,
        injection_log=injection_log,
    )
    # the campaign-side monkey rolls the supervisor's sites (kills
    # beyond the guaranteed schedule, slow-loris) — distinct sites, so
    # sharing the seed with the server monkey keeps both deterministic
    campaign_monkey = ChaosMonkey(ChaosConfig(
        seed=seed, p_server_kill=0.02, p_slow_loris=0.02,
        injection_log=injection_log,
    ))

    total_trials = n_studies * n_trials
    # guaranteed SIGKILLs at fixed progress fractions (mid-campaign =
    # mid-batch under 8 concurrent clients), seeded extras on top
    kill_ticks = {
        max(1, (total_trials * (i + 1)) // (min_kills + 1))
        for i in range(min_kills)
    }

    supervisor = ServerSupervisor(
        root, free_port(), server_cfg.to_json(), root
    )
    supervisor.start()

    progress = {"done": 0}
    progress_cv = threading.Condition()
    errors = []
    n_loris = 0
    stop_supervising = threading.Event()

    def client_for(idx):
        return ServiceClient(
            supervisor.url,
            timeout=60,
            deadline=300.0,
            max_transport_retries=200,
            backoff_base=0.05,
            backoff_max=1.0,
            jitter=0.2,
            retry_seed=seed,
            breaker_threshold=6,
            breaker_cooldown=0.5,
            idempotency_prefix=f"study{idx}",
        )

    def drive(idx):
        sid = f"chaos-{idx}"
        try:
            client = client_for(idx)
            client.create_study(
                sid, space, seed=_study_seed(seed, idx),
                algo="tpe", algo_params=ALGO_PARAMS, exist_ok=True,
            )
            for _ in range(n_trials):
                (t,) = client.suggest(sid)
                point = space_eval(space, t["vals"])
                client.report(sid, t["tid"], loss=_objective(point))
                with progress_cv:
                    progress["done"] += 1
                    progress_cv.notify_all()
        except Exception as e:
            errors.append(f"{sid}: {e!r}")
            with progress_cv:
                progress_cv.notify_all()

    def supervise():
        nonlocal n_loris
        seen = 0
        while not stop_supervising.is_set():
            with progress_cv:
                progress_cv.wait(timeout=0.5)
                done = progress["done"]
            try:
                # a torn-write site SIGKILLs the server from inside —
                # detect the corpse and restart it
                supervisor.ensure_alive()
            except Exception as e:  # pragma: no cover
                errors.append(f"crash restart failed: {e!r}")
                stop_supervising.set()
                return
            while seen < done:
                seen += 1
                kill = seen in kill_ticks
                if not kill and campaign_monkey.should_kill_server(
                    "extra"
                ):
                    kill = True
                if kill and supervisor.kill9():
                    try:
                        supervisor.start()
                    except Exception as e:  # pragma: no cover
                        errors.append(f"restart failed: {e!r}")
                        stop_supervising.set()
                        return
                if campaign_monkey.should_slow_loris("tick"):
                    if slow_loris("127.0.0.1", supervisor.port,
                                  hold_s=2.0):
                        n_loris += 1

    threads = [
        threading.Thread(target=drive, args=(i,), daemon=True)
        for i in range(n_studies)
    ]
    sup_thread = threading.Thread(target=supervise, daemon=True)
    sup_thread.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=1200)
    alive = [t for t in threads if t.is_alive()]
    if alive:
        errors.append(f"{len(alive)} study clients timed out")

    # -- exactly-once replay probe (on a scratch study; the supervisor
    # is still watching, so a tear-kill during the probe just restarts)
    try:
        replay = _replay_probe(supervisor.url, space, seed, root)
    except Exception as e:
        replay = {"ok": False, "error": repr(e)}
    stop_supervising.set()
    sup_thread.join(timeout=30)

    # -- graceful stop, then fsck the store -----------------------------
    supervisor.stop()
    fsck_repair = fsck_path(root, repair=True).summary()
    fsck_verify = fsck_path(root, repair=False).summary()

    # -- reconcile ------------------------------------------------------
    injected = _count_injections(injection_log)
    injected["server_kill_executed"] = supervisor.n_kills
    injected["tear_deaths"] = supervisor.n_tear_deaths
    injected["slow_loris_executed"] = n_loris
    n_injected = (
        sum(v for k, v in injected.items()
            if not k.endswith("_executed") and k != "tear_deaths")
        + supervisor.n_kills + n_loris
        - injected.get("server_kill", 0) - injected.get("slow_loris", 0)
    )
    total_sigkills = supervisor.n_kills + supervisor.n_tear_deaths

    integrity, trajectories_match = _verify_store(
        root, twin, n_studies, n_trials
    )

    ok = (
        not errors
        and integrity["lost_trials"] == 0
        and integrity["duplicated_trials"] == 0
        and trajectories_match
        and fsck_verify["clean"]
        and replay["ok"]
        and total_sigkills >= min_kills
    )
    return {
        "campaign": "chaos_serve",
        "ok": ok,
        "seed": seed,
        "n_studies": n_studies,
        "n_trials_per_study": n_trials,
        "algo_params": ALGO_PARAMS,
        "elapsed_s": round(time.time() - t0, 2),
        "errors": errors,
        "server_kills": total_sigkills,
        "server_kills_scheduled": supervisor.n_kills,
        "server_kills_mid_write": supervisor.n_tear_deaths,
        "server_starts": supervisor.n_starts,
        "slow_loris_connections": n_loris,
        "injected": injected,
        "total_injected": n_injected,
        "integrity": integrity,
        "trajectories_match_fault_free": trajectories_match,
        "fsck_after_repair": {
            k: v for k, v in fsck_verify.items() if k != "findings"
        },
        "fsck_repairs": fsck_repair["by_rule"],
        "replay": replay,
        "root": root,
    }


def _count_injections(path):
    out = {}
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return out
    from hyperopt_tpu.resilience.chaos import parse_injection_log

    # CRC-framed records; torn tail lines (the server was killed
    # mid-append) are detected by their frame and skipped
    for rec in parse_injection_log(raw):
        site = rec.get("site", "?")
        out[site] = out.get(site, 0) + 1
    return out


def _replay_probe(url, space, seed, root):
    """The acceptance's replay check, on a scratch study: same key →
    byte-identical response, seed cursor file untouched."""
    from hyperopt_tpu.service import ServiceClient
    from hyperopt_tpu.service.core import SEED_CURSOR_ATTACHMENT

    client = ServiceClient(url, deadline=120.0)
    sid = "replaycheck"
    client.create_study(sid, space, seed=seed + 7, algo="tpe",
                        algo_params=ALGO_PARAMS, exist_ok=True)
    body = {"n": 1, "idempotency_key": "probe-suggest"}
    st1, b1 = client._request(
        "POST", f"/v1/studies/{sid}/suggest", body, raw=True
    )
    cursor_file = os.path.join(
        root, "studies", sid, "attachments", SEED_CURSOR_ATTACHMENT
    )
    with open(cursor_file, "rb") as f:
        cursor_before = f.read()
    st2, b2 = client._request(
        "POST", f"/v1/studies/{sid}/suggest", body, raw=True
    )
    with open(cursor_file, "rb") as f:
        cursor_after = f.read()
    tid = json.loads(b1.decode())["trials"][0]["tid"]
    rbody = {"tid": tid, "loss": 1.25, "idempotency_key": "probe-report"}
    rs1, rb1 = client._request(
        "POST", f"/v1/studies/{sid}/report", rbody, raw=True
    )
    rbody2 = dict(rbody, loss=99.0)  # a buggy retry with a mutated loss
    rs2, rb2 = client._request(
        "POST", f"/v1/studies/{sid}/report", rbody2, raw=True
    )
    status = client.study_status(sid)
    ok = (
        st1 == st2 == rs1 == rs2 == 200
        and b1 == b2
        and rb1 == rb2
        and cursor_before == cursor_after
        and status["n_trials"] == 1
        and status["best"]["loss"] == 1.25
    )
    return {
        "ok": ok,
        "suggest_bytes_identical": b1 == b2,
        "report_bytes_identical": rb1 == rb2,
        "seed_cursor_unchanged": cursor_before == cursor_after,
        "first_loss_stands": status.get("best", {}).get("loss") == 1.25,
    }


def _verify_store(root, twin, n_studies, n_trials):
    """Read every study's docs off disk (post-fsck) and check the
    zero-lost/zero-duplicated and trajectory-identity invariants."""
    from hyperopt_tpu.base import JOB_STATE_DONE
    from hyperopt_tpu.parallel.file_trials import FileTrials

    lost = dup = incomplete = 0
    mismatched = []
    for i in range(n_studies):
        sid = f"chaos-{i}"
        qdir = os.path.join(root, "studies", sid)
        trials = FileTrials(qdir)
        docs = sorted(
            trials._dynamic_trials, key=lambda d: int(d["tid"])
        )
        tids = [int(d["tid"]) for d in docs]
        if len(set(tids)) != len(tids):
            dup += len(tids) - len(set(tids))
        if len(docs) < n_trials:
            lost += n_trials - len(docs)
        if len(docs) > n_trials:
            dup += len(docs) - n_trials
        incomplete += sum(
            1 for d in docs if d["state"] != JOB_STATE_DONE
        )
        got = [
            {
                label: v[0]
                for label, v in d["misc"]["vals"].items() if len(v)
            }
            for d in docs
        ]
        want = twin[sid]
        if len(got) != len(want) or any(
            g.keys() != w.keys()
            or any(not np.isclose(g[k], w[k]) for k in g)
            for g, w in zip(got, want)
        ):
            mismatched.append(sid)
    return (
        {
            "lost_trials": lost,
            "duplicated_trials": dup,
            "incomplete_trials": incomplete,
            "mismatched_studies": mismatched,
        },
        not mismatched and incomplete == 0,
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--studies", type=int, default=8)
    ap.add_argument("--trials", type=int, default=15)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kills", type=int, default=3,
                    help="guaranteed server SIGKILLs (seeded extras on top)")
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke config (caps trials per study at 8)")
    ap.add_argument(
        "--out",
        default=os.path.join(REPO, "CHAOS_SERVE.json"),
    )
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    report = run_campaign(
        n_studies=args.studies,
        n_trials=args.trials,
        seed=args.seed,
        min_kills=args.kills,
        quick=args.quick,
    )
    print(json.dumps(report, indent=1, default=str))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, default=str)
            f.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
