"""CI lint entry point: self-lint the repo with hyperopt_tpu.analysis.

Runs, in order of cost:

1. **race pass** over the concurrent driver layers (``pipeline.py``,
   ``parallel/file_trials.py``, ``parallel/jax_trials.py``) — enforces
   their own ``# guarded-by`` / ``# lock-order`` annotations;
2. **program pass, static** — the jax.jit donation contract of the
   device delta programs (no jax import);
3. **space pass** over every ``examples/`` space and the QUALITY.md
   benchmark domains (imports jax transitively via hyperopt_tpu);
4. with ``--trace``: the live jaxpr audit of the fused suggest program
   (host callbacks, f64 demotion — runs a small CPU probe);
5. with ``--audit [N]``: the N-trial (default 200) recompilation audit.

Exit code 0 even when diagnostics are found (the tier-1 flow runs this
as a NON-blocking step; the hard gate is tests/test_analysis.py, which
asserts zero diagnostics on the same targets).  ``--strict`` exits with
the error count instead.  Run: ``python scripts/lint.py [--fast]``.
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _example_spaces():
    """[(name, space)] from every examples/*.py module-level space."""
    from hyperopt_tpu.analysis import import_module_target, looks_like_space

    out = []
    ex_dir = os.path.join(_REPO, "examples")
    for fname in sorted(os.listdir(ex_dir)):
        if not fname.endswith(".py"):
            continue
        mod = import_module_target(os.path.join(ex_dir, fname))
        for name, obj in vars(mod).items():
            if not name.startswith("_") and looks_like_space(obj):
                out.append((f"examples/{fname}:{name}", obj))
    return out


def _quality_domains():
    from hyperopt_tpu.models import domains

    return [
        (f"QUALITY.md:{n}", domains.get(n).space)
        for n in ("quadratic1", "branin", "gauss_wave2", "hartmann6")
    ]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fast", action="store_true",
                    help="race + static program passes only (no jax)")
    ap.add_argument("--trace", action="store_true",
                    help="also trace the live suggest program to a jaxpr")
    ap.add_argument("--audit", nargs="?", const=200, type=int, default=None,
                    metavar="N", help="also run the N-trial recompilation "
                                      "audit (default N=200)")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on error diagnostics (default: "
                         "report-only — CI runs this non-blocking)")
    args = ap.parse_args(argv)

    from hyperopt_tpu.analysis import (
        Severity,
        format_report,
        lint_programs,
        lint_races,
        lint_space,
    )

    diags = list(lint_races())
    print(format_report(diags, header="== race pass (guarded-by/lock-order)"))

    prog = lint_programs(static_only=True)
    print(format_report(prog, header="== program pass (donation, static)"))
    diags += prog

    if not args.fast:
        spaces = _example_spaces() + _quality_domains()
        for name, space in spaces:
            ds = lint_space(space)
            if ds:
                print(format_report(ds, header=f"== space pass: {name}"))
            diags += ds
        print(f"== space pass: {len(spaces)} spaces checked")

        if args.trace or args.audit is not None:
            from hyperopt_tpu.analysis import lint_traced_program

            tr = lint_traced_program()
            print(format_report(tr, header="== program pass (jaxpr trace)"))
            diags += tr
        if args.audit is not None:
            from hyperopt_tpu.analysis import audit_tpe_run

            aud = audit_tpe_run(n_trials=args.audit)
            ds = aud.diagnostics()
            print(
                f"== recompilation audit: {aud.n_traces} trace(s) / "
                f"{aud.n_programs} program key(s) over {args.audit} "
                f"trials; buckets={aud.bucket_summary()}"
            )
            print(format_report(ds))
            diags += ds

    n_err = sum(1 for d in diags if d.severity == Severity.ERROR)
    print(f"\nlint: {len(diags)} diagnostic(s), {n_err} error(s)")
    if args.strict and n_err:
        return min(n_err, 125)
    return 0


if __name__ == "__main__":
    sys.exit(main())
