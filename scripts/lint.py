"""CI lint entry point: self-lint the repo with hyperopt_tpu.analysis.

Runs the shared ``analysis.run_self_lint()`` sections (the SAME list
``python -m hyperopt_tpu.analysis self`` runs — one package walk, one
discovery read, one pass ordering), in order of cost:

1. **race pass** over every auto-discovered lock-bearing module of the
   package — ``# guarded-by`` / ``# lock-order`` enforcement, the
   RL304 lock-acquisition-cycle check, RL305 blocking-calls-under-lock,
   and RL306 unregistered-lock-module coverage;
2. **durability pass** over every package module — the DL4xx
   crash-consistency discipline of every durable-write site;
3. **program pass, static** — the jax.jit donation contract, the PL206
   partition pin sites, and the PL208 dispatch-container call sites
   (no jax import);
4. **protocol pass** (SG7xx) over every ``protocol:``-annotated module
   plus the **protocol model check** — the explicit-state
   interleaving/crash checker over the segment store and replication
   plane (small scope by default; ``--deep`` runs the full sweep);
5. **space pass** over every ``examples/`` space and the QUALITY.md
   benchmark domains (imports jax transitively via hyperopt_tpu);
6. with ``--trace``: the live jaxpr audit of the fused suggest program
   (host callbacks, f64 demotion, and the PL206/PL207 partition audit
   on the virtual mesh — runs a small CPU probe);
7. with ``--audit [N]``: the N-trial (default 200) recompilation audit.

The self-lint is a HARD CI gate: error diagnostics exit nonzero (the
rule set is mature — every shipped module lints clean).  ``--no-gate``
is the escape hatch: report-only, always exit 0.  ``--json`` emits the
same stable ``[{rule, severity, file, line, message, hint}]`` schema
as ``python -m hyperopt_tpu.analysis --json`` so CI can upload a
machine-readable artifact.  Per-pass wall times are printed on a
``== timing:`` line; the ``--fast`` gate is budgeted (and tested) to
finish within 60 seconds.  Run: ``python scripts/lint.py [--fast]``.
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _example_spaces():
    """[(name, space)] from every examples/*.py module-level space."""
    from hyperopt_tpu.analysis import import_module_target, looks_like_space

    out = []
    ex_dir = os.path.join(_REPO, "examples")
    for fname in sorted(os.listdir(ex_dir)):
        if not fname.endswith(".py"):
            continue
        mod = import_module_target(os.path.join(ex_dir, fname))
        for name, obj in vars(mod).items():
            if not name.startswith("_") and looks_like_space(obj):
                out.append((f"examples/{fname}:{name}", obj))
    return out


def _quality_domains():
    from hyperopt_tpu.models import domains

    return [
        (f"QUALITY.md:{n}", domains.get(n).space)
        for n in ("quadratic1", "branin", "gauss_wave2", "hartmann6")
    ]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fast", action="store_true",
                    help="race + durability + static program passes "
                         "only (no jax)")
    ap.add_argument("--trace", action="store_true",
                    help="also trace the live suggest program to a jaxpr "
                         "(includes the partition audit when >=2 devices "
                         "are visible)")
    ap.add_argument("--audit", nargs="?", const=200, type=int, default=None,
                    metavar="N", help="also run the N-trial recompilation "
                                      "audit (default N=200)")
    ap.add_argument("--no-gate", action="store_true",
                    help="report-only: always exit 0 (the escape hatch; "
                         "the default is a hard gate on error "
                         "diagnostics)")
    ap.add_argument("--deep", action="store_true",
                    help="protocol model: full interleaving sweep "
                         "(crash budget 2) instead of the small scope")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the stable machine-readable schema "
                         "[{rule, severity, file, line, message, hint}] "
                         "instead of the human report (timing goes to "
                         "stderr)")
    # back-compat: --strict was the opt-in gate before the gate became
    # the default; it is now a no-op kept so existing CI lines work
    ap.add_argument("--strict", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    import json
    import time

    from hyperopt_tpu.analysis import (
        Severity,
        diagnostics_json,
        format_report,
        lint_space,
        run_self_lint,
    )

    t_start = time.perf_counter()
    diags = []
    timings = []
    # the shared self-lint sections (one package walk, one discovery
    # read) — identical to `python -m hyperopt_tpu.analysis self`
    for key, header, ds, secs in run_self_lint(deep=args.deep):
        diags += ds
        timings.append((key, secs))
        if not args.as_json:
            print(format_report(ds, header=header))

    if not args.fast:
        t0 = time.perf_counter()
        spaces = _example_spaces() + _quality_domains()
        for name, space in spaces:
            ds = lint_space(space)
            if ds and not args.as_json:
                print(format_report(ds, header=f"== space pass: {name}"))
            diags += ds
        timings.append(("space", time.perf_counter() - t0))
        if not args.as_json:
            print(f"== space pass: {len(spaces)} spaces checked")

        if args.trace or args.audit is not None:
            from hyperopt_tpu.analysis import (
                lint_partition_program,
                lint_traced_program,
            )
            from hyperopt_tpu.analysis.program_lint import capture_requests

            requests = capture_requests()
            tr = lint_traced_program(requests)
            tr.extend(lint_partition_program(requests))
            if not args.as_json:
                print(format_report(
                    tr, header="== program pass (jaxpr trace + "
                               "partition audit)",
                ))
            diags += tr
        if args.audit is not None:
            from hyperopt_tpu.analysis import audit_tpe_run

            aud = audit_tpe_run(n_trials=args.audit)
            ds = aud.diagnostics()
            if not args.as_json:
                print(
                    f"== recompilation audit: {aud.n_traces} trace(s) / "
                    f"{aud.n_programs} program key(s) over {args.audit} "
                    f"trials; buckets={aud.bucket_summary()}"
                )
                print(format_report(ds))
            diags += ds

    total = time.perf_counter() - t_start
    timing_line = "== timing: " + " ".join(
        f"{key}={secs:.2f}s" for key, secs in timings
    ) + f" total={total:.2f}s"
    if args.as_json:
        # machine-readable artifact on stdout; timing stays on stderr
        print(timing_line, file=sys.stderr)
        print(json.dumps(diagnostics_json(diags), indent=1))
    else:
        print(timing_line)

    n_err = sum(1 for d in diags if d.severity == Severity.ERROR)
    if not args.as_json:
        print(f"\nlint: {len(diags)} diagnostic(s), {n_err} error(s)")
    if args.no_gate:
        return 0
    return min(n_err, 125)


if __name__ == "__main__":
    sys.exit(main())
