"""CI lint entry point: self-lint the repo with hyperopt_tpu.analysis.

Runs, in order of cost:

1. **race pass** over every auto-discovered lock-bearing module of the
   package — ``# guarded-by`` / ``# lock-order`` enforcement, the
   RL304 lock-acquisition-cycle check, RL305 blocking-calls-under-lock,
   and RL306 unregistered-lock-module coverage;
2. **durability pass** over every package module — the DL4xx
   crash-consistency discipline of every durable-write site;
3. **program pass, static** — the jax.jit donation contract, the PL206
   partition pin sites, and the PL208 dispatch-container call sites
   (no jax import);
4. **space pass** over every ``examples/`` space and the QUALITY.md
   benchmark domains (imports jax transitively via hyperopt_tpu);
5. with ``--trace``: the live jaxpr audit of the fused suggest program
   (host callbacks, f64 demotion, and the PL206/PL207 partition audit
   on the virtual mesh — runs a small CPU probe);
6. with ``--audit [N]``: the N-trial (default 200) recompilation audit.

The self-lint is a HARD CI gate: error diagnostics exit nonzero (the
rule set is mature — every shipped module lints clean).  ``--no-gate``
is the escape hatch: report-only, always exit 0.  Run:
``python scripts/lint.py [--fast]``.
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _example_spaces():
    """[(name, space)] from every examples/*.py module-level space."""
    from hyperopt_tpu.analysis import import_module_target, looks_like_space

    out = []
    ex_dir = os.path.join(_REPO, "examples")
    for fname in sorted(os.listdir(ex_dir)):
        if not fname.endswith(".py"):
            continue
        mod = import_module_target(os.path.join(ex_dir, fname))
        for name, obj in vars(mod).items():
            if not name.startswith("_") and looks_like_space(obj):
                out.append((f"examples/{fname}:{name}", obj))
    return out


def _quality_domains():
    from hyperopt_tpu.models import domains

    return [
        (f"QUALITY.md:{n}", domains.get(n).space)
        for n in ("quadratic1", "branin", "gauss_wave2", "hartmann6")
    ]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fast", action="store_true",
                    help="race + durability + static program passes "
                         "only (no jax)")
    ap.add_argument("--trace", action="store_true",
                    help="also trace the live suggest program to a jaxpr "
                         "(includes the partition audit when >=2 devices "
                         "are visible)")
    ap.add_argument("--audit", nargs="?", const=200, type=int, default=None,
                    metavar="N", help="also run the N-trial recompilation "
                                      "audit (default N=200)")
    ap.add_argument("--no-gate", action="store_true",
                    help="report-only: always exit 0 (the escape hatch; "
                         "the default is a hard gate on error "
                         "diagnostics)")
    # back-compat: --strict was the opt-in gate before the gate became
    # the default; it is now a no-op kept so existing CI lines work
    ap.add_argument("--strict", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    from hyperopt_tpu.analysis import (
        Severity,
        discover_race_files,
        format_report,
        lint_durability,
        lint_programs,
        lint_races,
        lint_space,
        package_files,
    )

    # one package walk + one discovery read feed all three passes
    pkg = package_files()
    race_files = discover_race_files(paths=pkg)
    diags = list(lint_races(race_files))
    print(format_report(
        diags,
        header=f"== race pass ({len(race_files)} lock-bearing modules, "
               f"guarded-by/lock-order/lock-graph)",
    ))

    dur = lint_durability(pkg)
    print(format_report(
        dur,
        header=f"== durability pass ({len(pkg)} modules, "
               f"write-site discipline)",
    ))
    diags += dur

    prog = lint_programs(static_only=True, paths=pkg)
    print(format_report(
        prog,
        header="== program pass (donation + pin sites + dispatch "
               "containers, static)",
    ))
    diags += prog

    if not args.fast:
        spaces = _example_spaces() + _quality_domains()
        for name, space in spaces:
            ds = lint_space(space)
            if ds:
                print(format_report(ds, header=f"== space pass: {name}"))
            diags += ds
        print(f"== space pass: {len(spaces)} spaces checked")

        if args.trace or args.audit is not None:
            from hyperopt_tpu.analysis import (
                lint_partition_program,
                lint_traced_program,
            )
            from hyperopt_tpu.analysis.program_lint import capture_requests

            requests = capture_requests()
            tr = lint_traced_program(requests)
            tr.extend(lint_partition_program(requests))
            print(format_report(
                tr, header="== program pass (jaxpr trace + partition "
                           "audit)",
            ))
            diags += tr
        if args.audit is not None:
            from hyperopt_tpu.analysis import audit_tpe_run

            aud = audit_tpe_run(n_trials=args.audit)
            ds = aud.diagnostics()
            print(
                f"== recompilation audit: {aud.n_traces} trace(s) / "
                f"{aud.n_programs} program key(s) over {args.audit} "
                f"trials; buckets={aud.bucket_summary()}"
            )
            print(format_report(ds))
            diags += ds

    n_err = sum(1 for d in diags if d.severity == Severity.ERROR)
    print(f"\nlint: {len(diags)} diagnostic(s), {n_err} error(s)")
    if args.no_gate:
        return 0
    return min(n_err, 125)


if __name__ == "__main__":
    sys.exit(main())
