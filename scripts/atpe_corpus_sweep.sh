#!/bin/bash
# Full ATPE corpus sweep (VERDICT r4 #3): one shard per training domain
# (so partial progress survives interruption), then fit + held-out
# validation + artifact write.  ~3h on one CPU core.
#   bash scripts/atpe_corpus_sweep.sh [ROWS_DIR] [SEEDS] [SEED_OFFSET]
# SEED_OFFSET gives a disjoint seed range (corpus rows are deterministic
# per seed, so a replication run MUST use a non-overlapping offset or it
# regenerates the original rows).  SKIP_FIT=1 builds shards only.
set -u
cd /root/repo || exit 1
ROWS=${1:-/tmp/atpe_rows}
SEEDS=${2:-13}
SEED_OFFSET=${3:-0}
mkdir -p "$ROWS"
export JAX_PLATFORMS=cpu
unset PALLAS_AXON_POOL_IPS

DOMAINS="quadratic1 q1_lognormal n1 gauss_wave gauss_wave2 distractor hartmann6 many_dists nested_arch rosen10"

for d in $DOMAINS; do
  # seed range in the shard name: a rerun with different SEEDS/OFFSET
  # must not silently reuse (or mix with) another range's shards
  SHARD="$ROWS/$d.s${SEED_OFFSET}_${SEEDS}.pkl"
  if [ -s "$SHARD" ]; then
    echo "$(date -u +%FT%TZ) shard $SHARD already present, skipping"
    continue
  fi
  echo "$(date -u +%FT%TZ) building shard $SHARD"
  python -m hyperopt_tpu.models.train_atpe \
    --domains "$d" --seeds "$SEEDS" --seed-offset "$SEED_OFFSET" \
    --configs 20 --cont-evals 8 \
    --checkpoints 20 28 36 45 --rows-out "$SHARD" \
    || echo "$(date -u +%FT%TZ) shard $d FAILED"
done

if [ "${SKIP_FIT:-0}" = "1" ]; then
  echo "$(date -u +%FT%TZ) shards done (SKIP_FIT=1)"
  exit 0
fi
echo "$(date -u +%FT%TZ) fitting from shards"
python -m hyperopt_tpu.models.train_atpe --fit-from "$ROWS"/*.pkl
echo "$(date -u +%FT%TZ) sweep done"
