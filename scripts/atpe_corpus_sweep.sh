#!/bin/bash
# Full ATPE corpus sweep (VERDICT r4 #3): one shard per training domain
# (so partial progress survives interruption), then fit + held-out
# validation + artifact write.  ~3h on one CPU core.
#   bash scripts/atpe_corpus_sweep.sh [ROWS_DIR]
set -u
cd /root/repo || exit 1
ROWS=${1:-/tmp/atpe_rows}
mkdir -p "$ROWS"
export JAX_PLATFORMS=cpu
unset PALLAS_AXON_POOL_IPS

DOMAINS="quadratic1 q1_lognormal n1 gauss_wave gauss_wave2 distractor hartmann6 many_dists nested_arch rosen10"

for d in $DOMAINS; do
  if [ -s "$ROWS/$d.pkl" ]; then
    echo "$(date -u +%FT%TZ) shard $d already present, skipping"
    continue
  fi
  echo "$(date -u +%FT%TZ) building shard $d"
  python -m hyperopt_tpu.models.train_atpe \
    --domains "$d" --seeds 13 --configs 20 --cont-evals 8 \
    --checkpoints 20 28 36 45 --rows-out "$ROWS/$d.pkl" \
    || echo "$(date -u +%FT%TZ) shard $d FAILED"
done

echo "$(date -u +%FT%TZ) fitting from shards"
python -m hyperopt_tpu.models.train_atpe --fit-from "$ROWS"/*.pkl
echo "$(date -u +%FT%TZ) sweep done"
