#!/bin/bash
# TPU-artifact watcher (VERDICT r4 #1): the axon tunnel dies for hours at
# a time, so this loops probing it and, the moment a real chip answers,
# runs the full bench on hardware and saves committed-quality artifacts:
#   BENCH_TPU.json       - headline config (10k history, pallas/fma A/B)
#   BENCH_TPU_100k.json  - 100k-history host-transfer flatness point
# Exits once BENCH_TPU.json has "platform": "tpu".
cd /root/repo || exit 1

have_tpu_artifact() {
  [ -s "$1" ] && python -c "import json,sys; d=json.load(open('$1')); sys.exit(0 if d.get('platform')=='tpu' else 1)" 2>/dev/null
}

while true; do
  if have_tpu_artifact BENCH_TPU.json && have_tpu_artifact BENCH_TPU_100k.json; then
    echo "$(date -u +%FT%TZ) both TPU artifacts present; watcher done"
    break
  fi
  if timeout -k 15 180 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel ALIVE"
    if ! have_tpu_artifact BENCH_TPU.json; then
      # the tunnel can die again within minutes: grab a fast-but-complete
      # capture first (all metrics + full scorer_ab table, reduced timing
      # reps), then upgrade to the full-rep run if the window holds
      echo "$(date -u +%FT%TZ) running fast headline bench..."
      if BENCH_TIMED=8 BENCH_LOOP_ITERS=20 BENCH_BATCH_REPS=2 \
         timeout -k 30 2400 python bench.py >/tmp/bench_tpu_out.json 2>/tmp/bench_tpu_err.log \
         && have_tpu_artifact /tmp/bench_tpu_out.json; then
        cp /tmp/bench_tpu_out.json BENCH_TPU.json
        echo "$(date -u +%FT%TZ) captured BENCH_TPU.json (fast reps)"
      else
        echo "$(date -u +%FT%TZ) fast bench failed/CPU; stderr tail:"
        tail -5 /tmp/bench_tpu_err.log
      fi
    fi
    if have_tpu_artifact BENCH_TPU.json && ! have_tpu_artifact BENCH_TPU_100k.json; then
      echo "$(date -u +%FT%TZ) running 100k-history bench (AB off)..."
      if BENCH_N_HISTORY=100000 BENCH_AB=0 BENCH_TIMED=15 \
         timeout -k 30 3600 python bench.py >/tmp/bench_tpu100k_out.json 2>/tmp/bench_tpu100k_err.log \
         && have_tpu_artifact /tmp/bench_tpu100k_out.json; then
        cp /tmp/bench_tpu100k_out.json BENCH_TPU_100k.json
        echo "$(date -u +%FT%TZ) captured BENCH_TPU_100k.json"
      else
        echo "$(date -u +%FT%TZ) 100k bench failed/CPU; stderr tail:"
        tail -5 /tmp/bench_tpu100k_err.log
      fi
    fi
    if have_tpu_artifact BENCH_TPU.json && ! [ -s BENCH_TPU_full.json ]; then
      echo "$(date -u +%FT%TZ) running full-rep headline bench..."
      if timeout -k 30 3600 python bench.py >/tmp/bench_tpu_full.json 2>/tmp/bench_tpu_full_err.log \
         && have_tpu_artifact /tmp/bench_tpu_full.json; then
        cp /tmp/bench_tpu_full.json BENCH_TPU_full.json
        echo "$(date -u +%FT%TZ) captured BENCH_TPU_full.json"
      fi
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel dead"
  fi
  sleep 240
done
