"""Search-health study report → ``STUDY_HEALTH.json``.

Exercises the search-quality telemetry layer
(:mod:`hyperopt_tpu.diagnostics`) end to end and commits the evidence:

- **Healthy domains** — seeded TPE runs over the QUALITY.md zoo
  domains, each fed into a :class:`SearchStats` (fused-readback EI/
  Parzen snapshots + the loss stream); every one must verdict **OK**.
  The stall window is set to the trial budget: STALLED is an operator
  policy about *wasted* budget, and a study that converges inside its
  budget is healthy (the STALLED fixture below proves the rule fires
  when it should).
- **Seeded degenerate fixtures** — one per SH5xx rule, each flagged
  with its intended rule id: the warm-up boundary at ``n_startup_jobs``
  (SH501), a plateaued objective (SH502), a below/above-indistinguishable
  discrete space (SH503), a sigma-collapse history whose best trials
  share one exact x (SH504), an exhausted 3-choice space (SH505), and a
  NaN-storm objective (SH506).
- **The zero-dispatch contract** — the EI statistics ride the existing
  fused suggest readback: over M device-plane suggests, the
  :class:`~hyperopt_tpu.profiling.DeviceProfiler` must count exactly M
  dispatches, the PR-2 :class:`RecompilationAuditor` must stay within
  its one-trace-per-(trial-bucket, family) budget, and every suggest
  must have published a diag snapshot.
- **Overhead** — suggest p50 with the host-side snapshot build enabled
  vs disabled (``diagnostics.set_enabled``), interleaved rounds;
  acceptance: within 5%.

Run:  python scripts/study_report.py [--quick] [--out STUDY_HEALTH.json]
CI:   python bench.py --study-health --quick
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HEALTHY_DOMAINS = ("quadratic1", "branin", "gauss_wave2", "hartmann6")


def _done_doc(tid, vals, loss):
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

    return {
        "tid": tid, "spec": None,
        "result": {"status": STATUS_OK, "loss": loss},
        "misc": {
            "tid": tid, "cmd": None,
            "idxs": {k: [tid] for k in vals},
            "vals": {k: [v] for k, v in vals.items()},
        },
        "state": JOB_STATE_DONE, "owner": None, "book_time": None,
        "refresh_time": None, "exp_key": None,
    }


def _warm_trials(space, docs):
    from hyperopt_tpu import Trials
    from hyperopt_tpu.base import Domain

    domain = Domain(lambda c: 0.0, space)
    trials = Trials()
    trials._insert_trial_docs(docs)
    trials.refresh()
    return domain, trials


def _fmin_stats(obj, space, seed, max_evals, stats, **algo_kw):
    """One seeded fmin run feeding ``stats`` (fused snapshots + loss
    stream through the driver's search_stats wiring)."""
    from functools import partial

    import numpy as np

    from hyperopt_tpu import Trials, fmin
    from hyperopt_tpu.algos import tpe

    trials = Trials()
    fmin(
        obj, space, algo=partial(tpe.suggest, **algo_kw),
        max_evals=max_evals, trials=trials,
        rstate=np.random.default_rng(seed), show_progressbar=False,
        verbose=False, search_stats=stats,
    )
    return trials


def _suggest_into(stats, domain, trials, seed, **kw):
    """One direct device suggest; feeds the published snapshot and the
    trials' loss stream into ``stats``."""
    from hyperopt_tpu import diagnostics as sdiag
    from hyperopt_tpu.algos import tpe

    tpe.suggest([10_000], domain, trials, seed, **kw)
    stats.record_suggest(sdiag.last_suggest_diag())
    stats.observe_trials(trials)


# ---------------------------------------------------------------------
# fixtures (one per SH5xx rule, all seeded)
# ---------------------------------------------------------------------


def fixture_warmup(quick):
    """SH501: one result short of n_startup_jobs."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.diagnostics import SearchStats

    stats = SearchStats(n_startup_jobs=20)
    _fmin_stats(
        lambda c: float(c["x"] ** 2), {"x": hp.uniform("x", -5, 5)},
        seed=5, max_evals=19, stats=stats,
    )
    boundary = SearchStats(n_startup_jobs=20)
    _fmin_stats(
        lambda c: float(c["x"] ** 2), {"x": hp.uniform("x", -5, 5)},
        seed=5, max_evals=25, stats=boundary, n_startup_jobs=20,
        n_EI_candidates=64,
    )
    return stats, {"past_boundary_state": boundary.health()["state"]}


def fixture_stalled(quick):
    """SH502: an objective with a hard floor — best plateaus at 2.0."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.diagnostics import SearchStats

    stats = SearchStats(n_startup_jobs=10, stall_window=15)
    _fmin_stats(
        lambda c: max(abs(c["x"]), 2.0), {"x": hp.uniform("x", -5, 5)},
        seed=1, max_evals=30 if quick else 50, stats=stats,
        n_startup_jobs=10, n_EI_candidates=64,
    )
    return stats, {}


def fixture_flat_ei(quick):
    """SH503: a 6-choice space where below and above carry identical
    category evidence (only 3 categories ever observed, interleaved), so
    l(x)/g(x) rank nothing — and the space is NOT exhausted (3 of 6
    categories unseen), so no higher rule can own the verdict."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.diagnostics import SearchStats

    space = {"c": hp.choice("c", list(range(6)))}
    docs = [_done_doc(i, {"c": i % 3}, float(i % 2)) for i in range(40)]
    domain, trials = _warm_trials(space, docs)
    stats = SearchStats(n_startup_jobs=10, stall_window=40)
    # gamma 3.2 puts ~half the history below: equal below/above counts
    # per category is what makes the posteriors (hence EI) flat
    _suggest_into(
        stats, domain, trials, seed=11,
        n_startup_jobs=10, n_EI_candidates=64, gamma=3.2,
    )
    return stats, {}


def fixture_sigma_collapse(quick):
    """SH504: the 12 best trials share one exact x — every below-set
    neighbor gap is zero, so the adaptive-Parzen fit clips every
    observation component to the sigma floor."""
    import numpy as np

    from hyperopt_tpu import hp
    from hyperopt_tpu.diagnostics import SearchStats

    rng = np.random.default_rng(0)
    space = {"x": hp.uniform("x", 0.0, 1.0)}
    docs = []
    for i in range(100):
        if i < 12:
            docs.append(_done_doc(i, {"x": 0.5}, 0.0))
        else:
            docs.append(_done_doc(
                i, {"x": float(rng.uniform(0, 1))},
                1.0 + float(rng.random()),
            ))
    domain, trials = _warm_trials(space, docs)
    stats = SearchStats(n_startup_jobs=10, stall_window=200)
    _suggest_into(
        stats, domain, trials, seed=9,
        n_startup_jobs=10, n_EI_candidates=64, gamma=1.0,
    )
    return stats, {}


def fixture_exhausted(quick):
    """SH505: a 3-choice space driven well past its 3 configurations —
    every category observed, every EI argmax a duplicate."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.diagnostics import SearchStats

    stats = SearchStats(n_startup_jobs=8, stall_window=200)
    _fmin_stats(
        lambda c: float(c["c"]), {"c": hp.choice("c", [0.0, 1.0, 2.0])},
        seed=4, max_evals=20 if quick else 30, stats=stats,
        n_startup_jobs=8, n_EI_candidates=64,
    )
    return stats, {}


def fixture_nan_storm(quick):
    """SH506: the objective diverges (NaN loss) on most trials past the
    first few — the below set is starved while suggests stay fast."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.diagnostics import SearchStats

    cnt = {"n": 0}

    def nan_storm(c):
        cnt["n"] += 1
        return float("nan") if cnt["n"] > 5 else float(c["x"] ** 2)

    stats = SearchStats(n_startup_jobs=10, stall_window=200)
    _fmin_stats(
        nan_storm, {"x": hp.uniform("x", -5, 5)},
        seed=3, max_evals=20 if quick else 30, stats=stats,
        n_startup_jobs=10, n_EI_candidates=64,
    )
    return stats, {}


FIXTURES = (
    ("warmup_boundary", "SH501", fixture_warmup),
    ("stalled_plateau", "SH502", fixture_stalled),
    ("flat_ei_indistinct_choice", "SH503", fixture_flat_ei),
    ("sigma_collapse_identical_best", "SH504", fixture_sigma_collapse),
    ("exhausted_3_choice", "SH505", fixture_exhausted),
    ("nan_storm_objective", "SH506", fixture_nan_storm),
)


# ---------------------------------------------------------------------
# the zero-dispatch + overhead sections
# ---------------------------------------------------------------------


def zero_dispatch_check(quick):
    """The EI statistics must add ZERO device dispatches: M suggests →
    exactly M profiled dispatches, recompiles within the one-trace
    budget, and a published diag snapshot per suggest."""
    import numpy as np

    from hyperopt_tpu import diagnostics as sdiag
    from hyperopt_tpu import hp, profiling
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.analysis import RecompilationAuditor
    from hyperopt_tpu.observability import DeviceStats

    rng = np.random.default_rng(0)
    space = {
        "x": hp.uniform("x", -5, 5),
        "lr": hp.loguniform("lr", -5, 0),
        "c": hp.choice("c", ["a", "b", "d"]),
    }
    docs = [
        _done_doc(i, {
            "x": float(rng.uniform(-5, 5)),
            "lr": float(np.exp(rng.uniform(-5, 0))),
            "c": int(rng.integers(3)),
        }, float(rng.normal()))
        for i in range(60)
    ]
    domain, trials = _warm_trials(space, docs)
    n_suggests = 6 if quick else 12
    stats = DeviceStats()
    n_snapshots = 0
    with profiling.DeviceProfiler(stats=stats):
        with RecompilationAuditor() as auditor:
            # warm outside the count? No: the auditor budget covers the
            # single compile too; dispatch counting starts fresh below
            for i in range(n_suggests):
                tpe.suggest(
                    [1000 + i], domain, trials, i, n_startup_jobs=10,
                    n_EI_candidates=128, verbose=False,
                )
                if sdiag.last_suggest_diag() is not None:
                    n_snapshots += 1
    retrace_violations = [
        key for key, n in auditor.trace_counts.items() if n > 1
    ]
    return {
        "n_suggests": n_suggests,
        "n_dispatches": stats.n_dispatches,
        "extra_dispatches": stats.n_dispatches - n_suggests,
        "n_diag_snapshots": n_snapshots,
        "recompile_trace_counts": {
            str(bucket): n for bucket, n in auditor.bucket_summary()
        },
        "retrace_violations": [str(v) for v in retrace_violations],
        "ok": (
            stats.n_dispatches == n_suggests
            and n_snapshots == n_suggests
            and not retrace_violations
        ),
    }


def measure_overhead(quick, n=12, rounds=3):
    """Suggest p50 with the host-side snapshot build on vs off,
    interleaved rounds (median of per-round regressions)."""
    import numpy as np

    from hyperopt_tpu import diagnostics as sdiag
    from hyperopt_tpu import hp
    from hyperopt_tpu.algos import tpe

    rng = np.random.default_rng(1)
    space = {
        "x": hp.uniform("x", -5, 5),
        "lr": hp.loguniform("lr", -5, 0),
        "c": hp.choice("c", ["a", "b", "d"]),
    }
    docs = [
        _done_doc(i, {
            "x": float(rng.uniform(-5, 5)),
            "lr": float(np.exp(rng.uniform(-5, 0))),
            "c": int(rng.integers(3)),
        }, float(rng.normal()))
        for i in range(60)
    ]
    domain, trials = _warm_trials(space, docs)
    if quick:
        n, rounds = 6, 2

    def p50(enabled, ids_start, seed0):
        sdiag.set_enabled(enabled)
        try:
            times = []
            for i in range(n):
                t0 = time.perf_counter()
                tpe.suggest(
                    [ids_start + i], domain, trials, seed0 + i,
                    n_startup_jobs=10, n_EI_candidates=128, verbose=False,
                )
                times.append(time.perf_counter() - t0)
        finally:
            sdiag.set_enabled(True)
        return float(np.median(times))

    # warm the program once outside the timed sample
    tpe.suggest([90_000], domain, trials, 0, n_startup_jobs=10,
                n_EI_candidates=128, verbose=False)
    regressions = []
    ids = 100_000
    for r in range(rounds):
        base = p50(False, ids, 10 + r * 2 * n)
        ids += n
        on = p50(True, ids, 10 + r * 2 * n + n)
        ids += n
        regressions.append((on - base) / base)
    return {
        "n_per_round": n,
        "rounds": rounds,
        "p50_regression_frac": round(float(np.median(regressions)), 4),
        "p50_regression_rounds": [round(r, 4) for r in regressions],
    }


# ---------------------------------------------------------------------
# report
# ---------------------------------------------------------------------


def run_report(quick=False, overhead=True):
    import jax
    import numpy as np

    from hyperopt_tpu.diagnostics import SearchStats
    from hyperopt_tpu.models import domains as zoo

    platform = jax.devices()[0].platform
    t0 = time.time()

    # --- healthy domains: all must verdict OK -------------------------
    domains = HEALTHY_DOMAINS[:2] if quick else HEALTHY_DOMAINS
    max_evals = 30 if quick else 60
    healthy = {}
    for name in domains:
        d = zoo.get(name)
        optimum = (
            float(d.fmin)
            if d.fmin is not None and np.isfinite(d.fmin) else None
        )
        stats = SearchStats(
            n_startup_jobs=20, stall_window=max_evals, optimum=optimum,
        )
        _fmin_stats(
            d.fn, d.space, seed=0, max_evals=max_evals, stats=stats,
            n_EI_candidates=64,
        )
        h = stats.health()
        snap = stats.snapshot()
        labels = (snap["last_suggest"] or {}).get("labels", {})
        flats = [
            v["ei_flatness"] for v in labels.values()
            if v["ei_flatness"] is not None
        ]
        healthy[name] = {
            "state": h["state"],
            "rules": [r["rule"] for r in h["rules"]],
            "best_loss": snap["best_loss"],
            "regret": snap["regret"],
            "n_results": snap["n_results"],
            "ei_flatness_mean": (
                round(float(np.mean(flats)), 4) if flats else None
            ),
            "ok": h["state"] == "OK",
        }

    # --- degenerate fixtures: each flagged with its intended rule -----
    fixtures = {}
    for name, intended_rule, fn in FIXTURES:
        stats, extra = fn(quick)
        h = stats.health()
        fired = {r["rule"] for r in h["rules"]}
        rec = {
            "intended_rule": intended_rule,
            "state": h["state"],
            "rule": h["rule"],
            "rules": [r["rule"] for r in h["rules"]],
            "detail": h["rules"][0]["detail"] if h["rules"] else None,
            # the intended rule must OWN the verdict, not merely fire
            "ok": h["rule"] == intended_rule and intended_rule in fired,
        }
        rec.update(extra)
        if name == "warmup_boundary":
            # the boundary is two-sided: one short of n_startup_jobs is
            # WARMUP, past it is not
            rec["ok"] = rec["ok"] and rec["past_boundary_state"] != "WARMUP"
        fixtures[name] = rec

    # --- zero-dispatch + overhead -------------------------------------
    zd = zero_dispatch_check(quick)
    overhead_rec = measure_overhead(quick) if overhead else None

    ok = (
        all(v["ok"] for v in healthy.values())
        and all(v["ok"] for v in fixtures.values())
        and zd["ok"]
        and (
            overhead_rec is None
            or overhead_rec["p50_regression_frac"] < 0.05
        )
    )
    return {
        "metric": "study_health",
        "platform": platform,
        "quick": bool(quick),
        "max_evals_healthy": max_evals,
        "healthy": healthy,
        "fixtures": fixtures,
        "zero_dispatch": zd,
        "overhead": overhead_rec,
        "elapsed_s": round(time.time() - t0, 2),
        "ok": ok,
    }


def write_report(report, path):
    with open(path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=False)
        f.write("\n")


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--out", default="STUDY_HEALTH.json")
    parser.add_argument("--no-overhead", action="store_true")
    options = parser.parse_args(argv)
    report = run_report(
        quick=options.quick, overhead=not options.no_overhead
    )
    write_report(report, options.out)
    print(json.dumps({
        "metric": report["metric"],
        "ok": report["ok"],
        "healthy": {k: v["state"] for k, v in report["healthy"].items()},
        "fixtures": {
            k: f"{v['state']} (want {v['intended_rule']})"
            for k, v in report["fixtures"].items()
        },
        "extra_dispatches": report["zero_dispatch"]["extra_dispatches"],
        "overhead": (
            report["overhead"]["p50_regression_frac"]
            if report["overhead"] else None
        ),
        "out": options.out,
    }))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
