"""Roofline device profile of the fused suggest plane.

Runs a seeded suggest workload (serial driver loop across a history
bucket boundary, plus batched k-trial dispatches) with the
:class:`hyperopt_tpu.profiling.DeviceProfiler` installed, and
aggregates the per-dispatch records into ``DEVICE_PROFILE.json``:

- the **per-signature roofline table** — for every fused program
  signature: dispatch count, steady-state device time, modeled FLOPs
  and HBM bytes, achieved TFLOP/s and GB/s, arithmetic intensity, the
  **binding ceiling** (HBM bandwidth vs peak FLOP/s) and the fraction
  of it achieved, plus XLA's own ``cost_analysis()`` numbers for the
  same program as a cross-check of the analytical model;
- the **binding-ceiling histogram** (is this workload bandwidth- or
  compute-bound?), **duty cycle**, and **memory watermarks**;
- an **observer-overhead check**: suggest p50 with the profiler
  installed vs disabled (acceptance: within 5% — observability must
  not tax the hot path it measures).

Run:  python scripts/device_report.py [--quick] [--out DEVICE_PROFILE.json]
      python scripts/device_report.py --profile-dir /tmp/prof   (+ jax.profiler)
CI:   python bench.py --device-profile --quick

CPU runs use the nominal CPU ceilings (flagged in ``peaks.source``) so
the artifact schema — non-null binding ceiling and roofline_pct on
every row — holds on every platform; absolute percentages are only
meaningful on hardware captures.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _suggest_p50(tpe, domain, trials, n_cand, seed0, ids_start, n):
    """Median wall-clock of n fresh single-trial suggests (history is
    NOT grown, so no retrace can land inside the sample)."""
    import numpy as np

    times = []
    for i in range(n):
        t0 = time.perf_counter()
        tpe.suggest(
            [ids_start + i], domain, trials, seed0 + i,
            n_EI_candidates=n_cand, verbose=False,
        )
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), ids_start + n


def measure_overhead(tpe, domain, trials, n_cand, ids_start,
                     n=12, rounds=3):
    """Observer-overhead check: suggest p50 with a DeviceProfiler
    installed vs with the observer list empty, interleaved over
    ``rounds`` rounds (median of the per-round regressions — single
    ratios on a shared CI box are noise)."""
    import numpy as np

    from hyperopt_tpu import profiling
    from hyperopt_tpu.observability import DeviceStats

    regressions = []
    seed0 = 10_000
    for r in range(rounds):
        base, ids_start = _suggest_p50(
            tpe, domain, trials, n_cand, seed0, ids_start, n
        )
        seed0 += n
        with profiling.DeviceProfiler(stats=DeviceStats()):
            on, ids_start = _suggest_p50(
                tpe, domain, trials, n_cand, seed0, ids_start, n
            )
        seed0 += n
        regressions.append((on - base) / base)
    return {
        "n_per_round": n,
        "rounds": rounds,
        "p50_regression_frac": round(float(np.median(regressions)), 4),
        "p50_regression_rounds": [round(r, 4) for r in regressions],
    }


def run_profile(quick=False, overhead=True, n_history=None,
                profile_dir=None, cost_analysis=True):
    import jax
    import numpy as np

    import bench
    from hyperopt_tpu import profiling
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK
    from hyperopt_tpu.observability import DeviceStats

    platform = jax.devices()[0].platform
    n_hist = int(n_history) if n_history else (300 if quick else 1500)
    n_serial = 6 if quick else 20
    batch_ks = (8,) if quick else (8, 32)
    # candidate count: production size on hardware, bounded on CPU
    n_cand = bench.N_EI_CANDIDATES if platform == "tpu" else 512

    domain, trials = bench.build_history_trials(n_hist)
    rng = np.random.default_rng(1)

    def complete(docs):
        for d in docs:
            d["state"] = JOB_STATE_DONE
            d["result"] = {
                "status": STATUS_OK, "loss": float(rng.standard_normal()),
            }
        trials._insert_trial_docs(docs)
        trials.refresh()

    stats = DeviceStats()
    prof = profiling.DeviceProfiler(stats=stats, keep_samples=True)
    capture = (
        profiling.ProfileCapture(profile_dir, max_dispatches=16)
        if profile_dir else None
    )
    next_id = n_hist
    t0 = time.time()
    with prof:
        if capture is not None:
            capture.install()
        try:
            # serial driver loop: each suggest completes and joins the
            # history, so the run crosses a power-of-two bucket
            # boundary and profiles both the steady state and the
            # rebuild+retrace signature
            for i in range(n_serial):
                docs = tpe.suggest(
                    [next_id], domain, trials, i + 1,
                    n_EI_candidates=n_cand, verbose=False,
                )
                next_id += 1
                complete(docs)
            # batched dispatches: k trials through ONE fused program
            # (the JaxTrials / service production shape)
            for k in batch_ks:
                for r in range(2):
                    ids = list(range(next_id, next_id + k))
                    next_id += k
                    tpe.suggest(
                        ids, domain, trials, 100 + r,
                        n_EI_candidates=n_cand, verbose=False,
                    )
        finally:
            if capture is not None:
                capture.uninstall()
    workload_s = time.time() - t0

    summary = stats.summary()
    sigs = summary["signatures"]

    # XLA's own cost analysis of each profiled program — the
    # cross-check that keeps the analytical model honest (compiles a
    # fresh copy per signature: report-time cost, never dispatch-time)
    if cost_analysis:
        for row in sigs:
            reqs = prof.sample_requests(row["sig"])
            if reqs is None:
                continue
            try:
                xc = profiling.xla_cost(reqs)
            except Exception:
                xc = None
            if not xc:
                continue
            row["xla"] = {
                "flops": xc["flops"],
                "bytes_accessed": xc["bytes"],
                "flops_ratio_analytical_over_xla": (
                    round(row["flops_per_dispatch"] / xc["flops"], 4)
                    if xc["flops"] else None
                ),
                "bytes_ratio_analytical_over_xla": (
                    round(row["hbm_bytes_per_dispatch"] / xc["bytes"], 4)
                    if xc["bytes"] else None
                ),
            }

    unattributed = sum(
        row["n_dispatches"] for row in sigs
        if row["binding_ceiling"] is None or row["roofline_pct"] is None
    ) + summary["signature_drops"]

    overhead_rec = None
    if overhead:
        overhead_rec = measure_overhead(
            tpe, domain, trials, n_cand, next_id,
            n=6 if quick else 12,
        )

    ok = (
        summary["n_dispatches"] > 0
        and unattributed == 0
        and all(
            row["roofline_pct"] is not None
            and row["binding_ceiling"] is not None
            and row["achieved_GBps"] is not None
            for row in sigs
        )
        and summary["duty_cycle"] is not None
        and summary["memory"]["live_buffer_highwater_bytes"] > 0
        and (
            overhead_rec is None
            or overhead_rec["p50_regression_frac"] < 0.05
        )
    )
    return {
        "metric": "device_profile",
        "platform": platform,
        "quick": bool(quick),
        "n_history0": n_hist,
        "n_EI_candidates": n_cand,
        "n_serial_suggests": n_serial,
        "batch_ks": list(batch_ks),
        "peaks": prof.peaks,
        "workload_s": round(workload_s, 2),
        "n_dispatches": summary["n_dispatches"],
        "n_requests": summary["n_requests"],
        "n_compile_dispatches": summary["n_compile_dispatches"],
        "duty_cycle": summary["duty_cycle"],
        "device_busy_s": summary["busy_s"],
        "binding_ceiling_hist": summary["binding_ceiling_counts"],
        "roofline_pct_mean": summary["roofline_pct_mean"],
        "memory": summary["memory"],
        "signatures": sigs,
        "unattributed_dispatches": unattributed,
        "profile_capture": (
            capture.summary() if capture is not None else None
        ),
        "overhead": overhead_rec,
        "ok": ok,
    }


def write_report(report, path):
    with open(path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=False)
        f.write("\n")


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--out", default="DEVICE_PROFILE.json")
    parser.add_argument("--n-history", type=int, default=None)
    parser.add_argument("--profile-dir", default=None)
    parser.add_argument("--no-overhead", action="store_true")
    parser.add_argument(
        "--no-cost-analysis", action="store_true",
        help="skip the per-signature XLA cost_analysis() cross-check "
             "(one extra compile per signature)",
    )
    options = parser.parse_args(argv)
    report = run_profile(
        quick=options.quick,
        overhead=not options.no_overhead,
        n_history=options.n_history,
        profile_dir=options.profile_dir,
        cost_analysis=not options.no_cost_analysis,
    )
    write_report(report, options.out)
    print(json.dumps({
        "metric": report["metric"],
        "ok": report["ok"],
        "platform": report["platform"],
        "n_dispatches": report["n_dispatches"],
        "n_signatures": len(report["signatures"]),
        "duty_cycle": report["duty_cycle"],
        "binding_ceiling_hist": report["binding_ceiling_hist"],
        "out": options.out,
    }))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
