"""Replica-plane chaos campaign: kill -9 the owner, prove warm failover.

The ISSUE-13 acceptance run: N studies (default 8, each with its OWN
program bucket via a distinct ``n_EI_candidates``) drive TWO replica
server processes sharing one store root.  Mid-campaign the supervisor
``kill -9``s the replica that owns the larger half of the studies; the
survivor's failure detector claims the dead replica's leases after TTL
expiry and takes each study over **claim → fsck-clean → recover →
ledger pre-warm → serve**.  Clients ride through on consistent-hash
routing + ring failover + idempotent retries.  The campaign asserts:

1. every study the victim owned migrates to the survivor, every
   takeover record is ``ok`` with ``fsck_clean`` true;
2. the migrated studies' FIRST post-failover suggests hit **zero
   request-path compiles** on the survivor (the shared compile ledger
   + dry prepare probes pre-warmed their program grid before cutover;
   proven by the survivor's cold-suggest counters, sampled around a
   quiescent probe window in which ONLY those first suggests run);
3. zero lost or duplicated trials, and every study's ``vals``
   trajectory is trial-for-trial identical to a fault-free
   single-replica twin at the same seeds (exactly-once across the
   migration);
4. a final ``fsck`` pass reports the shared store clean (the FS409
   lease rules included).

The kill POINT is armed by the seeded ``replica_kill`` chaos site —
one roll per completed pre-phase trial against the current owner — and
executed at the pre-phase barrier: the probe window must be quiescent
so the cold-counter delta is attributable to the migrated studies'
first suggests alone.

Usage::

    JAX_PLATFORMS=cpu python scripts/failover_campaign.py \
        [--studies 8] [--pre 6] [--post 5] [--seed 0] [--quick] \
        [--ttl 2.0] [--out FAILOVER_SERVE.json]

Exit code 0 iff every assertion held.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _space():
    from hyperopt_tpu import hp

    return {
        "x": hp.uniform("x", -5, 5),
        "lr": hp.loguniform("lr", -5, 0),
        "c": hp.choice("c", ["a", "b", "d"]),
    }


def _objective(point):
    """Pure function of the point — the chaos run and the fault-free
    twin must compute identical losses for identical suggestions."""
    return (
        (point["x"] - 1.0) ** 2
        + (np.log(point["lr"]) + 2.0) ** 2
        + (0.5 if point["c"] == "b" else 0.0)
    )


def _study_seed(seed, idx):
    return seed * 1000 + idx


def _study_params(idx):
    """Every study gets its OWN program bucket (a distinct candidate
    count): the survivor never compiled a victim study's program while
    serving its own tenants, so a warm first post-failover suggest is
    evidence of the ledger pre-warm, not of bucket sharing."""
    return {"n_startup_jobs": 3, "n_EI_candidates": 8 * (idx + 1)}


# ---------------------------------------------------------------------
# fault-free twin (one in-process service, no HTTP, no replicas)
# ---------------------------------------------------------------------

def run_twin(study_ids, n_trials, seed):
    """Per-study vals trajectories of the uninterrupted single-replica
    run at the same seeds and algo params."""
    from hyperopt_tpu.fmin import space_eval
    from hyperopt_tpu.service import OptimizationService

    space = _space()
    svc = OptimizationService(root=None, batch_window=0.001)
    out = {}
    try:
        for i, sid in enumerate(study_ids):
            svc.create_study(sid, space, seed=_study_seed(seed, i),
                             algo="tpe", algo_params=_study_params(i))
            traj = []
            for _ in range(n_trials):
                (t,) = svc.suggest(sid)
                traj.append(t["vals"])
                point = space_eval(space, t["vals"])
                svc.report(sid, t["tid"], loss=_objective(point))
            out[sid] = traj
    finally:
        svc.close()
    return out


def _spread_study_ids(urls, n_studies):
    """Study ids whose consistent-hash primaries split evenly across
    the replicas.  The ring is deterministic in the URL set alone, so
    the campaign — like every client — computes the split with zero
    coordination; picking names BY the ring removes the (small) chance
    a fixed name set lands every study on one replica."""
    from hyperopt_tpu.service.replicas import HashRing

    ring = HashRing(urls)
    want = {u: n_studies // len(urls) for u in urls}
    spare = n_studies - sum(want.values())
    names, i = [], 0
    while len(names) < n_studies:
        sid = f"fo-{i}"
        i += 1
        primary = ring.primary(sid)
        if want.get(primary, 0) > 0:
            want[primary] -= 1
            names.append(sid)
        elif spare > 0:
            spare -= 1
            names.append(sid)
        if i > 10_000:
            raise RuntimeError("ring never covered the even split")
    return names


# ---------------------------------------------------------------------
# replica process management
# ---------------------------------------------------------------------

class Replica:
    """One replica server subprocess on the shared root."""

    def __init__(self, root, replica_id, port, ttl, log_dir):
        self.root = root
        self.replica_id = replica_id
        self.port = port
        self.ttl = ttl
        self.log_dir = log_dir
        self.proc = None

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def _env(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [REPO] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        )
        env.setdefault("JAX_PLATFORMS", "cpu")
        return env

    def start(self, wait_ready_timeout=300.0):
        from hyperopt_tpu.service import ServiceClient

        log = open(os.path.join(
            self.log_dir, f"{self.replica_id}.log"), "wb")
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "hyperopt_tpu.service",
                "--root", self.root,
                "--port", str(self.port),
                "--replica-id", self.replica_id,
                "--advertise-url", self.url,
                "--replica-ttl", str(self.ttl),
                "--batch-window", "0.002",
                # the persistent XLA cache can load an executable whose
                # low-bit numerics differ from a fresh in-process
                # compile, flipping near-tie EI winners — with two
                # replicas sharing the cache dir, WHICH replica
                # compiled a program first would decide the other's
                # numerics.  The twin comparison needs fresh-compile
                # numerics everywhere; the compile LEDGER (not the XLA
                # cache) is what the takeover pre-warm replays, so the
                # warm-failover proof is unaffected.
                "--compile-cache-dir", "none",
                "--log-level", "INFO",
            ],
            env=self._env(), cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=log,
        )
        client = ServiceClient(self.url, timeout=30)
        return client.wait_ready(timeout=wait_ready_timeout)

    def kill9(self):
        if self.proc is None or self.proc.poll() is not None:
            return False
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)
        return True

    def stop(self, timeout=60.0):
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.send_signal(signal.SIGTERM)
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10)


# ---------------------------------------------------------------------
# the campaign
# ---------------------------------------------------------------------

def _fleet_client(urls, seed, idx, phase):
    from hyperopt_tpu.service import ServiceClient

    return ServiceClient(
        replicas=urls,
        timeout=60,
        deadline=300.0,
        retry_timeout=300.0,
        backoff_base=0.05,
        backoff_max=1.0,
        jitter=0.2,
        retry_seed=seed,
        breaker_threshold=4,
        breaker_cooldown=0.5,
        # unique per (study, phase): a fresh client restarts its key
        # sequence, and the journal rejects cross-route key reuse
        idempotency_prefix=f"fo{idx}-{phase}",
    )


def _drive_phase(urls, study_ids, n_trials, seed, space, errors):
    """Drive every study ``n_trials`` further, one client thread each
    (the concurrent-tenant shape), joining at a barrier."""
    from hyperopt_tpu.fmin import space_eval

    def drive(idx, sid):
        try:
            client = _fleet_client(urls, seed, idx, "pre")
            for _ in range(n_trials):
                (t,) = client.suggest(sid)
                point = space_eval(space, t["vals"])
                client.report(sid, t["tid"], loss=_objective(point))
        except Exception as e:
            errors.append(f"{sid}: {e!r}")

    threads = [
        threading.Thread(target=drive, args=(i, sid), daemon=True)
        for i, sid in enumerate(study_ids)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=1200)
    stuck = [t for t in threads if t.is_alive()]
    if stuck:
        errors.append(f"{len(stuck)} study clients timed out")


def _owned_studies(url):
    from hyperopt_tpu.service import ServiceClient

    doc = ServiceClient(url, deadline=60.0).replicas()
    return doc.get("owned_studies", []), doc


def _cold_counters(url):
    from hyperopt_tpu.service import ServiceClient

    stats = ServiceClient(url, deadline=60.0).service_status()["stats"]
    return {
        "n_cold_suggests": stats["n_cold_suggests"],
        "n_cold_after_ready": stats["n_cold_after_ready"],
    }


def run_campaign(n_studies=8, n_pre=6, n_post=5, seed=0, ttl=2.0,
                 root=None, quick=False):
    from hyperopt_tpu.fmin import space_eval
    from hyperopt_tpu.resilience.chaos import ChaosConfig, ChaosMonkey
    from hyperopt_tpu.resilience.fsck import fsck_path
    from hyperopt_tpu.service import free_port

    if quick:
        n_pre, n_post = min(n_pre, 4), min(n_post, 3)
    space = _space()
    n_trials = n_pre + n_post
    t0 = time.time()
    errors = []

    if root is None:
        root = tempfile.mkdtemp(prefix="failover_serve_")
    os.makedirs(root, exist_ok=True)
    replicas = [
        Replica(root, "r1", free_port(), ttl, root),
        Replica(root, "r2", free_port(), ttl, root),
    ]
    for r in replicas:
        r.start()
    urls = [r.url for r in replicas]
    study_ids = _spread_study_ids(urls, n_studies)

    twin = run_twin(study_ids, n_trials, seed)

    # the seeded owning-replica SIGKILL site: one roll per completed
    # pre-phase trial against the current owner arms the kill, which
    # executes at the pre-phase barrier (the first-suggest probe window
    # must be quiescent so the survivor's cold-counter delta is
    # attributable to the migrated studies alone)
    monkey = ChaosMonkey(ChaosConfig(seed=seed, p_replica_kill=0.25))

    try:
        # -- create + pre phase ----------------------------------------
        for i, sid in enumerate(study_ids):
            _fleet_client(urls, seed, i, "create").create_study(
                sid, space, seed=_study_seed(seed, i),
                algo="tpe", algo_params=_study_params(i), exist_ok=True,
            )
        owned = {r.replica_id: _owned_studies(r.url)[0] for r in replicas}
        campaign_owned = {
            rid: sorted(set(sids) & set(study_ids))
            for rid, sids in owned.items()
        }
        victim = max(
            replicas, key=lambda r: len(campaign_owned[r.replica_id])
        )
        survivor = next(r for r in replicas if r is not victim)

        _drive_phase(urls, study_ids, n_pre, seed, space, errors)
        kill_rolls = sum(
            1 for _ in range(n_studies * n_pre)
            if monkey.should_kill_replica(victim.replica_id)
        )

        # -- the kill --------------------------------------------------
        victim_owned = sorted(
            set(_owned_studies(victim.url)[0]) & set(study_ids)
        )
        cold_before = _cold_counters(survivor.url)
        takeovers_before = len(
            _owned_studies(survivor.url)[1]["stats"]["recent_takeovers"]
        )
        if kill_rolls == 0:
            # the docstring's contract: the kill POINT is armed by the
            # seeded replica_kill site.  At p=0.25 over studies*pre
            # rolls this is a ~1e-6 branch — but if it happens, failing
            # honestly beats killing a replica no roll armed.
            errors.append(
                "seeded replica_kill site fired 0 rolls; kill not armed"
            )
        killed = victim.kill9() if kill_rolls > 0 else False
        t_kill = time.time()

        # -- first-suggest probe window (quiescent): ONE suggest+report
        # per migrated study, serially, through the failover client ----
        first_suggest = {}
        for sid in victim_owned:
            idx = study_ids.index(sid)
            client = _fleet_client(urls, seed, idx, "probe")
            t1 = time.monotonic()
            (t,) = client.suggest(sid)
            first_suggest[sid] = round(time.monotonic() - t1, 3)
            point = space_eval(space, t["vals"])
            client.report(sid, t["tid"], loss=_objective(point))
        mttr_s = round(time.time() - t_kill, 2)
        cold_after = _cold_counters(survivor.url)
        survivor_owned_now, survivor_doc = _owned_studies(survivor.url)

        # -- post phase: the remaining trials (migrated studies already
        # spent one on the probe), every study concurrent again --------
        remaining = {
            sid: n_post - (1 if sid in victim_owned else 0)
            for sid in study_ids
        }

        def drive_rest(idx, sid):
            try:
                client = _fleet_client(urls, seed, idx, "post")
                for _ in range(remaining[sid]):
                    (t,) = client.suggest(sid)
                    point = space_eval(space, t["vals"])
                    client.report(sid, t["tid"], loss=_objective(point))
            except Exception as e:
                errors.append(f"{sid}: {e!r}")

        threads = [
            threading.Thread(
                target=drive_rest, args=(i, sid), daemon=True
            )
            for i, sid in enumerate(study_ids)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=1200)
        if any(th.is_alive() for th in threads):
            errors.append("post-phase study clients timed out")

        # -- reconcile -------------------------------------------------
        final_owned, final_doc = _owned_studies(survivor.url)
        takeover_records = [
            rec for rec in
            final_doc["stats"]["recent_takeovers"][takeovers_before:]
            if rec["study_id"] in study_ids
        ]
        migrated = sorted(
            set(victim_owned) & set(final_owned)
        )
        cold_delta = {
            k: cold_after[k] - cold_before[k] for k in cold_before
        }
    finally:
        for r in replicas:
            r.stop()

    fsck_repair = fsck_path(root, repair=True).summary()
    fsck_verify = fsck_path(root, repair=False).summary()
    integrity, trajectories_match = _verify_store(
        root, twin, study_ids, n_trials
    )

    by_study = {rec["study_id"]: rec for rec in takeover_records}
    takeovers_ok = bool(victim_owned) and all(
        by_study.get(sid, {}).get("ok") is True
        and by_study.get(sid, {}).get("fsck_clean") is True
        for sid in victim_owned
    )
    prewarm = {"warm": 0, "skipped": 0, "error": 0, "pending": 0,
               "compiling": 0}
    for rec in takeover_records:
        for k, v in (rec.get("prewarm") or {}).items():
            prewarm[k] = prewarm.get(k, 0) + int(v)

    ok = (
        not errors
        and killed
        and migrated == victim_owned
        and takeovers_ok
        and prewarm["error"] == 0
        and cold_delta["n_cold_suggests"] == 0
        and cold_delta["n_cold_after_ready"] == 0
        and integrity["lost_trials"] == 0
        and integrity["duplicated_trials"] == 0
        and trajectories_match
        and fsck_verify["clean"]
    )
    return {
        "campaign": "failover_serve",
        "ok": ok,
        "quick": quick,
        "seed": seed,
        "n_studies": n_studies,
        "study_ids": study_ids,
        "n_replicas": len(replicas),
        "n_trials_per_study": n_trials,
        "n_pre": n_pre,
        "n_post": n_post,
        "replica_ttl_s": ttl,
        "elapsed_s": round(time.time() - t0, 2),
        "errors": errors,
        "ownership_before_kill": campaign_owned,
        "victim": victim.replica_id,
        "survivor": survivor.replica_id,
        "victim_killed": killed,
        "kill_site_rolls_hit": kill_rolls,
        "victim_owned": victim_owned,
        "migrated": migrated,
        "n_migrated": len(migrated),
        "takeovers": takeover_records,
        "all_takeovers_ok_and_fsck_clean": takeovers_ok,
        "prewarm": prewarm,
        "first_suggest_s": first_suggest,
        "migration_window_s": mttr_s,
        "cold_suggest_delta_over_probe_window": cold_delta,
        "integrity": integrity,
        "trajectories_match_fault_free": trajectories_match,
        "fsck_after_repair": {
            k: v for k, v in fsck_verify.items() if k != "findings"
        },
        "fsck_repairs": fsck_repair["by_rule"],
        "root": root,
    }


def _verify_store(root, twin, study_ids, n_trials):
    """Read every study's docs off disk (post-fsck) and check the
    zero-lost/zero-duplicated and trajectory-identity invariants."""
    from hyperopt_tpu.base import JOB_STATE_DONE
    from hyperopt_tpu.parallel.file_trials import FileTrials

    lost = dup = incomplete = 0
    mismatched = []
    for sid in study_ids:
        qdir = os.path.join(root, "studies", sid)
        trials = FileTrials(qdir)
        docs = sorted(
            trials._dynamic_trials, key=lambda d: int(d["tid"])
        )
        tids = [int(d["tid"]) for d in docs]
        if len(set(tids)) != len(tids):
            dup += len(tids) - len(set(tids))
        if len(docs) < n_trials:
            lost += n_trials - len(docs)
        if len(docs) > n_trials:
            dup += len(docs) - n_trials
        incomplete += sum(
            1 for d in docs if d["state"] != JOB_STATE_DONE
        )
        got = [
            {
                label: v[0]
                for label, v in d["misc"]["vals"].items() if len(v)
            }
            for d in docs
        ]
        want = twin[sid]
        if len(got) != len(want) or any(
            g.keys() != w.keys()
            or any(not np.isclose(g[k], w[k]) for k in g)
            for g, w in zip(got, want)
        ):
            mismatched.append(sid)
    return (
        {
            "lost_trials": lost,
            "duplicated_trials": dup,
            "incomplete_trials": incomplete,
            "mismatched_studies": mismatched,
        },
        not mismatched and incomplete == 0,
    )


def write_report(report, out_path):
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1, default=str)
        f.write("\n")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--studies", type=int, default=8)
    ap.add_argument("--pre", type=int, default=6,
                    help="trials per study before the kill")
    ap.add_argument("--post", type=int, default=5,
                    help="trials per study after the kill")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=2.0,
                    help="replica lease TTL (failover detection time)")
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke config (caps pre/post at 4/3)")
    ap.add_argument(
        "--out",
        default=os.path.join(REPO, "FAILOVER_SERVE.json"),
    )
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    report = run_campaign(
        n_studies=args.studies,
        n_pre=args.pre,
        n_post=args.post,
        seed=args.seed,
        ttl=args.ttl,
        quick=args.quick,
    )
    print(json.dumps(report, indent=1, default=str))
    if args.out:
        write_report(report, args.out)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
