"""Seeded multi-study load generator for the optimization service.

The ISSUE-4 acceptance run: ``--studies`` (default 8) concurrent
studies, each a serial HTTP client driving suggest → simulated
objective → report against ONE in-process server, all seeded.  Emits
``BENCH_SERVE.json`` with the serving headlines:

- ``suggest_p50_ms`` / ``suggest_p99_ms`` — end-to-end suggest latency
  through the HTTP plane (queue wait + batching window + fused device
  program + readback);
- ``mean_batch_occupancy`` — suggest requests per fused device
  dispatch (the continuous-batching win: > 1 means the device ran
  fewer programs than the studies made requests);
- ``n_dispatches`` vs ``n_batched_suggests`` — the dispatch-count
  reduction itself.

Acceptance gate (exit code): every study completes every trial, mean
occupancy > 1.5, and dispatches < device-plane suggest requests.

Usage::

    JAX_PLATFORMS=cpu python scripts/serve_loadgen.py \
        [--studies 8] [--trials 20] [--seed 0] [--quick] [--out BENCH_SERVE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# fast TPE engagement: the startup trials are host-side and don't
# exercise the batching plane this benchmark measures
ALGO_PARAMS = {"n_startup_jobs": 3, "n_EI_candidates": 64}


def _space():
    from hyperopt_tpu import hp

    return {
        "x": hp.uniform("x", -5, 5),
        "lr": hp.loguniform("lr", -5, 0),
        "w": hp.quniform("w", 0, 10, 1),
        "c": hp.choice("c", ["a", "b", "d"]),
    }


def _objective(point, rng):
    """Deterministic-per-draw synthetic objective (no sleep: latency
    under CONTENTION is the point — while one fused program runs, the
    other studies' requests pile into the next batch)."""
    return (
        (point["x"] - 1.0) ** 2
        + (np.log(point["lr"]) + 2.0) ** 2
        + 0.1 * point["w"]
        + (0.5 if point["c"] == "b" else 0.0)
        + float(rng.normal()) * 0.01
    )


def run_loadgen(n_studies=8, n_trials=20, seed=0, batch_window=0.004,
                root=None, tracer=None, slo_gate=False, on_service=None,
                service_kwargs=None):
    """Run the seeded campaign; returns the BENCH_SERVE.json payload.

    ``tracer``: an optional :class:`hyperopt_tpu.tracing.Tracer` — the
    server traces every sampled request end-to-end (clients send
    ``X-Hyperopt-Trace`` ids by default) and the caller aggregates the
    trace log afterwards (``scripts/trace_report.py``).

    ``slo_gate``: evaluate the SL6xx catalog after the campaign and
    fold "no rule breaching" into the exit gate (the ROADMAP's
    "SLO-gated loadgen"); the rule table lands in the report either
    way.  ``on_service(service)`` runs before shutdown — the hook
    slo_report uses to read stats the report does not carry.
    ``service_kwargs`` pass through to OptimizationService (e.g.
    ``slo_enabled=False`` for the overhead A/B)."""
    from hyperopt_tpu.fmin import space_eval
    from hyperopt_tpu.service import (
        OptimizationService,
        ServiceClient,
        ServiceServer,
    )

    space = _space()
    service_kwargs = dict(service_kwargs or {})
    if slo_gate and "slo_rules" not in service_kwargs:
        # SLO objectives are deployment config: the latency bounds are
        # calibrated to the serving platform — a CPU-backend CI run
        # legitimately pays ~seconds of fused-dispatch contention that
        # a TPU serves in milliseconds, and its warm p50 shrinks as
        # steady state accumulates while contention spikes keep the
        # warm p99 at dispatch scale, stretching the ratio.  The CPU
        # bounds (100x, 10 s) still catch the pathology on record —
        # the ~670x blended ratio of the original BENCH_SERVE capture.
        # The error/duty/store objectives are platform-independent.
        from hyperopt_tpu import slo as slo_mod

        tpu = _platform() == "tpu"
        service_kwargs["slo_rules"] = slo_mod.default_rules(
            latency_ratio={"ratio_max": 25.0 if tpu else 100.0},
            latency_absolute={"p99_bound_s": 2.5 if tpu else 10.0},
        )
    service = OptimizationService(
        root=root, batch_window=batch_window, tracer=tracer,
        **service_kwargs,
    )
    server = ServiceServer(service).start()
    errors = []
    t0 = time.perf_counter()
    try:
        def drive(study_idx):
            try:
                sid = f"load-{study_idx}"
                client = ServiceClient(server.url)
                client.create_study(
                    sid, space, seed=seed * 1000 + study_idx,
                    algo="tpe", algo_params=ALGO_PARAMS,
                )
                rng = np.random.default_rng(seed * 1000 + study_idx)
                for _ in range(n_trials):
                    (t,) = client.suggest(sid)
                    point = space_eval(space, t["vals"])
                    client.report(
                        sid, t["tid"], loss=_objective(point, rng)
                    )
            except Exception as e:
                errors.append(f"study {study_idx}: {e!r}")

        threads = [
            threading.Thread(target=drive, args=(i,), daemon=True)
            for i in range(n_studies)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        alive = [t for t in threads if t.is_alive()]
        if alive:
            errors.append(f"{len(alive)} study clients timed out")
        wall_s = time.perf_counter() - t0
        stats = service.stats.summary()
        # exact quantiles over the full run (the ring window exceeds
        # the sample count here) — the histogram-derived numbers in
        # ``stats`` are bucket-interpolated, too coarse for A/B deltas
        exact = service.stats.window_quantiles()
        completed = {
            sid: service.study_status(sid)["n_completed"]
            for sid in service.list_studies()
        }
        slo_rules = None
        if slo_gate:
            # one tick so the rule table reflects the whole campaign
            # (tick evaluates and handles breach transitions); the gate
            # reads that same cached evaluation
            service.slo.tick()
            slo_rules = service.slo.evaluate()
        if on_service is not None:
            on_service(service)
    finally:
        server.stop()

    total_suggests = n_studies * n_trials
    occ = stats["mean_batch_occupancy"]
    ok = (
        not errors
        and all(v == n_trials for v in completed.values())
        and occ is not None
        and occ > 1.5
        and stats["n_dispatches"] < stats["n_batched_suggests"]
    )
    if slo_rules is not None:
        ok = ok and all(r["status"] != "breach" for r in slo_rules)
    return {
        "metric": "serve_loadgen",
        "ok": ok,
        "errors": errors,
        "n_studies": n_studies,
        "n_trials_per_study": n_trials,
        "seed": seed,
        "batch_window_s": batch_window,
        "algo_params": ALGO_PARAMS,
        "total_suggest_requests": total_suggests,
        "suggest_p50_ms": stats["suggest_latency"]["p50_ms"],
        "suggest_p99_ms": stats["suggest_latency"]["p99_ms"],
        "suggest_p50_exact_ms": exact["p50_ms"],
        "suggest_p99_exact_ms": exact["p99_ms"],
        # the warm/cold split: first-touch (compile-carrying) vs
        # steady-state, so the blended p99 above is ATTRIBUTED — a
        # 26-second tail next to a 39 ms p50 is cold compiles, and
        # these fields say so instead of leaving it to folklore
        "suggest_warm_p50_ms": stats["suggest_latency_warm"]["p50_ms"],
        "suggest_warm_p99_ms": stats["suggest_latency_warm"]["p99_ms"],
        "suggest_cold_p50_ms": stats["suggest_latency_cold"]["p50_ms"],
        "suggest_cold_p99_ms": stats["suggest_latency_cold"]["p99_ms"],
        "n_warm_suggests": stats["suggest_latency_warm"]["count"],
        "n_cold_suggests": stats["suggest_latency_cold"]["count"],
        "mean_batch_occupancy": occ,
        "n_dispatches": stats["n_dispatches"],
        "n_batched_suggests": stats["n_batched_suggests"],
        "n_inline_suggests": stats["n_inline_suggests"],
        "dispatch_s_total": stats["dispatch_s"],
        "rejected": stats["rejected"],
        "completed_per_study": completed,
        "wall_s": round(wall_s, 3),
        "suggests_per_sec": round(total_suggests / wall_s, 2),
        "platform": _platform(),
        **({"slo": slo_rules} if slo_rules is not None else {}),
    }


def _platform():
    import jax

    return jax.devices()[0].platform


# the default shifting-load profile: a calm warm-up, a surge at 4x the
# concurrency with zero think time, then a taper.  Declarative and
# seeded — the same (profile, seed) pair replays the same request
# schedule, which is what makes the control_report A/B an A/B.
DEFAULT_PROFILE = (
    {"name": "calm", "studies": 2, "trials": 10, "think_s": 0.004},
    {"name": "surge", "studies": 8, "trials": 10, "think_s": 0.0},
    {"name": "taper", "studies": 3, "trials": 10, "think_s": 0.002},
)


def load_profile(spec):
    """Resolve a ``--profile`` operand: ``default`` (or empty) for
    :data:`DEFAULT_PROFILE`, an inline JSON array, or a path to a JSON
    file holding one."""
    if not spec or spec == "default":
        return [dict(p) for p in DEFAULT_PROFILE]
    if spec.lstrip().startswith("["):
        return json.loads(spec)
    with open(spec) as f:
        return json.load(f)


def run_profile(profile=None, seed=0, batch_window=0.004, root=None,
                tracer=None, service_kwargs=None, on_service=None):
    """The shifting-load campaign: run each profile phase's study
    cohort to completion in sequence against ONE server, so the
    arrival rate and concurrency move under the scheduler's feet.
    Each phase is ``{"name", "studies", "trials", "think_s"}`` —
    declarative and fully seeded.  Returns the campaign payload
    (per-phase walls + the same latency headlines as the steady
    loadgen); ``scripts/control_report.py`` replays the identical
    schedule against a static and a self-tuned server."""
    from hyperopt_tpu.fmin import space_eval
    from hyperopt_tpu.service import (
        OptimizationService,
        ServiceClient,
        ServiceServer,
    )

    phases = []
    for i, p in enumerate(profile or DEFAULT_PROFILE):
        p = dict(p)
        unknown = set(p) - {"name", "studies", "trials", "think_s"}
        if unknown:
            raise ValueError(
                f"profile phase {i}: unknown keys {sorted(unknown)}"
            )
        p.setdefault("name", f"phase{i}")
        p["studies"] = int(p.get("studies", 4))
        p["trials"] = int(p.get("trials", 10))
        p["think_s"] = float(p.get("think_s", 0.0))
        phases.append(p)

    space = _space()
    service = OptimizationService(
        root=root, batch_window=batch_window, tracer=tracer,
        **dict(service_kwargs or {}),
    )
    server = ServiceServer(service).start()
    errors = []
    phase_rows = []
    t0 = time.perf_counter()
    try:
        for pi, ph in enumerate(phases):
            pt0 = time.perf_counter()

            def drive(i, ph=ph, pi=pi):
                try:
                    sid = f"{ph['name']}-{i}"
                    client = ServiceClient(server.url)
                    client.create_study(
                        sid, space, seed=seed * 10000 + pi * 100 + i,
                        algo="tpe", algo_params=ALGO_PARAMS,
                    )
                    rng = np.random.default_rng(
                        seed * 10000 + pi * 100 + i
                    )
                    for _ in range(ph["trials"]):
                        (t,) = client.suggest(sid)
                        point = space_eval(space, t["vals"])
                        client.report(
                            sid, t["tid"], loss=_objective(point, rng)
                        )
                        if ph["think_s"]:
                            time.sleep(ph["think_s"])
                except Exception as e:
                    errors.append(f"{ph['name']} study {i}: {e!r}")

            threads = [
                threading.Thread(target=drive, args=(i,), daemon=True)
                for i in range(ph["studies"])
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            if any(t.is_alive() for t in threads):
                errors.append(f"phase {ph['name']}: clients timed out")
            phase_rows.append(
                {**ph, "wall_s": round(time.perf_counter() - pt0, 3)}
            )
        wall_s = time.perf_counter() - t0
        stats = service.stats.summary()
        exact = service.stats.window_quantiles()
        completed = {
            sid: service.study_status(sid)["n_completed"]
            for sid in service.list_studies()
        }
        if on_service is not None:
            on_service(service)
    finally:
        server.stop()

    expected = {
        f"{p['name']}-{i}": p["trials"]
        for p in phases for i in range(p["studies"])
    }
    ok = not errors and all(
        completed.get(s) == n for s, n in expected.items()
    )
    return {
        "metric": "serve_profile",
        "ok": ok,
        "errors": errors,
        "seed": seed,
        "batch_window_s": batch_window,
        "phases": phase_rows,
        "total_suggest_requests": sum(expected.values()),
        "suggest_p50_ms": stats["suggest_latency"]["p50_ms"],
        "suggest_p99_ms": stats["suggest_latency"]["p99_ms"],
        "suggest_p50_exact_ms": exact["p50_ms"],
        "suggest_p99_exact_ms": exact["p99_ms"],
        "suggest_warm_p50_ms": stats["suggest_latency_warm"]["p50_ms"],
        "suggest_warm_p99_ms": stats["suggest_latency_warm"]["p99_ms"],
        "n_warm_suggests": stats["suggest_latency_warm"]["count"],
        "queue_depth_mean": stats.get("queue_depth_mean"),
        "mean_batch_occupancy": stats["mean_batch_occupancy"],
        "n_dispatches": stats["n_dispatches"],
        "completed_per_study": completed,
        "wall_s": round(wall_s, 3),
        "platform": _platform(),
    }


def run_traced(n_studies, n_trials, seed, batch_window, trace_sample,
               trace_slow_ms=None, trace_log=None, overhead_check=False,
               min_coverage=0.9):
    """The traced campaign: run the loadgen with request tracing on,
    aggregate the trace log, and (optionally) measure the tracing-off
    overhead.  Returns (bench_report, trace_report)."""
    import tempfile

    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    import trace_report as trace_report_mod

    from hyperopt_tpu.tracing import Tracer

    if trace_log is None:
        trace_log = os.path.join(
            tempfile.mkdtemp(prefix="hyperopt-trace-"), "trace.jsonl"
        )
    tracer = Tracer(
        path=trace_log,
        sample=trace_sample,
        slow_threshold_s=(
            None if trace_slow_ms is None else trace_slow_ms / 1e3
        ),
    )
    bench = run_loadgen(
        n_studies=n_studies, n_trials=n_trials, seed=seed,
        batch_window=batch_window, tracer=tracer,
    )
    trep = trace_report_mod.report_for_log(
        trace_log, min_coverage=min_coverage
    )
    trep["tracer"] = tracer.summary()
    trep["bench"] = {
        "n_studies": n_studies,
        "n_trials_per_study": n_trials,
        "seed": seed,
        "suggest_p50_ms": bench["suggest_p50_ms"],
        "suggest_p99_ms": bench["suggest_p99_ms"],
        "platform": bench["platform"],
    }
    trep["ok"] = bool(trep["ok"] and bench["ok"])
    if overhead_check:
        # the sampling-off acceptance: a tracer at sample 0 must be a
        # no-op on the hot path (p50 within 5% of a tracer-less run).
        # Interleaved A/B pairs with EXACT (not bucket-interpolated)
        # p50s; min-of-runs per config is the standard noise-robust
        # latency estimator (host jitter only ever adds time).
        base_p50s, off_p50s = [], []
        for _ in range(2):
            base = run_loadgen(
                n_studies=n_studies, n_trials=n_trials, seed=seed,
                batch_window=batch_window, tracer=None,
            )
            base_p50s.append(base["suggest_p50_exact_ms"])
            off = run_loadgen(
                n_studies=n_studies, n_trials=n_trials, seed=seed,
                batch_window=batch_window, tracer=Tracer(sample=0.0),
            )
            off_p50s.append(off["suggest_p50_exact_ms"])
        p50_base, p50_off = min(base_p50s), min(off_p50s)
        trep["overhead"] = {
            "p50_untraced_ms": p50_base,
            "p50_sample0_ms": p50_off,
            "p50_untraced_runs_ms": base_p50s,
            "p50_sample0_runs_ms": off_p50s,
            "p50_regression_frac": (
                round(p50_off / p50_base - 1.0, 4) if p50_base else None
            ),
            "gate_frac": 0.05,
        }
    return bench, trep


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--studies", type=int, default=8)
    ap.add_argument("--trials", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch-window", type=float, default=0.004,
                    dest="batch_window")
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke config (8 studies x 8 trials)")
    ap.add_argument(
        "--profile", nargs="?", const="default", default=None,
        help="shifting-load mode: run a piecewise seeded phase "
             "schedule ('default', an inline JSON array, or a path "
             "to a JSON file of {name, studies, trials, think_s} "
             "phases) instead of the steady campaign",
    )
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BENCH_SERVE.json",
        ),
    )
    ap.add_argument("--trace", action="store_true",
                    help="trace every request and emit TRACE_SERVE.json")
    ap.add_argument("--trace-sample", type=float, default=1.0,
                    dest="trace_sample")
    ap.add_argument("--trace-slow-ms", type=float, default=None,
                    dest="trace_slow_ms")
    ap.add_argument("--trace-log", default=None, dest="trace_log",
                    help="trace log path (default: a fresh tmp dir)")
    ap.add_argument(
        "--trace-out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "TRACE_SERVE.json",
        ),
        dest="trace_out",
    )
    ap.add_argument(
        "--overhead-check", action="store_true", dest="overhead_check",
        help="also run untraced and sample=0 campaigns and report the "
             "p50 regression (the tracing-off-is-free acceptance)",
    )
    ap.add_argument(
        "--slo-gate", action="store_true", dest="slo_gate",
        help="evaluate the SL6xx SLO catalog after the campaign and "
             "fail the exit gate if any rule is breaching (the rule "
             "table lands in the report either way)",
    )
    options = ap.parse_args(argv)
    n_trials = 8 if options.quick else options.trials
    if options.profile is not None:
        profile = load_profile(options.profile)
        if options.quick:
            for p in profile:
                p["trials"] = min(int(p.get("trials", 10)), 4)
        report = run_profile(
            profile=profile, seed=options.seed,
            batch_window=options.batch_window,
        )
        print(json.dumps(report, indent=1))
        # the shifting-load payload is a different metric: never
        # clobber the committed steady-state BENCH_SERVE.json unless
        # the caller pointed --out somewhere on purpose
        return 0 if report["ok"] else 1
    if options.trace:
        report, trep = run_traced(
            n_studies=options.studies,
            n_trials=n_trials,
            seed=options.seed,
            batch_window=options.batch_window,
            trace_sample=options.trace_sample,
            trace_slow_ms=options.trace_slow_ms,
            trace_log=options.trace_log,
            overhead_check=options.overhead_check,
        )
        print(json.dumps(trep, indent=1))
        if options.trace_out:
            with open(options.trace_out, "w") as f:
                json.dump(trep, f, indent=1)
                f.write("\n")
        return 0 if trep["ok"] else 1
    report = run_loadgen(
        n_studies=options.studies,
        n_trials=n_trials,
        seed=options.seed,
        batch_window=options.batch_window,
        slo_gate=options.slo_gate,
    )
    print(json.dumps(report, indent=1))
    if options.out:
        with open(options.out, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
