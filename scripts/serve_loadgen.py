"""Seeded multi-study load generator for the optimization service.

The ISSUE-4 acceptance run: ``--studies`` (default 8) concurrent
studies, each a serial HTTP client driving suggest → simulated
objective → report against ONE in-process server, all seeded.  Emits
``BENCH_SERVE.json`` with the serving headlines:

- ``suggest_p50_ms`` / ``suggest_p99_ms`` — end-to-end suggest latency
  through the HTTP plane (queue wait + batching window + fused device
  program + readback);
- ``mean_batch_occupancy`` — suggest requests per fused device
  dispatch (the continuous-batching win: > 1 means the device ran
  fewer programs than the studies made requests);
- ``n_dispatches`` vs ``n_batched_suggests`` — the dispatch-count
  reduction itself.

Acceptance gate (exit code): every study completes every trial, mean
occupancy > 1.5, and dispatches < device-plane suggest requests.

Usage::

    JAX_PLATFORMS=cpu python scripts/serve_loadgen.py \
        [--studies 8] [--trials 20] [--seed 0] [--quick] [--out BENCH_SERVE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# fast TPE engagement: the startup trials are host-side and don't
# exercise the batching plane this benchmark measures
ALGO_PARAMS = {"n_startup_jobs": 3, "n_EI_candidates": 64}


def _space():
    from hyperopt_tpu import hp

    return {
        "x": hp.uniform("x", -5, 5),
        "lr": hp.loguniform("lr", -5, 0),
        "w": hp.quniform("w", 0, 10, 1),
        "c": hp.choice("c", ["a", "b", "d"]),
    }


def _objective(point, rng):
    """Deterministic-per-draw synthetic objective (no sleep: latency
    under CONTENTION is the point — while one fused program runs, the
    other studies' requests pile into the next batch)."""
    return (
        (point["x"] - 1.0) ** 2
        + (np.log(point["lr"]) + 2.0) ** 2
        + 0.1 * point["w"]
        + (0.5 if point["c"] == "b" else 0.0)
        + float(rng.normal()) * 0.01
    )


def run_loadgen(n_studies=8, n_trials=20, seed=0, batch_window=0.004,
                root=None):
    """Run the seeded campaign; returns the BENCH_SERVE.json payload."""
    from hyperopt_tpu.fmin import space_eval
    from hyperopt_tpu.service import (
        OptimizationService,
        ServiceClient,
        ServiceServer,
    )

    space = _space()
    service = OptimizationService(root=root, batch_window=batch_window)
    server = ServiceServer(service).start()
    errors = []
    t0 = time.perf_counter()
    try:
        def drive(study_idx):
            try:
                sid = f"load-{study_idx}"
                client = ServiceClient(server.url)
                client.create_study(
                    sid, space, seed=seed * 1000 + study_idx,
                    algo="tpe", algo_params=ALGO_PARAMS,
                )
                rng = np.random.default_rng(seed * 1000 + study_idx)
                for _ in range(n_trials):
                    (t,) = client.suggest(sid)
                    point = space_eval(space, t["vals"])
                    client.report(
                        sid, t["tid"], loss=_objective(point, rng)
                    )
            except Exception as e:
                errors.append(f"study {study_idx}: {e!r}")

        threads = [
            threading.Thread(target=drive, args=(i,), daemon=True)
            for i in range(n_studies)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        alive = [t for t in threads if t.is_alive()]
        if alive:
            errors.append(f"{len(alive)} study clients timed out")
        wall_s = time.perf_counter() - t0
        stats = service.stats.summary()
        completed = {
            sid: service.study_status(sid)["n_completed"]
            for sid in service.list_studies()
        }
    finally:
        server.stop()

    total_suggests = n_studies * n_trials
    occ = stats["mean_batch_occupancy"]
    ok = (
        not errors
        and all(v == n_trials for v in completed.values())
        and occ is not None
        and occ > 1.5
        and stats["n_dispatches"] < stats["n_batched_suggests"]
    )
    return {
        "metric": "serve_loadgen",
        "ok": ok,
        "errors": errors,
        "n_studies": n_studies,
        "n_trials_per_study": n_trials,
        "seed": seed,
        "batch_window_s": batch_window,
        "algo_params": ALGO_PARAMS,
        "total_suggest_requests": total_suggests,
        "suggest_p50_ms": stats["suggest_latency"]["p50_ms"],
        "suggest_p99_ms": stats["suggest_latency"]["p99_ms"],
        "mean_batch_occupancy": occ,
        "n_dispatches": stats["n_dispatches"],
        "n_batched_suggests": stats["n_batched_suggests"],
        "n_inline_suggests": stats["n_inline_suggests"],
        "dispatch_s_total": stats["dispatch_s"],
        "rejected": stats["rejected"],
        "completed_per_study": completed,
        "wall_s": round(wall_s, 3),
        "suggests_per_sec": round(total_suggests / wall_s, 2),
        "platform": _platform(),
    }


def _platform():
    import jax

    return jax.devices()[0].platform


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--studies", type=int, default=8)
    ap.add_argument("--trials", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch-window", type=float, default=0.004,
                    dest="batch_window")
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke config (8 studies x 8 trials)")
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BENCH_SERVE.json",
        ),
    )
    options = ap.parse_args(argv)
    n_trials = 8 if options.quick else options.trials
    report = run_loadgen(
        n_studies=options.studies,
        n_trials=n_trials,
        seed=options.seed,
        batch_window=options.batch_window,
    )
    print(json.dumps(report, indent=1))
    if options.out:
        with open(options.out, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
