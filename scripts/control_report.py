"""Proof artifact for the closed-loop control plane → CONTROL_SERVE.json.

Two campaigns of the SAME seeded shifting-load profile
(``scripts/serve_loadgen.py --profile``) against two servers:

- **static** — the control plane off (``control_enabled=False``): the
  scheduler reads the constructor knob values every batch, exactly the
  pre-control service;
- **self_tuned** — ``--self-tune`` on: the background controller runs
  TPE over the serving knobs, scoring each configuration over one
  objective window and reverting to static on any SL6xx breach.

Gates (the exit code, and the ``gates`` block in the artifact):

1. ``p99_no_worse`` — the self-tuned arm's warm suggest p99 is within
   a platform-calibrated tolerance of the static arm's (the controller
   must never cost the latency it exists to protect; warm-only because
   cold compiles are attributed separately per the PR 7 convention);
2. ``zero_breach_transitions`` — no SL6xx rule fired a breach
   transition during the self-tuned campaign;
3. ``decisions_journaled`` — every ``applied`` decision in the
   controller's durable decision journal also appears in the
   flight-recorder ring AND has a matching knob-provenance journal
   entry (source ``controller``) — no unlogged actuation;
4. ``controller_active`` — the loop actually ran (>= 1 decision);
5. ``forced_breach_reverts`` — a deterministic fixture (injected
   breach transition, fake probe) proves the controller reverts to the
   static config within ONE observation window and freezes.

Usage::

    JAX_PLATFORMS=cpu python scripts/control_report.py \
        [--quick] [--seed 0] [--window 1.0] [--out CONTROL_SERVE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)

import serve_loadgen  # noqa: E402

# warm-p99 no-worse tolerance by platform: CPU CI pays seconds-scale
# fused-dispatch contention with run-to-run noise far beyond a TPU's
# (same calibration precedent as the loadgen's SLO bounds), so the
# gate is tight on the hardware that matters and honest about CI
P99_TOLERANCE_FRAC = {"tpu": 0.10, "cpu": 0.50}


def _slo_rules():
    """Platform-calibrated SL6xx rules (the serve_loadgen convention:
    deployment-config bounds, CPU-CI values wide enough that only real
    pathology breaches)."""
    from hyperopt_tpu import slo as slo_mod

    tpu = serve_loadgen._platform() == "tpu"
    return slo_mod.default_rules(
        latency_ratio={"ratio_max": 25.0 if tpu else 100.0},
        latency_absolute={"p99_bound_s": 2.5 if tpu else 10.0},
    )


def forced_breach_fixture(seed=0):
    """Deterministic revert-within-one-window proof: a Controller with
    a fake probe and an injected breach schedule — one clean evaluated
    cycle, then a breach transition lands inside the second applied
    window.  Asserts the SECOND cycle ends reverted-to-static +
    frozen, i.e. the revert happened within that one window."""
    from hyperopt_tpu.control import Controller, KnobSet
    from hyperopt_tpu.control.objective import WindowResult

    knobs = KnobSet(static={
        "batch_window": 0.004, "max_batch": 8,
        "max_queue": 1024, "max_speculation": 0,
    })

    class _FakeProbe:
        def open(self):
            return {"t": 0.0}

        def close(self, opened):
            return WindowResult(
                ok=True, loss=0.1, warm_p99_s=0.1,
                mean_queue_depth=0.0, duty_cycle=0.5,
                warm_count=10, wall_s=0.1,
            )

    # breach_fn is consulted twice per cycle (before/after the window):
    # schedule [0, 0] = clean cycle 1, [0, 1] = transition fires during
    # cycle 2's window
    schedule = iter([0, 0, 0, 1])

    def breach_fn():
        return {"transitions": next(schedule, 1), "breaching": []}

    controller = Controller(
        knobs, _FakeProbe(), seed=seed, window_s=0.0,
        breach_fn=breach_fn,
    )
    out1 = controller.step()
    knobs_moved = not knobs.is_static
    out2 = controller.step()
    reverted = knobs.is_static and controller.frozen
    actions = [d["action"] for d in controller.recent_decisions()]
    out3 = controller.step()  # frozen: no further actuation
    return {
        "cycle1": out1,
        "knobs_moved_in_cycle1": knobs_moved,
        "cycle2": out2,
        "cycle3": out3,
        "decision_actions": actions,
        "windows_to_revert": 1,
        "ok": (
            out1 == "evaluated"
            and knobs_moved
            and out2 == "reverted"
            and reverted
            and out3 == "frozen"
            and actions[-1] == "reverted"
        ),
    }


def _audit_decisions(info):
    """Gate 3: applied decisions ⊆ flight ring ∧ knob journal."""
    decisions = info.get("decisions", [])
    flight = info.get("flight", [])
    journal = info.get("journal", [])
    applied = [d for d in decisions if d["action"] == "applied"]
    flight_seqs = {
        d["seq"] for d in flight if d["action"] == "applied"
    }
    controller_writes = [
        dict(r["changes"]) for r in journal
        if r.get("source") == "controller"
    ]
    missing_flight = [
        d["seq"] for d in applied if d["seq"] not in flight_seqs
    ]
    missing_journal = [
        d["seq"] for d in applied
        if dict(d["knobs"]) not in controller_writes
    ]
    return {
        "n_applied": len(applied),
        "n_controller_journal_writes": len(controller_writes),
        "missing_from_flight_ring": missing_flight,
        "missing_from_knob_journal": missing_journal,
        "ok": not missing_flight and not missing_journal,
    }


def run_ab(profile=None, seed=0, window_s=1.0, batch_window=0.004):
    """The static vs self-tuned A/B under the shifting profile."""
    profile = profile or [dict(p) for p in serve_loadgen.DEFAULT_PROFILE]

    static = serve_loadgen.run_profile(
        profile=profile, seed=seed, batch_window=batch_window,
        service_kwargs={"slo_rules": _slo_rules()},
    )

    tuned_info = {}

    def grab(service):
        tuned_info["decisions"] = (
            service.controller.decision_log_records()
        )
        tuned_info["flight"] = service.controller.recent_decisions()
        tuned_info["journal"] = service.knobs.journal_records()
        tuned_info["status"] = service.controller.status()
        rows = service.slo.evaluate(force=True)
        tuned_info["breach_transitions"] = sum(
            r.get("breaches_total", 0) for r in rows
        )
        tuned_info["breaching"] = [
            r["rule"] for r in rows if not r["ok"]
        ]

    tuned_root = tempfile.mkdtemp(prefix="hyperopt-control-ab-")
    tuned = serve_loadgen.run_profile(
        profile=profile, seed=seed, batch_window=batch_window,
        root=tuned_root, on_service=grab,
        service_kwargs={
            "slo_rules": _slo_rules(),
            "control_enabled": True,
            "control_window_s": window_s,
            "control_interval_s": 0.0,
            "control_seed": seed,
        },
    )

    fixture = forced_breach_fixture(seed=seed)
    audit = _audit_decisions(tuned_info)
    platform = serve_loadgen._platform()
    tol = P99_TOLERANCE_FRAC.get(platform, 0.50)
    p99_static = static["suggest_warm_p99_ms"]
    p99_tuned = tuned["suggest_warm_p99_ms"]
    p99_ok = (
        p99_static is not None and p99_tuned is not None
        and p99_tuned <= p99_static * (1.0 + tol)
    )
    status = tuned_info.get("status", {})
    gates = {
        "p99_no_worse": bool(p99_ok),
        "zero_breach_transitions": (
            tuned_info.get("breach_transitions", 0) == 0
        ),
        "decisions_journaled": audit["ok"],
        "controller_active": status.get("n_decisions", 0) >= 1,
        "forced_breach_reverts": fixture["ok"],
        "both_campaigns_complete": bool(static["ok"] and tuned["ok"]),
    }
    return {
        "metric": "control_serve_ab",
        "ok": all(gates.values()),
        "gates": gates,
        "platform": platform,
        "seed": seed,
        "control_window_s": window_s,
        "p99_tolerance_frac": tol,
        "profile": profile,
        "static": {
            "ok": static["ok"],
            "suggest_warm_p50_ms": static["suggest_warm_p50_ms"],
            "suggest_warm_p99_ms": p99_static,
            "queue_depth_mean": static["queue_depth_mean"],
            "wall_s": static["wall_s"],
        },
        "self_tuned": {
            "ok": tuned["ok"],
            "suggest_warm_p50_ms": tuned["suggest_warm_p50_ms"],
            "suggest_warm_p99_ms": p99_tuned,
            "queue_depth_mean": tuned["queue_depth_mean"],
            "wall_s": tuned["wall_s"],
            "breach_transitions": tuned_info.get("breach_transitions"),
            "breaching": tuned_info.get("breaching"),
            "controller": status,
            "decision_actions": [
                d["action"] for d in tuned_info.get("decisions", [])
            ],
        },
        "decision_audit": audit,
        "forced_breach": fixture,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--window", type=float, default=1.0,
                    help="controller observation window (seconds)")
    ap.add_argument("--batch-window", type=float, default=0.004,
                    dest="batch_window")
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke config (short phases, 0.5s window)")
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(_SCRIPTS), "CONTROL_SERVE.json"
        ),
    )
    options = ap.parse_args(argv)
    profile = [dict(p) for p in serve_loadgen.DEFAULT_PROFILE]
    window_s = options.window
    if options.quick:
        for p in profile:
            p["trials"] = min(int(p["trials"]), 4)
        window_s = min(window_s, 0.5)
    report = run_ab(
        profile=profile, seed=options.seed, window_s=window_s,
        batch_window=options.batch_window,
    )
    print(json.dumps(report, indent=1))
    if options.out:
        with open(options.out, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
