"""Cold start vs warmed restart A/B for the compile plane — the
ISSUE-10 acceptance artifact (``WARMUP_SERVE.json``).

Two REAL server subprocesses over one durable root with a persistent
XLA program cache and the compile ledger:

1. **Cold start** — a fresh root: the campaign's bucket×family program
   grid compiles first-touch (containment on: unwarmed batches are
   served host-side, tagged ``served_cold``, while compiles proceed
   off-thread).  The ledger records every compile with its duration.
2. ``kill -9`` mid-campaign, then **warmed restart** — the new process
   replays the ledger grid through the real dispatch path behind
   ``/readyz`` (programs load from the persistent cache), and the
   campaign's remaining trials run with ZERO request-path compiles.

Every guard is **structural** (ratios, coverage fractions, counts) —
never absolute milliseconds: sandbox latency legitimately swings ~30×
between sessions, but within ONE run the cold and warmed measurements
co-vary.

Report fields the artifact guard pins:

- ``coverage.frac`` — warmup items warmed before ready, as a fraction
  of the cold campaign's observed compile grid (≥ 0.95);
- ``warmed.n_cold_after_ready`` == 0 and SL607 ``breaches_total`` == 0
  on the warmed run (zero request-path compiles after ready);
- ``restart_ratio.warmed_over_cold`` — warmup replay seconds over the
  cold run's total ledger compile seconds (a small fraction);
- ``served_cold.attributed`` — every host-side containment fallback is
  trace-tagged ``served_cold=true`` (sampled at 1.0, so equality);
- ``overhead.p50_regression_frac`` — compile-plane-on steady-state p50
  within 5% of the compile-plane-off baseline (in-process A/B);
- ``warm_tail.ok`` on both runs — warm (steady-state) p99 within the
  platform-calibrated multiple of warm p50 (the ROADMAP acceptance).

Usage::

    JAX_PLATFORMS=cpu python scripts/warmup_report.py [--quick] \
        [--out WARMUP_SERVE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

ALGO_PARAMS = {"n_startup_jobs": 2, "n_EI_candidates": 64}
# warm-tail calibration mirrors serve_loadgen's SLO gate: CPU-backend
# fused dispatches legitimately run ~seconds under contention
WARM_RATIO_MAX = {"tpu": 25.0, "cpu": 100.0}


def _space():
    from hyperopt_tpu import hp

    return {
        "x": hp.uniform("x", -5, 5),
        "lr": hp.loguniform("lr", -5, 0),
        "w": hp.quniform("w", 0, 10, 1),
        "c": hp.choice("c", ["a", "b", "d"]),
    }


def _objective(point, rng):
    return (
        (point["x"] - 1.0) ** 2
        + (np.log(point["lr"]) + 2.0) ** 2
        + 0.1 * point["w"]
        + (0.5 if point["c"] == "b" else 0.0)
        + float(rng.normal()) * 0.01
    )


class Server:
    """One server subprocess with the compile plane fully on."""

    def __init__(self, root, port, log_dir, tag):
        self.root = root
        self.port = port
        self.log_dir = log_dir
        self.tag = tag
        self.proc = None

    def _env(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [REPO] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        )
        env.setdefault("JAX_PLATFORMS", "cpu")
        return env

    def spawn(self):
        log = open(
            os.path.join(self.log_dir, f"server.{self.tag}.log"), "wb"
        )
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "hyperopt_tpu.service",
                "--root", self.root,
                "--port", str(self.port),
                "--batch-window", "0.002",
                "--cold-fallback",
                "--trace-sample", "1.0",
                "--log-level", "INFO",
            ],
            env=self._env(), cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=log,
        )
        return self

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def kill9(self):
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def stop(self):
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.send_signal(signal.SIGTERM)
        try:
            self.proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10)


def _drive_concurrent(client_for, space, sids, n_trials, seed):
    from hyperopt_tpu.fmin import space_eval

    errors = []

    def drive(idx, sid):
        try:
            client = client_for()
            rng = np.random.default_rng(seed * 100 + idx)
            for _ in range(n_trials):
                (t,) = client.suggest(sid)
                point = space_eval(space, t["vals"])
                client.report(sid, t["tid"], loss=_objective(point, rng))
        except Exception as e:
            errors.append(f"{sid}: {e!r}")

    threads = [
        threading.Thread(target=drive, args=(i, sid), daemon=True)
        for i, sid in enumerate(sids)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
    if any(t.is_alive() for t in threads):
        errors.append("campaign thread timed out")
    return errors


def _drive_serial(client, space, sids, n_trials, seed):
    from hyperopt_tpu.fmin import space_eval

    errors = []
    rng = np.random.default_rng(seed + 999)
    for sid in sids:
        try:
            for _ in range(n_trials):
                (t,) = client.suggest(sid)
                point = space_eval(space, t["vals"])
                client.report(sid, t["tid"], loss=_objective(point, rng))
        except Exception as e:
            errors.append(f"{sid}: {e!r}")
    return errors


def _warm_tail(stats, platform):
    warm = stats["suggest_latency_warm"]
    p50, p99 = warm["p50_ms"], warm["p99_ms"]
    bound = WARM_RATIO_MAX[platform if platform in WARM_RATIO_MAX else "cpu"]
    ratio = (p99 / p50) if p50 and p99 else None
    return {
        "warm_p50_ms": p50,
        "warm_p99_ms": p99,
        "n_warm": warm["count"],
        "ratio": round(ratio, 2) if ratio is not None else None,
        "ratio_max": bound,
        # no warm traffic yet (or a floor-level p50) reads ok=None —
        # the artifact guard requires ok is not False
        "ok": (ratio <= bound) if ratio is not None else None,
    }


def _served_cold_from_traces(trace_log):
    from hyperopt_tpu.tracing import read_trace_log

    if not os.path.exists(trace_log):
        return 0
    # read_trace_log folds in the one-deep rotated sibling itself
    records, _torn = read_trace_log(trace_log)
    return sum(
        1 for rec in records
        if (rec.get("root_attrs") or {}).get("served_cold")
    )


def _sl607(alerts):
    for row in alerts["rules"]:
        if row["rule"] == "SL607":
            return row
    return None


def run_report(quick=False, seed=0, workdir=None):
    from hyperopt_tpu.service import ServiceClient
    from hyperopt_tpu.service.server import free_port

    space = _space()
    n_studies = 3 if quick else 4
    # phase-1 trial counts end INSIDE the final power-of-two history
    # bucket so phase 2 (post-restart) stays within it — the warmed
    # restart then needs zero new programs beyond the replayed grid
    phase1_concurrent = 5 if quick else 11
    phase2_trials = 1 if quick else 3
    workdir = workdir or tempfile.mkdtemp(prefix="hyperopt-warmup-")
    root = os.path.join(workdir, "root")
    os.makedirs(root, exist_ok=True)
    port = free_port()
    sids = [f"warm-{i}" for i in range(n_studies)]
    errors = []

    # ---- phase 1: cold start --------------------------------------
    server = Server(root, port, workdir, "cold").spawn()
    t_spawn = time.monotonic()
    client = ServiceClient(server.url, timeout=120)
    client.wait_ready(timeout=300)
    cold_ready_s = time.monotonic() - t_spawn
    for i, sid in enumerate(sids):
        client.create_study(
            sid, space, seed=seed * 1000 + i, algo="tpe",
            algo_params=ALGO_PARAMS,
        )
    errors += _drive_concurrent(
        lambda: ServiceClient(server.url, timeout=120), space, sids,
        phase1_concurrent, seed,
    )
    # serial coda: one solo suggest per study at the final bucket, so
    # the single-study program composition phase 2 will use is in the
    # ledger before the kill
    errors += _drive_serial(client, space, sids, 1, seed)
    # ledger records land at dispatch COMPLETION (compile events fire
    # at trace time) — wait until every observed compile has its
    # ledger record before the kill, or the warmup grid under-covers
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        status_cold = client.service_status()
        n_events = sum(status_cold["stats"]["compile_events"].values())
        if status_cold["compile_ledger"][
            "recorded_this_process"
        ] >= n_events:
            break
        time.sleep(0.25)
    status_cold = client.service_status()
    alerts_cold = client.alerts()
    cold_stats = status_cold["stats"]
    cold_ledger = status_cold["compile_ledger"]
    campaign_grid = sorted(cold_stats["compile_events"])
    server.kill9()
    killed_at = time.monotonic()

    # ---- phase 2: warmed restart ----------------------------------
    server2 = Server(root, port, workdir, "warm").spawn()
    t_spawn2 = time.monotonic()
    client2 = ServiceClient(server2.url, timeout=120)
    ready_doc = client2.wait_ready(timeout=600)
    warmed_ready_s = time.monotonic() - t_spawn2
    warmup_doc = client2.warmup()
    warmed_keys = sorted({
        f"{i['bucket']}/{i['families']}"
        for i in warmup_doc["items"] if i["state"] == "warm"
    })
    covered = [k for k in campaign_grid if k in warmed_keys]
    coverage_frac = (
        len(covered) / len(campaign_grid) if campaign_grid else None
    )
    errors += _drive_serial(client2, space, sids, phase2_trials, seed)
    status_warm = client2.service_status()
    alerts_warm = client2.alerts()
    warm_stats = status_warm["stats"]
    platform = status_warm["version"]["backend"]
    server2.stop()
    restart_dead_s = round(t_spawn2 - killed_at, 3)

    # ---- attribution + ratios -------------------------------------
    n_fallbacks = (
        cold_stats["n_cold_fallbacks"] + warm_stats["n_cold_fallbacks"]
    )
    n_tagged = _served_cold_from_traces(os.path.join(root, "trace.jsonl"))
    warmup_replay_s = warmup_doc.get("elapsed_s")
    cold_compile_s = cold_ledger["total_compile_s"]
    ratio = (
        round(warmup_replay_s / cold_compile_s, 4)
        if warmup_replay_s is not None and cold_compile_s else None
    )
    sl607_warm = _sl607(alerts_warm)
    warm_tail_cold = _warm_tail(cold_stats, platform)
    warm_tail_warm = _warm_tail(warm_stats, platform)

    # ---- overhead A/B (in-process, exact p50s, min-of-pairs) -------
    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    import serve_loadgen

    on_p50s, off_p50s = [], []
    ab_trials = 6 if quick else 10
    # two throwaway passes first: in-process programs (and the delta-
    # append programs of a fresh history) compile here, so neither
    # timed arm pays first-touch; pairs ALTERNATE order (a fixed order
    # correlates each arm with drifting system load) and min-of-runs
    # is the noise-robust estimator (jitter only ever adds time)
    for _ in range(2):
        serve_loadgen.run_loadgen(
            n_studies=4, n_trials=ab_trials, seed=seed
        )
    for i in range(3):
        arms = ("off", "on") if i % 2 == 0 else ("on", "off")
        for arm in arms:
            kwargs = (
                {} if arm == "on"
                else {"service_kwargs": {"compile_plane": False}}
            )
            r = serve_loadgen.run_loadgen(
                n_studies=4, n_trials=ab_trials, seed=seed, **kwargs
            )
            (on_p50s if arm == "on" else off_p50s).append(
                r["suggest_p50_exact_ms"]
            )
    p50_on, p50_off = min(on_p50s), min(off_p50s)
    overhead = {
        "p50_compile_plane_on_ms": p50_on,
        "p50_compile_plane_off_ms": p50_off,
        "p50_on_runs_ms": on_p50s,
        "p50_off_runs_ms": off_p50s,
        "p50_regression_frac": (
            round(p50_on / p50_off - 1.0, 4) if p50_off else None
        ),
        "gate_frac": 0.05,
    }

    zero_cold = warm_stats["n_cold_after_ready"] == 0
    sl607_clean = (
        sl607_warm is not None and sl607_warm["breaches_total"] == 0
        and sl607_warm["status"] != "breach"
    )
    ok = (
        not errors
        and coverage_frac is not None and coverage_frac >= 0.95
        and zero_cold
        and sl607_clean
        # True required (None = no warm traffic, which the campaign
        # sizes preclude — and the artifact guard asserts True too)
        and ratio is not None and ratio < 0.85
        and n_tagged == n_fallbacks
        and warm_tail_cold["ok"] is True
        and warm_tail_warm["ok"] is True
        and (
            overhead["p50_regression_frac"] is not None
            and overhead["p50_regression_frac"] < 0.05
        )
    )
    return {
        "metric": "warmup_serve",
        "ok": bool(ok),
        "quick": bool(quick),
        "errors": errors,
        "platform": platform,
        "n_studies": n_studies,
        "phase1_trials_per_study": phase1_concurrent + 1,
        "phase2_trials_per_study": phase2_trials,
        "algo_params": ALGO_PARAMS,
        "cold": {
            "spawn_to_ready_s": round(cold_ready_s, 3),
            "n_compile_events": sum(
                cold_stats["compile_events"].values()
            ),
            "compile_grid": campaign_grid,
            "ledger": cold_ledger,
            "n_cold_fallbacks": cold_stats["n_cold_fallbacks"],
            "warm_tail": warm_tail_cold,
            "slo_breaching": status_cold["slo_breaching"],
        },
        "warmed": {
            "spawn_to_ready_s": round(warmed_ready_s, 3),
            "restart_gap_s": restart_dead_s,
            "warmup": {
                k: v for k, v in warmup_doc.items() if k != "items"
            },
            "warmup_items": warmup_doc["items"],
            "n_cold_after_ready": warm_stats["n_cold_after_ready"],
            "n_cold_suggests": warm_stats["n_cold_suggests"],
            "n_cold_fallbacks": warm_stats["n_cold_fallbacks"],
            "compile_events": warm_stats["compile_events"],
            "cache_events": status_warm["compile_ledger"][
                "cache_events"
            ],
            "warm_tail": warm_tail_warm,
            "sl607": sl607_warm,
            "ready_doc_warmup": ready_doc.get("warmup"),
        },
        "coverage": {
            "campaign_grid": campaign_grid,
            "warmed_before_ready": warmed_keys,
            "covered": covered,
            "frac": (
                round(coverage_frac, 4)
                if coverage_frac is not None else None
            ),
            "gate": 0.95,
        },
        "restart_ratio": {
            "warmup_replay_s": warmup_replay_s,
            "cold_compile_s": cold_compile_s,
            "warmed_over_cold": ratio,
            "gate": 0.85,
        },
        "served_cold": {
            "n_fallbacks": n_fallbacks,
            "n_trace_tagged": n_tagged,
            "attributed": n_tagged == n_fallbacks,
        },
        "overhead": overhead,
        "workdir": workdir,
    }


def write_report(report, out_path):
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--out", default=os.path.join(REPO, "WARMUP_SERVE.json")
    )
    options = ap.parse_args(argv)
    report = run_report(quick=options.quick, seed=options.seed)
    print(json.dumps(report, indent=1))
    if options.out:
        write_report(report, options.out)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
