"""Seeded chaos harness: deterministic, reproducible fault injection.

Testing a fault-tolerance layer against real faults is flaky by
construction; this harness makes the faults themselves reproducible.
Every injection decision is a pure function of
``(seed, site, key, occurrence)`` — SHA-256 hashed to a uniform draw
compared against the site's probability — where ``occurrence`` counts
how many times that exact ``(site, key)`` has rolled.  Re-running a
campaign with the same seed therefore injects the same faults at the
same logical points (trial 7's first execution, the third device
dispatch, ...), regardless of wall-clock timing or which worker thread
got the job, and a trial that retries after an injected fault rolls a
*fresh* occurrence — so transient faults stay transient.

Injected fault classes (ISSUE archetype list):

- **worker kill mid-trial** — :class:`WorkerKilled` raised inside
  ``FileWorker.run_one`` outside its error-writing path: the trial
  stays RUNNING with its lock and lease in place, exactly like a
  SIGKILL'd process.  Recovery: lease expiry → reaper reclamation.
- **torn/stale lock files** — garbage bytes written to a fresh trial's
  lock path at insert time (a worker that died inside its lock write).
  Recovery: the reaper's stale-lock GC.
- **delayed / duplicated results** — a full-process stall (heartbeat
  paused with the worker, modelling a VM freeze / stop-the-world pause)
  before, or a second idempotent write after, the worker's final doc
  write.  Recovery: the lease-ownership/expiry re-check drops genuinely
  stale writes (when the stall exceeds the TTL the reaper reclaims and
  re-queues); duplicates are idempotent by construction.
- **objective exceptions / NaNs / hangs** — raised/returned/slept inside
  the objective.  Recovery: retry policy (backoff + watchdog timeout),
  quarantine past ``max_attempts``; NaN losses are NaN-safe in the TPE
  fit.
- **synthetic device errors** — :class:`SyntheticDeviceError` raised
  from a ``tpe_device`` suggest-dispatch observer.  Recovery:
  :class:`~hyperopt_tpu.resilience.device.DeviceRecovery` re-init / CPU
  fallback; the speculative engine discards and re-issues cleanly.

Service-plane fault classes (ISSUE 5), aimed at the optimization
server's HTTP edge and its crash-consistent store:

- **server SIGKILL mid-batch** — the chaos-serve campaign's supervisor
  rolls ``should_kill_server`` per completed trial and ``kill -9``s the
  server process at the hits.  Recovery: startup fsck + response-journal
  replay + seed-cursor re-verification; clients retry through the
  outage with idempotency keys.
- **connection reset before/after response commit** — the HTTP handler
  drops the connection without a response, either before any state
  change (client retry is trivially safe) or after the journal+store
  commit (client retry replays the journaled response byte-for-byte).
- **torn doc / torn journal writes** — a trial doc is truncated in
  place after its atomic write (latent disk corruption discovered at
  the next read/restart: the CRC trailer detects it and quarantines),
  or the append-only response journal loses the tail of its last
  record (the per-line CRC detects it; replay of a lost tail entry is
  safe because the entry's effects had not landed either).
- **slow-loris clients** — the campaign parks sockets that trickle a
  request forever; the handler's read timeout bounds the damage to one
  handler thread per socket.

Replica-plane fault classes (ISSUE 13), aimed at the multi-replica
serving topology's ownership leases and routing:

- **owning-replica SIGKILL** — the failover campaign's supervisor rolls
  ``should_kill_replica`` per progress tick and ``kill -9``s the
  replica owning the watched studies.  Recovery: lease expiry →
  fencing-token claim → fsck-clean takeover → compile-ledger pre-warm
  on a surviving replica; clients ride through on ring failover.
- **lease-renewal stall** — the replica's heartbeat thread freezes past
  the lease TTL (``maybe_lease_stall``), modelling a stop-the-world
  pause: the study is reclaimed while the holder still *thinks* it
  owns it.  Recovery: the resumed holder's writes are stale-fenced and
  dropped; its next heartbeat discovers the bumped fence and
  relinquishes.
- **asymmetric partition** — ``maybe_client_partition`` opens a window
  during which the HTTP layer drops every client connection while the
  replica's store-side heartbeats keep running (client↔replica dead,
  replica↔store alive).  No failover fires — the lease stays warm —
  so redirects + client-side ring failover alone must carry traffic.

Every service-plane injection can be appended to a crash-surviving
``injection_log`` (``O_APPEND``, CRC-framed records via
``tracing.format_record`` — the same journal discipline as the response
journal and trace log) so a campaign can reconcile injected-fault
counts across server kills.

Activate with :func:`active` (a context manager setting the process-wide
monkey); the production code paths cost one ``sys.modules`` lookup when
the harness was never imported.  Every injection is counted in the
monkey's :class:`~hyperopt_tpu.observability.FaultStats` under
``chaos_<site>`` keys, which the campaign report reconciles against the
recovery counters.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import threading
import time
import zlib
from collections import defaultdict
from dataclasses import dataclass

from ..observability import FaultStats
from ..tracing import current_trace_id
from .device import SyntheticDeviceError

logger = logging.getLogger(__name__)


class WorkerKilled(Exception):
    """Chaos-injected worker death: propagate without touching the
    queue (the trial must look exactly like its worker was SIGKILL'd)."""


class ChaosObjectiveError(RuntimeError):
    """Chaos-injected transient objective failure."""


@dataclass(frozen=True)
class ChaosConfig:
    """Per-site injection probabilities (0 disables a site) + the seed.

    ``hang_seconds`` should exceed the run's ``trial_timeout`` for hangs
    to be *observable* faults; ``delay_seconds`` should exceed the lease
    TTL for delays to exercise the stale-result drop (below it they are
    harmless slow writes)."""

    seed: int = 0
    p_worker_kill: float = 0.0
    p_torn_lock: float = 0.0
    p_result_delay: float = 0.0
    p_result_duplicate: float = 0.0
    p_objective_error: float = 0.0
    p_objective_nan: float = 0.0
    p_objective_hang: float = 0.0
    p_device_error: float = 0.0
    hang_seconds: float = 1.0
    delay_seconds: float = 0.5
    # service-plane sites (chaos-serve campaign)
    p_server_kill: float = 0.0
    p_conn_reset_pre: float = 0.0
    p_conn_reset_post: float = 0.0
    p_torn_doc: float = 0.0
    p_torn_journal: float = 0.0
    p_slow_loris: float = 0.0
    # segmented-store sites (segment log campaign)
    p_torn_segment: float = 0.0     # clip the tail off a segment append
    p_compaction_kill: float = 0.0  # SIGKILL inside the compaction window
    # replica-plane sites (failover campaign, ISSUE 13)
    p_replica_kill: float = 0.0     # supervisor SIGKILLs the owning replica
    p_lease_stall: float = 0.0      # heartbeat frozen past the lease TTL
    lease_stall_seconds: float = 3.0
    p_client_partition: float = 0.0  # client<->replica dead, replica<->store alive
    partition_seconds: float = 2.0
    # crash-consistent tears: a REAL torn write only damages data whose
    # fsync never returned — i.e. it happens AT a crash, and the write
    # was never acknowledged downstream.  With this flag (the default)
    # a torn doc/journal site tears the file and then SIGKILLs its own
    # process mid-write, exactly that semantics.  False gives a plain
    # in-place tear (a lying disk) for unit tests of the detectors —
    # a model under which NO single-copy store can avoid data loss once
    # both the doc and its journal record rot independently.
    tear_kills_process: bool = True
    # crash-surviving injection record (JSONL, appended O_APPEND): lets
    # a campaign count injections made by a process that was later
    # SIGKILL'd.  None disables.
    injection_log: str | None = None

    def to_json(self) -> str:
        return json.dumps(
            {f: getattr(self, f) for f in self.__dataclass_fields__},
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, blob: str) -> "ChaosConfig":
        d = json.loads(blob)
        known = {
            k: v for k, v in d.items() if k in cls.__dataclass_fields__
        }
        return cls(**known)


def parse_injection_log(raw: bytes) -> list:
    """Records from raw injection-log bytes.

    Records are CRC-framed (``tracing.format_record``: ``\\n<crc32 hex>
    <json>``); bare-JSON lines written by pre-framing versions of this
    module are still accepted, so an upgraded server replays its old
    log.  Torn lines (a SIGKILL mid-append) are skipped — the frame
    makes them detectable rather than silently half-parsed."""
    records = []
    for line in raw.split(b"\n"):
        line = line.strip()
        if not line:
            continue
        rec = None
        try:
            crc_hex, body = line.split(b" ", 1)
            if (zlib.crc32(body) & 0xFFFFFFFF) == int(crc_hex, 16):
                rec = json.loads(body.decode())
        except (ValueError, json.JSONDecodeError, UnicodeDecodeError):
            rec = None
        if rec is None:
            try:
                rec = json.loads(line.decode())  # legacy unframed line
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue  # the torn tail of a mid-append SIGKILL
        if isinstance(rec, dict):
            records.append(rec)
    return records


def stable_key(cfg) -> str:
    """Deterministic key for an objective's config dict (the same
    suggested point maps to the same key in every run)."""
    if isinstance(cfg, dict):
        return repr(sorted((str(k), repr(v)) for k, v in cfg.items()))
    return repr(cfg)


class ChaosMonkey:
    """One seeded fault-injection schedule + its accounting."""

    # lock-order: _roll_lock
    def __init__(self, config: ChaosConfig, stats: FaultStats | None = None):
        self.config = config
        self.stats = stats if stats is not None else FaultStats()
        self._roll_lock = threading.Lock()
        self._occurrence = defaultdict(int)  # guarded-by: _roll_lock
        # open client-partition windows (replica_id -> deadline epoch)
        self._partition_lock = threading.Lock()
        self._partition_until = {}  # guarded-by: _partition_lock
        # replicas whose ONE window already opened (see
        # maybe_client_partition: at most one window per replica per
        # monkey, or a p=1.0 campaign would re-open the window on every
        # request and blackhole the fleet forever)
        self._partition_opened = set()  # guarded-by: _partition_lock
        self._installed_observer = None
        # bounded ring of the most recent injections (log path or not)
        # — the flight recorder's chaos-correlation evidence; deque
        # appends are GIL-atomic, snapshots copy via list()
        from collections import deque

        self._recent = deque(maxlen=256)
        self._replay_injection_log()

    def recent_injections(self) -> list:
        """The last injections as record dicts, oldest first (a
        snapshot) — pulled by the flight recorder at dump time."""
        return [dict(r) for r in list(self._recent)]

    def _replay_injection_log(self):
        """Restore occurrence counters from the crash-surviving log.

        "Transient faults stay transient" must hold across process
        death too: a tear site that SIGKILLs its own process would
        otherwise re-roll the retried write at occurrence 0 in the
        restarted server — same hash, same hit, a deterministic crash
        loop.  Replaying the log advances each ``(site, key)`` past its
        already-injected occurrences, so the retry rolls fresh."""
        if not self.config.injection_log:
            return
        try:
            with open(self.config.injection_log, "rb") as f:
                raw = f.read()
        except OSError:
            return
        with self._roll_lock:
            for rec in parse_injection_log(raw):
                try:
                    site, key = rec["site"], rec["key"]
                    occ = int(rec["occurrence"])
                except (KeyError, TypeError, ValueError):
                    continue
                if self._occurrence[(site, key)] <= occ:
                    self._occurrence[(site, key)] = occ + 1

    # -- the deterministic roll ----------------------------------------
    def _roll(self, site: str, key, p: float) -> bool:
        if p <= 0.0:
            return False
        # occurrence is tracked by the key's STRING form — the hash
        # below already stringifies, and the injection-log replay can
        # then restore counters across a process death
        skey = str(key)
        with self._roll_lock:
            occ = self._occurrence[(site, skey)]
            self._occurrence[(site, skey)] = occ + 1
        h = hashlib.sha256(
            f"{self.config.seed}:{site}:{key}:{occ}".encode()
        ).digest()
        hit = int.from_bytes(h[:8], "big") / 2 ** 64 < p
        if hit:
            self.stats.record(f"chaos_{site}")
            self._log_injection(site, skey, occ)
        return hit

    def _log_injection(self, site, key, occ):
        """Append one injection record to the crash-surviving log.
        One CRC-framed ``O_APPEND`` write (``tracing.format_record``):
        a SIGKILL mid-append tears at most the final record, and the
        frame makes the tear detectable instead of a parse guess.

        The active request-trace id (if the injecting thread is inside
        a traced request) is stamped into the record, so a fault in a
        ``CHAOS_SERVE.json`` campaign can be joined to the exact trace
        it perturbed — "this p99 outlier ate a torn-journal injection"
        becomes a log join instead of a guess."""
        record = {
            "site": site, "key": str(key), "occurrence": occ,
            "trace_id": current_trace_id(),
        }
        self._recent.append(record)
        if not self.config.injection_log:
            return
        from .. import journal_io

        try:
            # advisory log: no fsync — losing the final record at a
            # crash is exactly the torn tail the frame detects
            journal_io.append_record(
                self.config.injection_log, record, fsync=False
            )
        except OSError:
            logger.warning("could not append injection log", exc_info=True)

    # -- worker-plane sites --------------------------------------------
    def maybe_kill_worker(self, tid, where: str = "mid"):
        """Raise :class:`WorkerKilled` per the schedule.  ``where``
        distinguishes kill points (before vs. after the objective) so
        each rolls independently."""
        if self._roll("worker_kill", (int(tid), where),
                      self.config.p_worker_kill):
            logger.info("chaos: killing worker at trial %s (%s)", tid, where)
            raise WorkerKilled(f"chaos kill at trial {tid} ({where})")

    def should_delay_result(self, tid) -> bool:
        """Roll the result_delay site.  The WORKER implements the stall
        (pausing its heartbeat for the sleep) so the fault models a
        frozen process — otherwise the heartbeat thread would keep the
        lease warm and a delay could never exercise the stale-result
        drop, however long."""
        return self._roll("result_delay", int(tid),
                          self.config.p_result_delay)

    def should_duplicate_result(self, tid) -> bool:
        return self._roll(
            "result_duplicate", int(tid), self.config.p_result_duplicate
        )

    # -- queue-plane sites ---------------------------------------------
    def maybe_torn_lock(self, jobs, tid):
        """Write garbage to ``tid``'s lock path (iff currently unlocked):
        a worker that died inside its lock write."""
        if not self._roll("torn_lock", int(tid), self.config.p_torn_lock):
            return
        lock = jobs.lock_path(tid)
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return
        with os.fdopen(fd, "wb") as f:
            f.write(b"\x00torn\x00")  # never a valid owner string
        logger.info("chaos: tore lock file for trial %s", tid)

    # -- objective-plane sites -----------------------------------------
    def objective_fault(self, key):
        """Inject at one objective evaluation.  May sleep (hang), raise
        (:class:`ChaosObjectiveError`), or return ``float('nan')`` to be
        used as the loss; returns None when nothing fired."""
        if self._roll("objective_hang", key, self.config.p_objective_hang):
            logger.info("chaos: hanging objective (%.2fs)",
                        self.config.hang_seconds)
            time.sleep(self.config.hang_seconds)
        if self._roll("objective_error", key, self.config.p_objective_error):
            raise ChaosObjectiveError(f"chaos objective error at {key!r}")
        if self._roll("objective_nan", key, self.config.p_objective_nan):
            return float("nan")
        return None

    def wrap_objective(self, fn):
        """In-process convenience: ``fn`` with faults injected per point.
        (Out-of-process workers can't unpickle a closure — they call
        :func:`objective_fault` from a module-level objective instead.)"""

        def chaotic(cfg):
            fault = self.objective_fault(stable_key(cfg))
            if fault is not None:
                return fault
            return fn(cfg)

        return chaotic

    # -- service-plane sites -------------------------------------------
    @staticmethod
    def _tear_file(path, drop_bytes=None):
        """Truncate ``path`` in place — the on-disk shape of a write the
        kernel never finished.  ``drop_bytes=None`` halves the file (a
        torn doc); a positive value clips just the tail (a torn
        append)."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return False
        keep = size // 2 if drop_bytes is None else max(
            0, size - int(drop_bytes)
        )
        if keep >= size:
            keep = max(0, size - 1)
        try:
            with open(path, "r+b") as f:
                f.truncate(keep)
        except OSError:
            return False
        return True

    def _die_mid_write(self):
        """SIGKILL our own process — the write we just tore is now a
        write the crash interrupted, never one the caller acknowledged."""
        import signal as _signal

        os.kill(os.getpid(), _signal.SIGKILL)

    def maybe_torn_doc(self, path, tid):
        """Tear a just-written trial doc: the CRC trailer detects it at
        the next read and fsck quarantines/restores it.  With
        ``tear_kills_process`` (default) the process dies mid-write —
        the crash-consistent torn write."""
        if not self._roll("torn_doc", int(tid), self.config.p_torn_doc):
            return
        if self._tear_file(path):
            logger.info("chaos: tore doc for trial %s", tid)
            if self.config.tear_kills_process:
                self._die_mid_write()

    def maybe_torn_journal(self, path, key):
        """Clip the tail off the append-only response journal — a torn
        final append.  The per-line CRC detects it; with
        ``tear_kills_process`` (default) the process dies mid-append, so
        the lost record is by construction one no client was answered
        for."""
        if not self._roll("torn_journal", key, self.config.p_torn_journal):
            return
        if self._tear_file(path, drop_bytes=7):
            logger.info("chaos: tore journal tail at %s", path)
            if self.config.tear_kills_process:
                self._die_mid_write()

    def maybe_torn_segment(self, path, key):
        """Clip the tail off a just-appended segment — the torn group
        commit.  The incremental chunk parser leaves the invalid tail
        unconsumed on an active segment and counts it torn once sealed;
        with ``tear_kills_process`` (default) the process dies
        mid-append, so the lost batch was never acknowledged."""
        if not self._roll(
            "torn_segment", int(key), self.config.p_torn_segment
        ):
            return
        if self._tear_file(path, drop_bytes=11):
            logger.info("chaos: tore segment tail at %s", path)
            if self.config.tear_kills_process:
                self._die_mid_write()

    def maybe_compaction_kill(self, segments_dir, epoch):
        """SIGKILL inside compaction's vulnerable window: the new
        manifest (epoch N+1) is published but the retired epoch-N
        segments are not yet unlinked — recovery must replay the folded
        base and fsck FS412 must sweep the orphans."""
        if not self._roll(
            "compaction_kill", int(epoch), self.config.p_compaction_kill
        ):
            return
        logger.info(
            "chaos: killing mid-compaction (epoch %s) in %s",
            epoch, segments_dir,
        )
        self._die_mid_write()

    def should_reset_connection(self, route: str, key, when: str) -> bool:
        """Roll a connection-reset site.  ``when`` is ``"pre"`` (drop
        before any state change) or ``"post"`` (drop after the
        journal+store commit, before the response bytes leave)."""
        p = (
            self.config.p_conn_reset_pre
            if when == "pre"
            else self.config.p_conn_reset_post
        )
        return self._roll(f"conn_reset_{when}", (route, key), p)

    def should_kill_server(self, key) -> bool:
        """One supervisor roll of the server-SIGKILL site (the campaign
        rolls once per completed trial and kills at the hits)."""
        return self._roll("server_kill", key, self.config.p_server_kill)

    def should_slow_loris(self, key) -> bool:
        return self._roll("slow_loris", key, self.config.p_slow_loris)

    # -- replica-plane sites -------------------------------------------
    def should_kill_replica(self, replica_id) -> bool:
        """One supervisor roll of the owning-replica SIGKILL site (the
        failover campaign rolls per progress tick against the replica
        that currently OWNS the watched studies and ``kill -9``s it at
        the hits).  Recovery: lease expiry → fencing claim → fsck-clean
        takeover → ledger pre-warm on the surviving replica."""
        return self._roll(
            "replica_kill", str(replica_id), self.config.p_replica_kill
        )

    def maybe_lease_stall(self, replica_id) -> float:
        """Roll the lease-renewal stall site: a hit returns the stall
        duration (seconds) and the replica's heartbeat thread FREEZES
        for it — renewals stop with the lease left in place, modelling
        a stop-the-world-paused holder.  ``lease_stall_seconds`` should
        exceed the replica lease TTL for the stall to be an observable
        fault (the study is reclaimed; the stalled holder's resumed
        writes are stale-fenced and dropped)."""
        if self._roll(
            "lease_stall", str(replica_id), self.config.p_lease_stall
        ):
            logger.info(
                "chaos: stalling lease heartbeat of %s for %.2fs",
                replica_id, self.config.lease_stall_seconds,
            )
            return float(self.config.lease_stall_seconds)
        return 0.0

    def maybe_client_partition(self, replica_id):
        """Roll the asymmetric-partition site: a hit opens a
        ``partition_seconds`` window during which the HTTP layer drops
        EVERY client connection to this replica while its store-side
        heartbeats keep running (client↔replica dead, replica↔store
        alive).  No failover fires — the lease stays warm — so the
        traffic must ride on client-side ring failover + redirects.

        At most ONE window opens per replica per monkey: the site is
        rolled per request, and a per-request re-roll at p=1.0 would
        otherwise re-open the window forever and model a permanent
        outage instead of a partition EVENT.  Re-arm by constructing a
        fresh monkey (the campaign does, one per scenario)."""
        rid = str(replica_id)
        with self._partition_lock:
            if rid in self._partition_opened:
                return
        if self._roll(
            "client_partition", rid, self.config.p_client_partition,
        ):
            until = time.time() + float(self.config.partition_seconds)
            with self._partition_lock:
                if rid in self._partition_opened:
                    return  # lost the race to a concurrent request
                self._partition_opened.add(rid)
                self._partition_until[rid] = until
            logger.info(
                "chaos: client partition of %s for %.2fs",
                replica_id, self.config.partition_seconds,
            )

    def client_partitioned(self, replica_id) -> bool:
        with self._partition_lock:
            until = self._partition_until.get(str(replica_id), 0.0)
        return time.time() < until

    # -- device-plane site ---------------------------------------------
    def maybe_device_error(self):
        """Roll the device-error site once (one suggest dispatch)."""
        if self._roll("device_error", "dispatch", self.config.p_device_error):
            raise SyntheticDeviceError("chaos device error at dispatch")

    def install_device_faults(self):
        """Register a ``tpe_device`` suggest observer that raises
        :class:`SyntheticDeviceError` per the schedule (undone by
        :func:`active`'s exit or :meth:`uninstall_device_faults`)."""
        if self.config.p_device_error <= 0 or self._installed_observer:
            return
        from ..algos import tpe_device

        def _observer(requests):
            self.maybe_device_error()

        tpe_device._suggest_observers.append(_observer)
        self._installed_observer = _observer

    def uninstall_device_faults(self):
        if self._installed_observer is None:
            return
        from ..algos import tpe_device

        try:
            tpe_device._suggest_observers.remove(self._installed_observer)
        except ValueError:
            pass
        self._installed_observer = None


# -- process-wide activation -------------------------------------------
#
# Production call sites (worker.py, file_trials.py) look the monkey up
# through ``sys.modules`` so a run that never imported the chaos harness
# pays one dict miss, not an import.

_active_lock = threading.Lock()
_active_monkey: ChaosMonkey | None = None


def get_active() -> ChaosMonkey | None:
    return _active_monkey


@contextlib.contextmanager
def active(monkey: ChaosMonkey):
    """Make ``monkey`` the process-wide chaos source for the block (and
    register its device-fault observer when configured).  Nested
    activation is refused — overlapping schedules would not be
    reproducible."""
    global _active_monkey
    with _active_lock:
        if _active_monkey is not None:
            raise RuntimeError("a chaos monkey is already active")
        _active_monkey = monkey
    monkey.install_device_faults()
    try:
        yield monkey
    finally:
        monkey.uninstall_device_faults()
        with _active_lock:
            _active_monkey = None
