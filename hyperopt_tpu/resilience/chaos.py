"""Seeded chaos harness: deterministic, reproducible fault injection.

Testing a fault-tolerance layer against real faults is flaky by
construction; this harness makes the faults themselves reproducible.
Every injection decision is a pure function of
``(seed, site, key, occurrence)`` — SHA-256 hashed to a uniform draw
compared against the site's probability — where ``occurrence`` counts
how many times that exact ``(site, key)`` has rolled.  Re-running a
campaign with the same seed therefore injects the same faults at the
same logical points (trial 7's first execution, the third device
dispatch, ...), regardless of wall-clock timing or which worker thread
got the job, and a trial that retries after an injected fault rolls a
*fresh* occurrence — so transient faults stay transient.

Injected fault classes (ISSUE archetype list):

- **worker kill mid-trial** — :class:`WorkerKilled` raised inside
  ``FileWorker.run_one`` outside its error-writing path: the trial
  stays RUNNING with its lock and lease in place, exactly like a
  SIGKILL'd process.  Recovery: lease expiry → reaper reclamation.
- **torn/stale lock files** — garbage bytes written to a fresh trial's
  lock path at insert time (a worker that died inside its lock write).
  Recovery: the reaper's stale-lock GC.
- **delayed / duplicated results** — a full-process stall (heartbeat
  paused with the worker, modelling a VM freeze / stop-the-world pause)
  before, or a second idempotent write after, the worker's final doc
  write.  Recovery: the lease-ownership/expiry re-check drops genuinely
  stale writes (when the stall exceeds the TTL the reaper reclaims and
  re-queues); duplicates are idempotent by construction.
- **objective exceptions / NaNs / hangs** — raised/returned/slept inside
  the objective.  Recovery: retry policy (backoff + watchdog timeout),
  quarantine past ``max_attempts``; NaN losses are NaN-safe in the TPE
  fit.
- **synthetic device errors** — :class:`SyntheticDeviceError` raised
  from a ``tpe_device`` suggest-dispatch observer.  Recovery:
  :class:`~hyperopt_tpu.resilience.device.DeviceRecovery` re-init / CPU
  fallback; the speculative engine discards and re-issues cleanly.

Activate with :func:`active` (a context manager setting the process-wide
monkey); the production code paths cost one ``sys.modules`` lookup when
the harness was never imported.  Every injection is counted in the
monkey's :class:`~hyperopt_tpu.observability.FaultStats` under
``chaos_<site>`` keys, which the campaign report reconciles against the
recovery counters.
"""

from __future__ import annotations

import contextlib
import hashlib
import logging
import threading
import time
from collections import defaultdict
from dataclasses import dataclass

from ..observability import FaultStats
from .device import SyntheticDeviceError

logger = logging.getLogger(__name__)


class WorkerKilled(Exception):
    """Chaos-injected worker death: propagate without touching the
    queue (the trial must look exactly like its worker was SIGKILL'd)."""


class ChaosObjectiveError(RuntimeError):
    """Chaos-injected transient objective failure."""


@dataclass(frozen=True)
class ChaosConfig:
    """Per-site injection probabilities (0 disables a site) + the seed.

    ``hang_seconds`` should exceed the run's ``trial_timeout`` for hangs
    to be *observable* faults; ``delay_seconds`` should exceed the lease
    TTL for delays to exercise the stale-result drop (below it they are
    harmless slow writes)."""

    seed: int = 0
    p_worker_kill: float = 0.0
    p_torn_lock: float = 0.0
    p_result_delay: float = 0.0
    p_result_duplicate: float = 0.0
    p_objective_error: float = 0.0
    p_objective_nan: float = 0.0
    p_objective_hang: float = 0.0
    p_device_error: float = 0.0
    hang_seconds: float = 1.0
    delay_seconds: float = 0.5


def stable_key(cfg) -> str:
    """Deterministic key for an objective's config dict (the same
    suggested point maps to the same key in every run)."""
    if isinstance(cfg, dict):
        return repr(sorted((str(k), repr(v)) for k, v in cfg.items()))
    return repr(cfg)


class ChaosMonkey:
    """One seeded fault-injection schedule + its accounting."""

    # lock-order: _roll_lock
    def __init__(self, config: ChaosConfig, stats: FaultStats | None = None):
        self.config = config
        self.stats = stats if stats is not None else FaultStats()
        self._roll_lock = threading.Lock()
        self._occurrence = defaultdict(int)  # guarded-by: _roll_lock
        self._installed_observer = None

    # -- the deterministic roll ----------------------------------------
    def _roll(self, site: str, key, p: float) -> bool:
        if p <= 0.0:
            return False
        with self._roll_lock:
            occ = self._occurrence[(site, key)]
            self._occurrence[(site, key)] = occ + 1
        h = hashlib.sha256(
            f"{self.config.seed}:{site}:{key}:{occ}".encode()
        ).digest()
        hit = int.from_bytes(h[:8], "big") / 2 ** 64 < p
        if hit:
            self.stats.record(f"chaos_{site}")
        return hit

    # -- worker-plane sites --------------------------------------------
    def maybe_kill_worker(self, tid, where: str = "mid"):
        """Raise :class:`WorkerKilled` per the schedule.  ``where``
        distinguishes kill points (before vs. after the objective) so
        each rolls independently."""
        if self._roll("worker_kill", (int(tid), where),
                      self.config.p_worker_kill):
            logger.info("chaos: killing worker at trial %s (%s)", tid, where)
            raise WorkerKilled(f"chaos kill at trial {tid} ({where})")

    def should_delay_result(self, tid) -> bool:
        """Roll the result_delay site.  The WORKER implements the stall
        (pausing its heartbeat for the sleep) so the fault models a
        frozen process — otherwise the heartbeat thread would keep the
        lease warm and a delay could never exercise the stale-result
        drop, however long."""
        return self._roll("result_delay", int(tid),
                          self.config.p_result_delay)

    def should_duplicate_result(self, tid) -> bool:
        return self._roll(
            "result_duplicate", int(tid), self.config.p_result_duplicate
        )

    # -- queue-plane sites ---------------------------------------------
    def maybe_torn_lock(self, jobs, tid):
        """Write garbage to ``tid``'s lock path (iff currently unlocked):
        a worker that died inside its lock write."""
        if not self._roll("torn_lock", int(tid), self.config.p_torn_lock):
            return
        import os

        lock = jobs.lock_path(tid)
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return
        with os.fdopen(fd, "wb") as f:
            f.write(b"\x00torn\x00")  # never a valid owner string
        logger.info("chaos: tore lock file for trial %s", tid)

    # -- objective-plane sites -----------------------------------------
    def objective_fault(self, key):
        """Inject at one objective evaluation.  May sleep (hang), raise
        (:class:`ChaosObjectiveError`), or return ``float('nan')`` to be
        used as the loss; returns None when nothing fired."""
        if self._roll("objective_hang", key, self.config.p_objective_hang):
            logger.info("chaos: hanging objective (%.2fs)",
                        self.config.hang_seconds)
            time.sleep(self.config.hang_seconds)
        if self._roll("objective_error", key, self.config.p_objective_error):
            raise ChaosObjectiveError(f"chaos objective error at {key!r}")
        if self._roll("objective_nan", key, self.config.p_objective_nan):
            return float("nan")
        return None

    def wrap_objective(self, fn):
        """In-process convenience: ``fn`` with faults injected per point.
        (Out-of-process workers can't unpickle a closure — they call
        :func:`objective_fault` from a module-level objective instead.)"""

        def chaotic(cfg):
            fault = self.objective_fault(stable_key(cfg))
            if fault is not None:
                return fault
            return fn(cfg)

        return chaotic

    # -- device-plane site ---------------------------------------------
    def maybe_device_error(self):
        """Roll the device-error site once (one suggest dispatch)."""
        if self._roll("device_error", "dispatch", self.config.p_device_error):
            raise SyntheticDeviceError("chaos device error at dispatch")

    def install_device_faults(self):
        """Register a ``tpe_device`` suggest observer that raises
        :class:`SyntheticDeviceError` per the schedule (undone by
        :func:`active`'s exit or :meth:`uninstall_device_faults`)."""
        if self.config.p_device_error <= 0 or self._installed_observer:
            return
        from ..algos import tpe_device

        def _observer(requests):
            self.maybe_device_error()

        tpe_device._suggest_observers.append(_observer)
        self._installed_observer = _observer

    def uninstall_device_faults(self):
        if self._installed_observer is None:
            return
        from ..algos import tpe_device

        try:
            tpe_device._suggest_observers.remove(self._installed_observer)
        except ValueError:
            pass
        self._installed_observer = None


# -- process-wide activation -------------------------------------------
#
# Production call sites (worker.py, file_trials.py) look the monkey up
# through ``sys.modules`` so a run that never imported the chaos harness
# pays one dict miss, not an import.

_active_lock = threading.Lock()
_active_monkey: ChaosMonkey | None = None


def get_active() -> ChaosMonkey | None:
    return _active_monkey


@contextlib.contextmanager
def active(monkey: ChaosMonkey):
    """Make ``monkey`` the process-wide chaos source for the block (and
    register its device-fault observer when configured).  Nested
    activation is refused — overlapping schedules would not be
    reproducible."""
    global _active_monkey
    with _active_lock:
        if _active_monkey is not None:
            raise RuntimeError("a chaos monkey is already active")
        _active_monkey = monkey
    monkey.install_device_faults()
    try:
        yield monkey
    finally:
        monkey.uninstall_device_faults()
        with _active_lock:
            _active_monkey = None
