"""Fault-tolerant trial execution.

The reference's distributed model (Bergstra, Yamins & Cox, ICML 2013)
assumes workers die and does nothing about it: MongoDB leaves a dead
worker's job reserved forever, and the FileTrials port faithfully
reproduced that flaw — ``requeue_stale`` existed but nothing called it.
This package is the recovery story the production north-star requires,
spanning four layers:

- :mod:`.retry` — per-trial retry policy: exponential backoff with
  deterministic jitter, per-trial objective timeouts (watchdog thread,
  distinct from ``fmin``'s global ``timeout``), and poison-trial
  quarantine (after ``max_attempts`` a trial lands in
  ``JOB_STATE_ERROR`` and is excluded from the TPE fit instead of
  poisoning it or killing the run).
- :mod:`.leases` — FileTrials reservations become renewable heartbeat
  leases; a driver-side :class:`~.leases.LeaseReaper` automatically
  reclaims expired leases with attempt counters, replacing the
  never-invoked manual ``requeue_stale``.
- :mod:`.device` — XLA/TPU runtime errors (preemption, OOM, disconnect)
  around the fused suggest-program dispatch trigger bounded
  re-initialization and a CPU-backend fallback that continues the run.
- :mod:`.chaos` — deterministic, seed-reproducible fault injection
  (worker kills, torn locks, delayed/duplicated results, objective
  exceptions/NaNs/hangs, synthetic device errors, and the service-plane
  sites: server SIGKILL, connection resets, torn doc/journal writes,
  slow-loris clients) for tests, ``scripts/chaos_campaign.py``, and
  ``scripts/chaos_serve_campaign.py``.
- :mod:`.fsck` — offline detect-and-repair for the durable trial store
  (torn docs, orphan leases/locks, duplicate tids, stale seed cursors,
  tmp droppings, torn response journals); run at server startup and via
  ``python -m hyperopt_tpu.service fsck``.

All recovery events flow into :class:`hyperopt_tpu.observability.FaultStats`
counters; see ``docs/resilience.md`` for the protocols and knobs.
"""

from .device import DeviceRecovery, SyntheticDeviceError, is_device_error
from .fsck import FsckReport, fsck_path, fsck_queue, fsck_service_root
from .leases import LeaseReaper
from .retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryPolicy,
    TrialQuarantined,
    TrialTimeout,
    backoff_delay,
    execute_with_retry,
    run_with_timeout,
)

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "DeviceRecovery",
    "FsckReport",
    "LeaseReaper",
    "RetryPolicy",
    "SyntheticDeviceError",
    "TrialQuarantined",
    "TrialTimeout",
    "backoff_delay",
    "execute_with_retry",
    "fsck_path",
    "fsck_queue",
    "fsck_service_root",
    "is_device_error",
    "run_with_timeout",
]
