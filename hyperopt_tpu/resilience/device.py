"""Device-failure recovery for the fused suggest-program dispatch.

On real TPUs the runtime errors routinely: preemption of a donated
slice, HBM OOM from a concurrent tenant, a tunnel disconnect.  JAX
surfaces all of these as ``XlaRuntimeError``/``JaxRuntimeError`` at
dispatch or (because dispatch is asynchronous) at the blocking readback.
The reference has no story here; this module gives the driver one:

1. **Bounded re-initialization** — on a device error the recovery wrapper
   drops every piece of device-resident state that could pin the dead
   device (the jit executable cache and the ``DeviceHistory`` mirrors via
   :func:`hyperopt_tpu.algos.tpe_device.reset_device_state`, plus
   ``jax.clear_caches()``) and retries the dispatch; the next suggest
   re-uploads the history from host truth.
2. **CPU-backend fallback** — after ``max_reinits`` consecutive failures
   the recovery pins subsequent suggest programs to the host CPU backend
   (``jax.default_device``), trading suggest speed for run survival; the
   speculative engine re-issues cleanly because its failed speculations
   are discarded, never consumed.

Used by ``FMinIter`` (synchronous suggest calls) and the pipelined
engine (speculative re-issues / synchronous recomputes).  Every event is
counted in :class:`~hyperopt_tpu.observability.FaultStats`
(``device_error`` / ``device_reinit`` / ``cpu_fallback``).
"""

from __future__ import annotations

import logging
import threading

from .. import tracing

logger = logging.getLogger(__name__)


class SyntheticDeviceError(RuntimeError):
    """A chaos-injected device failure (stands in for XlaRuntimeError)."""


# Exception type names that the XLA/JAX runtimes raise for device-plane
# failures.  Matched by name + module prefix, not identity: jaxlib moves
# these between modules across versions, and the tunnel plugin wraps
# them.
_DEVICE_ERROR_NAMES = frozenset(
    {
        "XlaRuntimeError",
        "JaxRuntimeError",
        "InternalError",
        "ResourceExhaustedError",
        "UnavailableError",
        "AbortedError",
    }
)
_DEVICE_ERROR_MODULE_PREFIXES = ("jaxlib", "jax.")


def is_device_error(exc) -> bool:
    """Is ``exc`` an XLA/TPU runtime failure (or a chaos stand-in)?"""
    if isinstance(exc, SyntheticDeviceError):
        return True
    if getattr(exc, "_hyperopt_device_error", False):
        return True
    for klass in type(exc).__mro__:
        if klass.__name__ in _DEVICE_ERROR_NAMES and (
            klass.__module__.startswith(_DEVICE_ERROR_MODULE_PREFIXES)
        ):
            return True
    return False


def mark_device_error(exc):
    """Tag ``exc`` so :func:`is_device_error` recognizes it regardless of
    type — used by dispatch sites that positively know the failure came
    from the device plane (e.g. the fused-program readback)."""
    try:
        exc._hyperopt_device_error = True
    except Exception:  # extension-type exceptions may reject attributes
        pass
    return exc


def _reset_device_state():
    """Drop device-resident caches so retried dispatches rebuild from
    host truth.  Best-effort: each layer is cleared independently."""
    try:
        from ..algos import tpe_device

        tpe_device.reset_device_state()
    except Exception:
        logger.debug("tpe_device state reset failed", exc_info=True)
    try:
        import jax

        jax.clear_caches()
    except Exception:
        logger.debug("jax.clear_caches failed", exc_info=True)


def _cpu_device():
    try:
        import jax

        cpus = jax.devices("cpu")
        return cpus[0] if cpus else None
    except Exception:
        return None


class DeviceRecovery:
    """Run device-dispatching callables with bounded re-init + fallback.

    One instance per driver run (``FMinIter`` owns it and shares it with
    the speculative engine).  Thread-safe: the engine's speculation
    thread and the driver thread may both hit device errors.

    ``max_reinits``: CONSECUTIVE device errors absorbed by
    re-initialization before the CPU fallback engages — a successful
    dispatch refills the budget (scattered transient preemptions over a
    long run each recover; only a persistently dead device escalates).
    After the fallback, one more device error (now on the CPU backend,
    i.e. genuinely unrecoverable) propagates.  The fallback itself is
    sticky: a backend that just preempted is not handed new work.
    """

    # lock-order: _state_lock
    def __init__(self, max_reinits: int = 2, stats=None):
        self.max_reinits = int(max_reinits)
        self.stats = stats
        self._state_lock = threading.Lock()
        self._n_reinits = 0  # guarded-by: _state_lock
        self._on_cpu = False  # guarded-by: _state_lock

    @property
    def cpu_fallback_active(self) -> bool:
        with self._state_lock:
            return self._on_cpu

    @property
    def n_reinits(self) -> int:
        with self._state_lock:
            return self._n_reinits

    def note_success(self):
        """A dispatch went through: refill the consecutive-failure
        budget (the CPU fallback stays sticky)."""
        with self._state_lock:
            self._n_reinits = 0

    def _record(self, event):
        if self.stats is not None:
            self.stats.record(event)

    def absorb(self, exc):
        """Process one observed device error WITHOUT retrying — for
        callers that have their own degrade path (the speculative
        engine drops a failed launch and recomputes synchronously, but
        the device still needs the re-init or the recompute hits the
        same dead executable).

        Returns None when ``exc`` is not a device error (caller should
        re-raise), True when the recovery state advanced (re-init done /
        CPU fallback engaged — a retry is sensible), False when the
        budget is exhausted (caller must propagate)."""
        if not is_device_error(exc):
            return None
        self._record("device_error")
        with self._state_lock:
            if self._n_reinits < self.max_reinits:
                self._n_reinits += 1
                action = "reinit"
            elif not self._on_cpu and _cpu_device() is not None:
                self._on_cpu = True
                action = "cpu"
            else:
                action = "exhausted"
        if action == "exhausted":
            return False
        # visible in the request trace that absorbed the failure: the
        # re-init/fallback wall-time explains an otherwise-unattributed
        # slow dispatch (no-op outside a traced request)
        tracing.add_event(
            "device.recovery", action=action,
            error=type(exc).__name__,
        )
        if action == "cpu":
            self._record("cpu_fallback")
            logger.error(
                "device error persisted through %d re-inits; falling "
                "back to the CPU backend: %s",
                self.max_reinits,
                exc,
            )
        else:
            self._record("device_reinit")
            logger.warning(
                "device error during suggest dispatch "
                "(re-initializing, %d/%d): %s",
                self.n_reinits,
                self.max_reinits,
                exc,
            )
        with tracing.span("device.reinit", action=action):
            _reset_device_state()
        return True

    def run(self, fn):
        """``fn()`` with recovery.  Non-device exceptions propagate
        untouched; device errors trigger re-init (bounded), then the CPU
        fallback, then propagate."""
        while True:
            with self._state_lock:
                on_cpu = self._on_cpu
            ctx = None
            if on_cpu:
                cpu = _cpu_device()
                if cpu is not None:
                    import jax

                    ctx = jax.default_device(cpu)
            try:
                if ctx is not None:
                    with ctx:
                        out = fn()
                else:
                    out = fn()
            except Exception as e:
                if not self.absorb(e):  # None (not device) or False
                    raise
            else:
                self.note_success()
                return out
