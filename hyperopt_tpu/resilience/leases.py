"""Heartbeat leases and the driver-side reaper for the FileTrials queue.

The reference's known flaw (SURVEY.md §5): a dead worker's job keeps its
``owner`` stamp forever — Mongo there, the reservation lock file here.
The seed port shipped a manual ``requeue_stale`` that nothing invoked.
This module replaces it with an automatic protocol:

- **Lease grant** — ``FileJobs.reserve`` writes
  ``<queue>/leases/<tid>.lease`` (JSON: owner, expiry epoch, attempt)
  atomically next to the lock file, and stamps the trial doc's
  ``misc["attempts"]`` execution counter.
- **Heartbeat** — the worker renews the lease (:class:`LeaseHeartbeat`,
  a daemon thread at ``ttl/3`` cadence) while the objective runs and
  between poll iterations; a renewal that discovers the lease gone or
  re-owned flips ``lost`` and the worker drops its result instead of
  clobbering the reclaimed trial.
- **Reap** — the driver runs a :class:`LeaseReaper` thread for the
  duration of ``FMinIter.run``: RUNNING trials whose lease expired are
  reclaimed (lock + lease removed, doc back to ``JOB_STATE_NEW``) until
  ``misc["attempts"]`` reaches the policy's ``max_attempts``, at which
  point the trial is quarantined in ``JOB_STATE_ERROR`` — excluded from
  the TPE fit, never blocking run completion.  Torn or orphaned lock
  files (a worker that died between lock creation and doc rewrite, or a
  chaos-injected garbage lock) older than the TTL are cleared so they
  cannot strand a NEW trial.

Deliberately conservative about races with *live* workers: reclamation
re-reads the doc immediately before rewriting it and aborts if the state
moved off RUNNING, and the worker side re-verifies lease ownership
before its final result write — between them, a slow-but-alive worker
either lands its result or has it dropped, never half of each.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from ..base import (
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
)
from ..utils import coarse_utcnow
from .retry import RetryPolicy

logger = logging.getLogger(__name__)


class LeaseHeartbeat:
    """Daemon thread renewing one reservation's lease until stopped.

    ``lost`` flips permanently when a renewal finds the lease missing or
    owned by someone else (the reaper reclaimed it): the worker must then
    discard its in-flight result."""

    def __init__(self, jobs, tid, owner, ttl=None, interval=None, stats=None):
        self.jobs = jobs
        self.tid = int(tid)
        self.owner = owner
        self.ttl = float(ttl if ttl is not None else jobs.lease_ttl)
        self.interval = float(
            interval if interval is not None else max(self.ttl / 3.0, 0.01)
        )
        self.stats = stats
        self._stop = threading.Event()
        self._lost = threading.Event()
        self._thread = None

    @property
    def lost(self) -> bool:
        return self._lost.is_set()

    def renew_now(self) -> bool:
        """One synchronous renewal; False (and ``lost``) if the lease is
        no longer ours."""
        ok = self.jobs.renew_lease(self.tid, self.owner, ttl=self.ttl)
        if ok:
            if self.stats is not None:
                self.stats.record("heartbeat")
        else:
            self._lost.set()
        return ok

    def _run(self):
        while not self._stop.wait(self.interval):
            if not self.renew_now():
                return

    def start(self):
        self._thread = threading.Thread(
            target=self._run,
            name=f"hyperopt-lease-heartbeat-{self.tid}",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class LeaseReaper:
    """Driver-side reclamation of expired leases (+ stale-lock GC).

    Owned by ``FMinIter`` for async FileTrials runs (started/stopped
    around ``run``); also usable standalone — ``reap_once`` is the whole
    protocol, the thread just repeats it every ``interval`` seconds.
    """

    # lock-order: _state_lock
    def __init__(self, trials, policy: RetryPolicy | None = None,
                 stats=None, interval: float | None = None):
        self.jobs = trials.jobs
        self.policy = policy if policy is not None else RetryPolicy()
        self.stats = stats
        self.interval = float(
            interval
            if interval is not None
            else self.policy.effective_reap_interval
        )
        self._stop = threading.Event()
        self._thread = None
        self._state_lock = threading.Lock()
        self._n_reclaimed = 0  # guarded-by: _state_lock
        self._n_quarantined = 0  # guarded-by: _state_lock
        self._n_stale_locks = 0  # guarded-by: _state_lock

    # -- counters ------------------------------------------------------
    @property
    def n_reclaimed(self):
        with self._state_lock:
            return self._n_reclaimed

    @property
    def n_quarantined(self):
        with self._state_lock:
            return self._n_quarantined

    @property
    def n_stale_locks(self):
        with self._state_lock:
            return self._n_stale_locks

    def _record(self, event):
        if self.stats is not None:
            self.stats.record(event)

    @staticmethod
    def _record_store_lease(event):
        """Mirror a reap-protocol event into the process-wide storage
        telemetry (observability.StoreStats) when one is installed —
        the lease-churn axis of the SL6xx storage-plane evidence."""
        from ..parallel.file_trials import store_stats

        stats = store_stats()
        if stats is not None:
            stats.record_lease(event)

    # -- the protocol --------------------------------------------------
    def _lease_expired(self, tid, now) -> bool:
        lease = self.jobs.read_lease(tid)
        if lease is not None:
            try:
                return float(lease["expires_at"]) <= now
            except (KeyError, TypeError, ValueError):
                return True  # torn/garbage lease: treat as expired
        # no lease: the worker died between lock and lease write, or the
        # queue predates leases — fall back to the lock file's age
        try:
            age = now - os.path.getmtime(self.jobs.lock_path(tid))
        except OSError:
            return True  # RUNNING with neither lease nor lock: orphaned
        return age > self.jobs.lease_ttl

    def _reclaim(self, doc):
        tid = doc["tid"]
        attempts = int(doc.get("misc", {}).get("attempts", 1))
        self.jobs.clear_lease(tid)
        try:
            os.unlink(self.jobs.lock_path(tid))
        except FileNotFoundError:
            pass
        # the worker may have completed in the scan window — re-read and
        # leave a finished doc alone (its result is valid; re-running it
        # would only burn an attempt)
        fresh = self.jobs.read_doc(tid)
        if fresh is None or fresh["state"] != JOB_STATE_RUNNING:
            return
        doc = fresh
        if attempts >= self.policy.max_attempts:
            doc["state"] = JOB_STATE_ERROR
            doc.setdefault("misc", {})["error"] = (
                "LeaseExpired",
                f"worker lease expired on attempt {attempts}/"
                f"{self.policy.max_attempts}; trial quarantined",
            )
            self._record("lease_quarantined")
            self._record_store_lease("quarantine")
            with self._state_lock:
                self._n_quarantined += 1
            logger.warning(
                "trial %s quarantined after %d expired lease(s)",
                tid, attempts,
            )
        else:
            doc["state"] = JOB_STATE_NEW
            doc["owner"] = None
            doc["book_time"] = None
            self._record("lease_reclaimed")
            self._record_store_lease("reap")
            with self._state_lock:
                self._n_reclaimed += 1
            logger.info(
                "reclaimed expired lease for trial %s (attempt %d/%d)",
                tid, attempts, self.policy.max_attempts,
            )
        doc["refresh_time"] = coarse_utcnow()
        self.jobs.write(doc)

    def reap_once(self) -> int:
        """One full scan; returns the number of trials reclaimed or
        quarantined."""
        now = time.time()
        n = 0
        # native fast scan for RUNNING ids; docs are materialized only
        # for candidates whose lease actually expired
        running_tids = set(int(t) for t in self.jobs.running_tids())
        for tid in sorted(running_tids):
            if not self._lease_expired(tid, now):
                continue
            doc = self.jobs.read_doc(tid)
            if doc is None or doc["state"] != JOB_STATE_RUNNING:
                continue  # finished while we scanned
            self._record("lease_expired")
            self._reclaim(doc)
            n += 1
        # stale/torn lock GC: a lock file whose trial is NOT running
        # (crashed mid-reserve, chaos-torn, or plain orphaned) blocks
        # re-reservation forever if left in place
        for tid in self.jobs.locked_tids():
            if tid in running_tids:
                continue
            lock = self.jobs.lock_path(tid)
            try:
                age = now - os.path.getmtime(lock)
            except OSError:
                continue  # already gone
            if age <= self.jobs.lease_ttl:
                continue  # may be a reservation in flight
            try:
                os.unlink(lock)
            except FileNotFoundError:
                continue
            self.jobs.clear_lease(tid)
            self._record("stale_lock_cleared")
            with self._state_lock:
                self._n_stale_locks += 1
            logger.info("cleared stale lock for trial %s", tid)
        # tmp-dropping GC: `*.tmp.*` files from a writer killed between
        # open and os.replace in _atomic_write.  Age-gated by the lease
        # TTL so an in-flight write is never yanked out from under its
        # writer.
        n_tmp = self.jobs.gc_tmp_droppings()
        if n_tmp:
            if self.stats is not None:
                self.stats.record("tmp_dropping_cleared", n_tmp)
            logger.info("cleared %d torn tmp file(s)", n_tmp)
        return n

    # -- thread lifecycle ----------------------------------------------
    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.reap_once()
            except Exception:
                # the reaper must outlive transient queue errors (NFS
                # blips, concurrent delete_all) — log and keep scanning
                logger.exception("lease reaper scan failed; continuing")

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="hyperopt-lease-reaper", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
