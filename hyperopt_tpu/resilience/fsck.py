"""fsck for the durable trial store: detect and repair crash damage.

``kill -9``, torn disk writes, and writers that died mid-operation leave
a FileTrials queue directory (or a whole optimization-service root) in
states the happy path never produces.  This module is the offline
checker/repairer — run automatically by the optimization server before
it admits traffic, and by hand via::

    python -m hyperopt_tpu.service fsck <root>            # dry-run report
    python -m hyperopt_tpu.service fsck <root> --repair   # fix what it finds

Rule catalog (stable ids, mirroring the analysis passes' convention):

========  ==============================================================
FS401     torn/corrupt trial doc (fails its length+CRC32 trailer or does
          not parse).  Repair: quarantine to ``<doc>.corrupt``; if the
          study's response journal holds the doc, restore it.
FS402     orphan lease (no trial doc, or the doc is not RUNNING).
          Repair: delete the lease file.
FS403     orphan/stale lock (no trial doc, or the doc is in a state that
          cannot legitimately hold a reservation: NEW/DONE/ERROR).
          Repair: delete the lock file.
FS404     duplicate/mismatched tid (the doc's internal ``tid`` does not
          match its filename — two files can then claim one tid).
          Repair: quarantine the mismatched file.
FS405     stale seed-cursor attachment (the service's durable cursor is
          BEHIND the highest draw position evidenced by docs/journal —
          a restart would re-issue a seed an existing trial already
          used).  Repair: advance the attachment.
FS406     tmp droppings (``*.tmp.*`` files from a writer killed between
          ``open`` and ``os.replace`` in ``_atomic_write``).
          Repair: delete.
FS407     torn response-journal record (a line failing its per-record
          CRC — a torn final append, or latent corruption).  Repair:
          rewrite the journal keeping only the valid records.
FS408     broken id allocator: a stuck ``ids.counter.lock`` (allocator
          SIGKILL'd inside its critical section — every later
          allocation would spin to a 30s timeout), or an
          empty/regressed ``ids.counter`` at or below the highest tid
          on disk (the next allocation would re-issue an existing tid).
          Repair: delete the stuck lock / advance the counter past the
          highest tid.
FS410     torn segment record(s) in the segmented trial store (a line
          failing its per-record CRC inside a sealed segment's byte
          range, or — offline, where no appender can be in flight — a
          torn final append on the active segment).  Repair: rewrite
          the segment keeping only valid records (and update the
          manifest entry for a sealed one).
FS411     manifest/segment mismatch: the ``segments/MANIFEST.json`` is
          missing or corrupt while segment files exist (repair: rebuild
          it from the files), a sealed entry references a segment file
          that is gone (repair: drop the entry), a sealed entry's byte
          length exceeds the file (repair: re-pin to the valid prefix),
          or a sealed range's CRC no longer matches (repair: recompute).
FS412     orphaned segment file: a ``seg-*.log`` referenced by neither
          the manifest's sealed list nor its active pointer — retired
          segments a compactor SIGKILL'd mid-retirement failed to
          unlink.  Repair: delete (their live records were folded into
          the compacted base; unacknowledged stragglers share torn-
          write semantics).
FS409     replica-plane damage under ``<root>/replicas/``: an orphaned
          study-ownership lease (no study directory AND not live — a
          live one is the mid-create window, not damage), an expired
          lease still naming a dead owner (past one extra TTL of
          grace, so a briefly-stalled live holder is never fenced by a
          sibling's startup fsck), a torn lease or replica-registry
          record (fails its CRC trailer), a stuck ``.claimlock`` (a
          claimant SIGKILL'd inside the lease critical section; only
          flagged past an age grace no live claimant can reach), or a
          garbled fence counter.  Repair: delete orphans/stuck locks,
          reclaim expired leases (owner cleared, **fence preserved** —
          deleting the fence would reset tokens and let a stale
          holder's writes through), quarantine torn records, and
          rewrite a garbled fence counter past the highest evidenced
          token.
========  ==============================================================

Offline by design: run it on a queue no process is writing (the server
runs it before starting its scheduler).  The FS409 replica-plane rules
are the one exception forced to tolerate liveness: in multi-replica
mode every replica's STARTUP fsck repairs the shared root while
siblings serve, so those rules gate on lease liveness and age before
touching anything.  Repairs are individually crash-safe (atomic
rename/replace or unlink).
"""

from __future__ import annotations

import glob
import json
import os
import time
from dataclasses import dataclass, field

from ..base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_RUNNING,
    STATUS_FAIL,
)
from ..parallel.file_trials import (
    DocCorrupt,
    _decode_doc,
    quarantine_path,
)

# states that can legitimately hold a reservation lock
_LOCKABLE_STATES = (JOB_STATE_RUNNING,)

# a .claimlock younger than this may be a live peer inside the
# O_CREAT|O_EXCL critical section (the claim path itself steals locks
# older than the store TTL; fsck can't know the TTL, so it uses a
# ceiling no live claimant can reach)
FS409_CLAIMLOCK_GRACE_S = 60.0


@dataclass
class Finding:
    rule: str
    path: str
    detail: str
    repaired: bool = False
    action: str = ""

    def format(self) -> str:
        mark = "FIXED" if self.repaired else "FOUND"
        out = f"[{self.rule}] {mark} {self.path}: {self.detail}"
        if self.action:
            out += f" -> {self.action}"
        return out


@dataclass
class FsckReport:
    root: str
    repair: bool
    findings: list = field(default_factory=list)
    n_docs: int = 0
    n_queues: int = 0

    def add(self, rule, path, detail, repaired=False, action=""):
        self.findings.append(
            Finding(rule, path, detail, repaired=repaired, action=action)
        )

    @property
    def n_unrepaired(self) -> int:
        return sum(1 for f in self.findings if not f.repaired)

    @property
    def clean(self) -> bool:
        """True when the store is consistent NOW: either nothing was
        found, or everything found was repaired."""
        return self.n_unrepaired == 0

    def by_rule(self) -> dict:
        out = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def summary(self) -> dict:
        return {
            "root": self.root,
            "repair": self.repair,
            "clean": self.clean,
            "n_queues": self.n_queues,
            "n_docs": self.n_docs,
            "n_findings": len(self.findings),
            "n_unrepaired": self.n_unrepaired,
            "by_rule": self.by_rule(),
            "findings": [
                {
                    "rule": f.rule, "path": f.path, "detail": f.detail,
                    "repaired": f.repaired, "action": f.action,
                }
                for f in self.findings
            ],
        }

    def format(self) -> str:
        lines = [
            f"fsck {self.root}: {self.n_queues} queue(s), "
            f"{self.n_docs} doc(s), {len(self.findings)} finding(s)"
            + ("" if self.clean else f", {self.n_unrepaired} UNREPAIRED")
        ]
        lines.extend(f.format() for f in self.findings)
        lines.append("clean" if self.clean else "NOT CLEAN")
        return "\n".join(lines)


def _tid_from_name(name, suffix):
    stem = os.path.basename(name)
    if not stem.endswith(suffix):
        return None
    try:
        return int(stem[: -len(suffix)])
    except ValueError:
        return None


def _attachment_path(qdir, key):
    from ..parallel.file_trials import attachment_filename

    return os.path.join(qdir, "attachments", attachment_filename(key))


def _journal_path(qdir):
    # lazy import: service -> resilience is the load-bearing direction;
    # this reverse edge exists only for the journal's file format
    from ..service.core import RESPONSE_JOURNAL_ATTACHMENT

    return _attachment_path(qdir, RESPONSE_JOURNAL_ATTACHMENT)


def _load_journal(qdir):
    """(entries, n_torn, path) for the study's response journal (empty
    when none exists)."""
    from ..service.core import ResponseJournal

    path = _journal_path(qdir)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return [], 0, path
    entries, torn = ResponseJournal.parse_lines(raw)
    entries.sort(key=lambda e: int(e.get("seq", 0)))
    return entries, torn, path


def _rebuild_manifest(sdir, seg_paths, parse, object_hook):
    """A best-effort manifest from the segment files alone: every
    segment but the last (by sequence) sealed at its valid prefix, the
    last one active.  Epoch 1 so any cached reader does a full replay."""
    import zlib as _zlib

    from ..parallel import segment_store as sstore

    names = sorted(os.path.basename(p) for p in seg_paths)
    sealed = []
    for name in names[:-1]:
        try:
            with open(os.path.join(sdir, name), "rb") as f:
                raw = f.read()
        except OSError:
            continue
        records, consumed, _, _ = parse(raw, object_hook=object_hook)
        sealed.append({
            "name": name,
            "bytes": consumed,
            "records": len(records),
            "crc32": "%08x" % (_zlib.crc32(raw[:consumed]) & 0xFFFFFFFF),
        })
    active = names[-1] if names else sstore.segment_name(1)
    try:
        next_seq = int(active[4:12]) + 1
    except ValueError:
        next_seq = len(names) + 1
    return {
        "version": 1,
        "epoch": 1,
        "next_seq": next_seq,
        "active": active,
        "sealed": sealed,
    }


def _fsck_segments(qdir, repair, report: FsckReport) -> dict:  # protocol: orphan-sweep
    """FS410/FS411/FS412 over ``<qdir>/segments``; returns the replayed
    {tid: doc} view so the lease/lock/cursor/counter rules see segment-
    stored trials exactly like per-doc ones.  Empty dict when the queue
    is not segmented."""
    import zlib as _zlib

    from ..parallel import segment_store as sstore
    from ..parallel.file_trials import (
        _atomic_write,
        _json_object_hook,
        _read_doc,
        _write_doc,
    )

    sdir = os.path.join(qdir, "segments")
    manifest_path = os.path.join(sdir, sstore.MANIFEST_NAME)
    seg_paths = sorted(glob.glob(os.path.join(sdir, sstore.SEGMENT_GLOB)))
    have_manifest = os.path.exists(manifest_path)
    if not (have_manifest or seg_paths):
        return {}
    parse = sstore.parse_segment_chunk

    manifest = (
        _read_doc(manifest_path, quarantine=False) if have_manifest else None
    )
    if manifest is None:
        # FS411: segment files with no (readable) manifest — recovery
        # cannot know the replay order or sealed byte ranges
        rebuilt = _rebuild_manifest(sdir, seg_paths, parse, _json_object_hook)
        fixed = False
        action = ""
        if repair:
            try:
                if have_manifest:
                    dest = quarantine_path(manifest_path)
                    os.replace(manifest_path, dest)
                    action = f"quarantined to {os.path.basename(dest)}; "
                # durability: exempt(offline repair: fsck runs single-writer against a stopped queue)
                _write_doc(manifest_path, rebuilt, fsync_kind="segment")
                fixed = True
                action += (
                    f"rebuilt manifest from {len(seg_paths)} segment "
                    f"file(s)"
                )
            except OSError:
                pass
        report.add(
            "FS411", manifest_path,
            "corrupt segment manifest" if have_manifest
            else "segment files without a manifest",
            repaired=fixed, action=action,
        )
        manifest = rebuilt  # replay from the in-memory rebuild either way

    view = {}
    sealed_out = []
    manifest_dirty = False
    for entry in manifest.get("sealed", ()):
        name = entry.get("name", "")
        path = os.path.join(sdir, name)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            # FS411: the manifest promises a sealed segment that is gone
            manifest_dirty = manifest_dirty or repair
            report.add(
                "FS411", path,
                f"manifest references missing sealed segment {name!r}",
                repaired=repair,
                action="dropped manifest entry" if repair else "",
            )
            continue
        limit = int(entry.get("bytes", 0))
        short = len(raw) < limit
        chunk = raw[:limit]
        records, consumed, torn, pending = parse(
            chunk, object_hook=_json_object_hook
        )
        # a sealed segment is immutable: a trailing-invalid line cannot
        # be an in-flight append — it is torn
        n_torn = torn + pending
        entry = dict(entry)
        if short:
            fixed = False
            if repair:
                entry["bytes"] = consumed
                entry["records"] = len(records)
                entry["crc32"] = "%08x" % (
                    _zlib.crc32(raw[:consumed]) & 0xFFFFFFFF
                )
                manifest_dirty = True
                fixed = True
            report.add(
                "FS411", path,
                f"sealed segment shorter than its manifest entry "
                f"({len(raw)} < {limit} bytes)",
                repaired=fixed,
                action=(f"re-pinned entry to valid prefix ({consumed} "
                        f"bytes, {len(records)} records)") if fixed else "",
            )
        elif n_torn:
            # FS410: torn record(s) inside the sealed range
            fixed = False
            action = ""
            if repair:
                from .. import journal_io

                from ..parallel.file_trials import _json_default

                blob = b"".join(
                    journal_io.frame_record(r, default=_json_default)
                    for r in records
                )
                try:
                    # durability: exempt(offline repair: fsck runs single-writer against a stopped queue)
                    _atomic_write(path, blob, fsync_kind="segment")
                    entry["bytes"] = len(blob)
                    entry["records"] = len(records)
                    entry["crc32"] = "%08x" % (
                        _zlib.crc32(blob) & 0xFFFFFFFF
                    )
                    manifest_dirty = True
                    fixed = True
                    action = (
                        f"rewrote segment keeping {len(records)} valid "
                        f"record(s)"
                    )
                except OSError:
                    pass
            report.add(
                "FS410", path,
                f"{n_torn} torn record(s) in sealed segment",
                repaired=fixed, action=action,
            )
        elif entry.get("crc32") and entry["crc32"] != (
            "%08x" % (_zlib.crc32(chunk) & 0xFFFFFFFF)
        ):
            # parseable but the sealed-range CRC moved: in-place rot
            fixed = False
            if repair:
                entry["crc32"] = "%08x" % (_zlib.crc32(chunk) & 0xFFFFFFFF)
                manifest_dirty = True
                fixed = True
            report.add(
                "FS411", path,
                "sealed-range CRC does not match its manifest entry",
                repaired=fixed,
                action="recomputed entry CRC" if fixed else "",
            )
        sealed_out.append(entry)
        for rec in records:
            view[int(rec["tid"])] = rec

    active = manifest.get("active")
    if active:
        path = os.path.join(sdir, active)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            raw = None  # an active segment not yet appended to is normal
        if raw is not None:
            records, consumed, torn, pending = parse(
                raw, object_hook=_json_object_hook
            )
            # offline there is no in-flight appender: a pending trailing
            # line is a torn final append
            n_torn = torn + pending
            if n_torn:
                fixed = False
                action = ""
                if repair:
                    from .. import journal_io

                    from ..parallel.file_trials import _json_default

                    blob = b"".join(
                        journal_io.frame_record(r, default=_json_default)
                        for r in records
                    )
                    try:
                        # durability: exempt(offline repair: fsck runs single-writer against a stopped queue)
                        _atomic_write(path, blob, fsync_kind="segment")
                        fixed = True
                        action = (
                            f"rewrote active segment keeping "
                            f"{len(records)} valid record(s)"
                        )
                    except OSError:
                        pass
                report.add(
                    "FS410", path,
                    f"{n_torn} torn record(s) at active segment tail",
                    repaired=fixed, action=action,
                )
            for rec in records:
                view[int(rec["tid"])] = rec

    # FS412: segment files referenced by neither sealed list nor active
    referenced = {e["name"] for e in sealed_out} | {
        e.get("name") for e in manifest.get("sealed", ())
    }
    if active:
        referenced.add(active)
    for path in seg_paths:
        if os.path.basename(path) in referenced:
            continue
        # an orphan can hold ACKED records that exist nowhere else: an
        # appender whose post-append manifest check ran before the
        # compactor's swap left fsync'd records in the old active, and
        # a compactor killed after the swap but before re-homing the
        # stragglers never copied them forward.  Fold the orphan
        # latest-wins per tid and re-home anything the replayed view
        # does not already supersede before deleting the file.
        orphan_latest = {}
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            raw = b""
        if raw:
            records, _consumed, _torn, _pending = parse(
                raw, object_hook=_json_object_hook
            )
            for rec in records:
                orphan_latest[int(rec["tid"])] = rec
        stragglers = []
        for tid in sorted(orphan_latest):
            rec = orphan_latest[tid]
            have = view.get(tid)
            if have is None or (
                rec != have
                and int(rec.get("state", 0)) >= int(have.get("state", 0))
            ):
                stragglers.append(rec)
        fixed = False
        action = ""
        if repair and (active or not stragglers):
            try:
                if stragglers:
                    from .. import journal_io
                    from ..parallel.file_trials import _json_default

                    # durability: exempt(offline repair: fsck runs single-writer against a stopped queue)
                    journal_io.append_records(
                        os.path.join(sdir, active), stragglers,
                        default=_json_default, fsync_kind="segment",
                    )
                    for rec in stragglers:
                        view[int(rec["tid"])] = rec
                os.unlink(path)
                fixed = True
                action = (
                    f"re-homed {len(stragglers)} acked record(s) to "
                    f"{active}; deleted"
                ) if stragglers else "deleted"
            except OSError:
                pass
        msg = "orphaned segment file (compactor killed before retiring it)"
        if stragglers:
            msg += (
                f"; holds {len(stragglers)} acked record(s) absent from "
                "the replayed view"
            )
        report.add("FS412", path, msg, repaired=fixed, action=action)

    if repair and manifest_dirty:
        manifest = dict(manifest)
        manifest["sealed"] = sealed_out
        # bump the epoch: cached readers must full-replay the repaired
        # lineage instead of trusting pinned offsets into rewritten files
        manifest["epoch"] = int(manifest.get("epoch", 0)) + 1
        try:
            # durability: exempt(offline repair: fsck runs single-writer against a stopped queue)
            _write_doc(manifest_path, manifest, fsync_kind="segment")
        except OSError:
            pass
    return view


def fsck_queue(qdir, repair=False, report: FsckReport = None) -> FsckReport:
    """Check (and optionally repair) ONE FileTrials queue directory."""
    qdir = os.path.abspath(qdir)
    if report is None:
        report = FsckReport(root=qdir, repair=repair)
    report.n_queues += 1

    entries, n_torn, journal_file = _load_journal(qdir)
    journal_docs = {}  # tid -> (doc, draw_index) recoverable from journal
    journal_results = {}  # tid -> result from journaled reports
    max_journal_draw = 0
    for entry in entries:
        if entry.get("kind") == "suggest":
            max_journal_draw = max(
                max_journal_draw, int(entry.get("draw_index", 0))
            )
            for doc in entry.get("docs") or []:
                journal_docs[int(doc["tid"])] = (
                    doc, entry.get("draw_index")
                )
        elif entry.get("kind") == "report":
            journal_results[int(entry.get("tid", -1))] = entry.get("result")

    # FS407: torn journal records
    if n_torn:
        fixed = False
        action = ""
        if repair:
            from ..parallel.file_trials import _atomic_write
            from ..service.core import ResponseJournal

            try:
                j = ResponseJournal(path=None)
                blob = b"".join(j._format_record(e) for e in entries)
                _atomic_write(journal_file, blob)
                fixed = True
                action = (
                    f"rewrote journal keeping {len(entries)} valid "
                    f"record(s)"
                )
            except OSError:
                pass
        report.add(
            "FS407", journal_file,
            f"{n_torn} torn journal record(s)",
            repaired=fixed, action=action,
        )

    # -- scan the docs ---------------------------------------------------
    docs_by_tid = {}
    seen_states = {}
    max_doc_draw = 0
    for path in sorted(glob.glob(os.path.join(qdir, "trials", "*.json"))):
        name_tid = _tid_from_name(path, ".json")
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        try:
            doc = _decode_doc(raw)
        except DocCorrupt as e:
            # FS401: torn/corrupt doc.  One unrepairable file (EACCES,
            # vanished mid-scan) must degrade to a "found, unrepaired"
            # finding, never abort the whole scan — the other queues
            # still deserve their repairs.
            action = ""
            fixed = False
            if repair:
                try:
                    dest = quarantine_path(path)
                    os.replace(path, dest)
                    fixed = True
                    action = f"quarantined to {os.path.basename(dest)}"
                except OSError:
                    pass
            if fixed:
                restored = journal_docs.get(name_tid)
                if restored is not None:
                    from ..parallel.file_trials import _write_doc

                    try:
                        doc, draw = restored
                        # durability: exempt(offline repair: fsck runs single-writer against a stopped queue)
                        _write_doc(path, doc)
                        docs_by_tid[int(doc["tid"])] = doc
                        seen_states[int(doc["tid"])] = doc["state"]
                        result = journal_results.get(int(doc["tid"]))
                        if result is not None:
                            doc = dict(doc)
                            doc["result"] = result
                            doc["state"] = (
                                JOB_STATE_ERROR
                                if result.get("status") == STATUS_FAIL
                                else JOB_STATE_DONE
                            )
                            # durability: exempt(offline repair: fsck runs single-writer against a stopped queue)
                            _write_doc(path, doc)
                            seen_states[int(doc["tid"])] = doc["state"]
                        action += "; restored from response journal"
                    except OSError:
                        action += "; journal restore FAILED"
            report.add(
                "FS401", path, f"corrupt trial doc ({e})",
                repaired=fixed, action=action,
            )
            continue
        report.n_docs += 1
        tid = int(doc.get("tid", -1))
        if name_tid is None or tid != name_tid:
            # FS404: the doc claims a tid its filename does not carry —
            # two files can then answer for one tid
            fixed = False
            action = ""
            if repair:
                try:
                    dest = quarantine_path(path)
                    os.replace(path, dest)
                    fixed = True
                    action = f"quarantined to {os.path.basename(dest)}"
                except OSError:
                    pass
            report.add(
                "FS404", path,
                f"doc tid {tid} does not match filename tid {name_tid}",
                repaired=fixed, action=action,
            )
            continue
        if tid in docs_by_tid:
            report.add(
                "FS404", path, f"duplicate tid {tid}", repaired=False
            )
            continue
        docs_by_tid[tid] = doc
        seen_states[tid] = doc["state"]
        max_doc_draw = max(
            max_doc_draw, int(doc.get("misc", {}).get("service_draw", 0))
        )

    # -- segmented store (FS410/FS411/FS412) ------------------------------
    # replayed segment docs join the same tables, so the lease/lock/
    # cursor/counter rules work identically on either backend; a doc
    # file AND a segment record for one tid is the benign mid-migration
    # leftover (migrate appends before unlinking), not FS404
    for tid, doc in sorted(_fsck_segments(qdir, repair, report).items()):
        report.n_docs += 1
        if tid not in docs_by_tid:
            docs_by_tid[tid] = doc
            seen_states[tid] = doc["state"]
        max_doc_draw = max(
            max_doc_draw, int(doc.get("misc", {}).get("service_draw", 0))
        )

    # -- leases (FS402) ---------------------------------------------------
    for path in sorted(glob.glob(os.path.join(qdir, "leases", "*.lease"))):
        tid = _tid_from_name(path, ".lease")
        state = seen_states.get(tid)
        if tid is not None and state == JOB_STATE_RUNNING:
            continue
        detail = (
            "lease without a trial doc" if state is None
            else f"lease for non-RUNNING doc (state {state})"
        )
        fixed = False
        if repair:
            try:
                os.unlink(path)
                fixed = True
            except OSError:
                pass
        report.add("FS402", path, detail, repaired=fixed,
                   action="deleted" if fixed else "")

    # -- locks (FS403) ----------------------------------------------------
    for path in sorted(glob.glob(os.path.join(qdir, "locks", "*.lock"))):
        tid = _tid_from_name(path, ".lock")
        state = seen_states.get(tid)
        if tid is not None and state in _LOCKABLE_STATES:
            continue
        detail = (
            "lock without a trial doc"
            if state is None or tid is None
            else f"lock on a doc that cannot hold one (state {state})"
        )
        fixed = False
        if repair:
            try:
                os.unlink(path)
                fixed = True
            except OSError:
                pass
        report.add("FS403", path, detail, repaired=fixed,
                   action="deleted" if fixed else "")

    # -- tmp droppings (FS406) --------------------------------------------
    for sub in ("trials", "locks", "leases", "attachments", "segments"):
        for path in sorted(glob.glob(os.path.join(qdir, sub, "*.tmp.*"))):
            fixed = False
            if repair:
                try:
                    os.unlink(path)
                    fixed = True
                except OSError:
                    pass
            report.add(
                "FS406", path,
                "tmp dropping from a writer killed mid-atomic-write",
                repaired=fixed, action="deleted" if fixed else "",
            )

    # -- id allocator (FS408) ---------------------------------------------
    counter_lock = os.path.join(qdir, "ids.counter.lock")
    if os.path.exists(counter_lock):
        # offline there is no legitimate holder: an allocator died
        # inside its critical section and every later allocation would
        # spin to its 30s timeout forever
        fixed = False
        if repair:
            try:
                os.unlink(counter_lock)
                fixed = True
            except OSError:
                pass
        report.add(
            "FS408", counter_lock,
            "stuck id-counter lock (allocator killed mid-allocation)",
            repaired=fixed, action="deleted" if fixed else "",
        )
    counter_file = os.path.join(qdir, "ids.counter")
    if docs_by_tid and os.path.exists(counter_file):
        try:
            with open(counter_file) as f:
                counter = int(f.read().strip() or 0)
        except (OSError, ValueError):
            counter = 0
        max_tid = max(docs_by_tid)
        if counter <= max_tid:
            fixed = False
            if repair:
                from ..parallel.file_trials import _atomic_write

                try:
                    # durability: exempt(offline repair: fsck runs single-writer against a stopped queue)
                    _atomic_write(counter_file, str(max_tid + 1).encode())
                    fixed = True
                except OSError:
                    pass
            report.add(
                "FS408", counter_file,
                f"id counter {counter} at or below highest tid "
                f"{max_tid}: the next allocation would duplicate a tid",
                repaired=fixed,
                action=(f"advanced counter {counter} -> {max_tid + 1}"
                        if fixed else ""),
            )

    # -- seed cursor (FS405) ----------------------------------------------
    from ..service.core import SEED_CURSOR_ATTACHMENT

    cursor_file = _attachment_path(qdir, SEED_CURSOR_ATTACHMENT)
    evidenced = max(max_doc_draw, max_journal_draw)
    if evidenced:
        cursor = 0
        try:
            with open(cursor_file) as f:
                cursor = int(f.read().strip() or 0)
        except (OSError, ValueError):
            cursor = 0
        if cursor < evidenced:
            fixed = False
            if repair:
                from ..parallel.file_trials import _atomic_write

                try:
                    # durability: exempt(offline repair: fsck runs single-writer against a stopped queue)
                    _atomic_write(cursor_file, str(evidenced).encode())
                    fixed = True
                except OSError:
                    pass
            report.add(
                "FS405", cursor_file,
                f"seed cursor {cursor} behind evidenced draw position "
                f"{evidenced}: a restart would re-issue a used seed",
                repaired=fixed,
                action=(f"advanced cursor {cursor} -> {evidenced}"
                        if fixed else ""),
            )

    return report


def _fsck_replica_plane(root, repair, report: FsckReport):
    """FS409: the replica plane under ``<root>/replicas/`` — ownership
    leases, fence counters, claim locks, and registry records."""
    leases_dir = os.path.join(root, "replicas", "leases")
    registry_dir = os.path.join(root, "replicas", "registry")
    studies_dir = os.path.join(root, "studies")
    if not (os.path.isdir(leases_dir) or os.path.isdir(registry_dir)):
        return
    now = time.time()

    def _has_study(study_id):
        return os.path.isdir(os.path.join(studies_dir, study_id))

    def _read_lease(study_id):
        try:
            with open(
                os.path.join(leases_dir, f"{study_id}.lease"), "rb"
            ) as f:
                return _decode_doc(f.read())
        except (OSError, DocCorrupt):
            return None

    def _lease_live(lease):
        if not lease or not lease.get("owner"):
            return False
        try:
            return float(lease.get("expires_at", 0.0)) > now
        except (TypeError, ValueError):
            return False

    def _remove(path, detail, action="deleted"):
        fixed = False
        if repair:
            try:
                os.unlink(path)
                fixed = True
            except OSError:
                pass
        report.add("FS409", path, detail, repaired=fixed,
                   action=action if fixed else "")

    for path in sorted(glob.glob(os.path.join(leases_dir, "*.lease"))):
        study_id = os.path.basename(path)[: -len(".lease")]
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        try:
            lease = _decode_doc(raw)
        except DocCorrupt as e:
            # torn lease: quarantine — safe because the FENCE COUNTER,
            # not the lease file, carries token monotonicity
            fixed = False
            action = ""
            if repair:
                try:
                    dest = quarantine_path(path)
                    os.replace(path, dest)
                    fixed = True
                    action = f"quarantined to {os.path.basename(dest)}"
                except OSError:
                    pass
            report.add(
                "FS409", path, f"torn replica-ownership lease ({e})",
                repaired=fixed, action=action,
            )
            continue
        if not _has_study(study_id):
            if _lease_live(lease):
                # a LIVE lease with no study dir is the mid-create
                # window (ownership-before-side-effects claims the
                # lease before the directory exists) — deleting it
                # would steal a live creator's ownership and, via the
                # fence file, reset token monotonicity.  Not damage.
                continue
            _remove(
                path,
                "orphaned replica-ownership lease (no study directory)",
            )
            continue
        owner = lease.get("owner")
        try:
            expires_at = float(lease.get("expires_at", 0.0))
            expired = expires_at <= now
        except (TypeError, ValueError):
            expires_at = 0.0
            expired = True
        try:
            grace = max(
                expires_at - float(lease.get("granted_at", expires_at)),
                0.0,
            )
        except (TypeError, ValueError):
            grace = 0.0
        if owner and expired and now <= expires_at + grace:
            # within one TTL of expiry the holder may be briefly
            # stalled, not dead: verify() deliberately treats an
            # expired-but-unreclaimed lease as still held, and claim()
            # can already take over without fsck's help.  Clearing the
            # owner here (e.g. a sibling replica's STARTUP fsck on the
            # shared root) would spuriously fence a live holder.
            continue
        if owner and expired:
            # expired residue of a dead replica: reclaim — owner
            # cleared, fence PRESERVED (resetting it would let the
            # dead owner's buffered writes pass a later verify)
            fixed = False
            action = ""
            if repair:
                from ..parallel.file_trials import _write_doc

                lease = dict(lease)
                lease["owner"] = None
                lease["expires_at"] = 0.0
                lease["reclaimed_by"] = "fsck"
                try:
                    # durability: exempt(offline repair: fsck runs single-writer against a stopped store)
                    _write_doc(path, lease)
                    fixed = True
                    action = (
                        f"reclaimed (owner {owner!r} cleared, fence "
                        f"{lease.get('fence')} preserved)"
                    )
                except OSError:
                    pass
            report.add(
                "FS409", path,
                f"expired replica-ownership lease still naming "
                f"{owner!r}",
                repaired=fixed, action=action,
            )

    # fence counters: garbled → rewrite past the highest evidenced
    # token; orphaned (no study) → delete
    for path in sorted(glob.glob(os.path.join(leases_dir, "*.fence"))):
        study_id = os.path.basename(path)[: -len(".fence")]
        if not _has_study(study_id):
            if _lease_live(_read_lease(study_id)):
                continue  # mid-create window (see the lease pass)
            _remove(path, "orphaned fence counter (no study directory)")
            continue
        try:
            with open(path) as f:
                int(f.read().strip() or 0)
            continue  # parseable: fine at any value
        except ValueError:
            pass
        except OSError:
            continue
        evidenced = 0
        lease_file = os.path.join(leases_dir, f"{study_id}.lease")
        try:
            with open(lease_file, "rb") as f:
                evidenced = int(_decode_doc(f.read()).get("fence", 0))
        except (OSError, DocCorrupt, TypeError, ValueError):
            pass
        fixed = False
        if repair:
            from ..parallel.file_trials import _atomic_write

            try:
                # durability: exempt(offline repair: fsck runs single-writer against a stopped store)
                _atomic_write(path, str(evidenced + 1).encode())
                fixed = True
            except OSError:
                pass
        report.add(
            "FS409", path,
            "garbled fence counter (token monotonicity at risk)",
            repaired=fixed,
            action=(f"rewrote as {evidenced + 1}" if fixed else ""),
        )

    # stuck claim locks: a FRESH lock is a peer inside the O_CREAT |
    # O_EXCL critical section (this fsck may be a sibling replica's
    # startup pass against a live shared root) — only a lock old
    # enough that no live claimant can hold it is damage.  The store
    # itself steals locks older than its TTL; this grace is the
    # conservative ceiling for roots fsck can't know the TTL of.
    for path in sorted(glob.glob(os.path.join(leases_dir, "*.claimlock"))):
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            continue
        if age <= FS409_CLAIMLOCK_GRACE_S:
            continue
        _remove(
            path,
            "stuck lease claim lock (claimant killed mid-claim)",
        )

    # registry records: torn → delete (regenerated by the replica's
    # next heartbeat; advisory data, never a safety input)
    for path in sorted(glob.glob(os.path.join(registry_dir, "*.json"))):
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        try:
            _decode_doc(raw)
        except DocCorrupt as e:
            _remove(path, f"torn replica-registry record ({e})")


def fsck_service_root(root, repair=False) -> FsckReport:
    """fsck every study queue under an optimization-service root, plus
    the replica plane (FS409) when one exists."""
    root = os.path.abspath(root)
    report = FsckReport(root=root, repair=repair)
    studies_dir = os.path.join(root, "studies")
    if os.path.isdir(studies_dir):
        for name in sorted(os.listdir(studies_dir)):
            qdir = os.path.join(studies_dir, name)
            if os.path.isdir(qdir):
                fsck_queue(qdir, repair=repair, report=report)
    _fsck_replica_plane(root, repair, report)
    return report


def fsck_path(path, repair=False) -> FsckReport:
    """fsck a service root (has ``studies/`` or ``replicas/``) or a
    single queue dir (has ``trials/``) — detected by layout."""
    path = os.path.abspath(path)
    if os.path.isdir(os.path.join(path, "studies")) or os.path.isdir(
        os.path.join(path, "replicas")
    ):
        return fsck_service_root(path, repair=repair)
    return fsck_queue(path, repair=repair)


def main(argv=None) -> int:
    """CLI body for ``python -m hyperopt_tpu.service fsck``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.service fsck",
        description="Check (and repair) a durable trial store: torn "
                    "docs, orphan leases/locks, duplicate tids, stale "
                    "seed cursors, tmp droppings, torn journals.",
    )
    ap.add_argument("root", help="service root or single queue directory")
    ap.add_argument(
        "--repair", action="store_true",
        help="apply repairs (default: dry-run report only)",
    )
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report as JSON")
    args = ap.parse_args(argv)
    report = fsck_path(args.root, repair=args.repair)
    if args.as_json:
        print(json.dumps(report.summary(), indent=1))
    else:
        print(report.format())
    return 0 if report.clean else 1
