"""Per-trial retry policy: backoff, jitter, timeouts, quarantine.

A flaky objective (transient OOM, a preempted data source, a network
hiccup) or a hung one must not abort a whole ``fmin`` run.  This module
gives every trial a bounded number of attempts with exponential backoff
and **deterministic** jitter (the jitter is a pure function of
``(seed, trial key, attempt)``, so a re-run of the same campaign sleeps
the same schedule — chaos runs stay reproducible), plus a per-trial
objective timeout enforced by a watchdog thread — distinct from
``fmin``'s global ``timeout``, which bounds the whole run.

A trial that exhausts ``max_attempts`` is **quarantined**: it lands in
``JOB_STATE_ERROR``, which the history builder already excludes from the
TPE fit, instead of poisoning the fit or killing the run
(:class:`TrialQuarantined` carries the last error for the driver to
record).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from dataclasses import dataclass

# single source of truth for the queue's lease TTL default — the policy
# default and the queue-side default must not drift apart
from ..parallel.file_trials import DEFAULT_LEASE_TTL


class TrialTimeout(Exception):
    """The objective exceeded the per-trial ``trial_timeout`` watchdog."""


class TrialQuarantined(Exception):
    """A trial exhausted ``max_attempts`` and was quarantined.

    ``last_error`` is the exception from the final attempt; ``attempts``
    the number of executions that were tried."""

    def __init__(self, msg, last_error=None, attempts=0):
        super().__init__(msg)
        self.last_error = last_error
        self.attempts = attempts


@dataclass(frozen=True)
class RetryPolicy:
    """Knobs of the fault-tolerance layer (``fmin(retry_policy=...)``).

    - ``max_attempts``: executions a trial may consume (reservations by
      workers and in-place retries both count) before quarantine.
    - ``backoff_base`` / ``backoff_multiplier`` / ``backoff_max``:
      attempt *k* (1-based) sleeps
      ``min(base * multiplier**(k-1), backoff_max)`` scaled by jitter.
    - ``jitter``: relative jitter width; the factor is deterministic in
      ``(seed, key, attempt)`` and lies in ``[1-jitter, 1+jitter]``.
    - ``trial_timeout``: per-trial objective watchdog in seconds (None
      disables).  Orthogonal to ``fmin``'s global ``timeout``.
    - ``lease_ttl``: heartbeat lease time-to-live for FileTrials
      reservations (see :mod:`hyperopt_tpu.resilience.leases`).
    - ``reap_interval``: reaper scan period; None → ``lease_ttl / 4``.
    - ``seed``: jitter seed (campaign reproducibility).
    """

    max_attempts: int = 3
    backoff_base: float = 0.1
    backoff_multiplier: float = 2.0
    backoff_max: float = 30.0
    jitter: float = 0.1
    trial_timeout: float | None = None
    lease_ttl: float = DEFAULT_LEASE_TTL
    reap_interval: float | None = None
    seed: int = 0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        if self.lease_ttl <= 0:
            raise ValueError(
                f"lease_ttl must be positive, got {self.lease_ttl}"
            )

    @property
    def effective_reap_interval(self) -> float:
        if self.reap_interval is not None:
            return self.reap_interval
        return self.lease_ttl / 4.0

    # -- (de)serialization for the queue attachment --------------------
    def to_json(self) -> bytes:
        """Encode for the ``FMinIter_RetryPolicy`` queue attachment, so
        out-of-process workers inherit the driver's policy."""
        return json.dumps(
            {f: getattr(self, f) for f in self.__dataclass_fields__},
            sort_keys=True,
        ).encode()

    @classmethod
    def from_json(cls, blob: bytes) -> "RetryPolicy":
        d = json.loads(blob.decode())
        known = {k: v for k, v in d.items() if k in cls.__dataclass_fields__}
        return cls(**known)


def _unit_hash(*parts) -> float:
    """Deterministic uniform in [0, 1) from arbitrary hashable parts."""
    h = hashlib.sha256(":".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "big") / 2 ** 64


def backoff_delay(policy: RetryPolicy, attempt: int, key=0) -> float:
    """Sleep before attempt ``attempt + 1`` (``attempt`` is 1-based, the
    attempt that just failed).  Exponential in the attempt number, capped
    at ``backoff_max``, scaled by deterministic jitter so concurrent
    retries for different trials decorrelate without breaking seed
    reproducibility."""
    base = policy.backoff_base * policy.backoff_multiplier ** (attempt - 1)
    base = min(base, policy.backoff_max)
    if policy.jitter:
        frac = _unit_hash(policy.seed, key, attempt)
        base *= 1.0 + policy.jitter * (2.0 * frac - 1.0)
    return base


class CircuitOpenError(Exception):
    """The circuit breaker is open: the peer has failed ``threshold``
    consecutive times and the cooldown has not elapsed.  ``retry_in``
    says how long until the breaker half-opens for a probe."""

    def __init__(self, msg, retry_in=0.0):
        super().__init__(msg)
        self.retry_in = float(retry_in)


class CircuitBreaker:
    """Trip-after-N circuit breaker for a flaky peer (the service
    client wraps every HTTP round-trip in one).

    Closed → open after ``threshold`` CONSECUTIVE transport failures
    (an HTTP error response counts as success at this layer: the peer
    answered).  While open, :meth:`before_request` reports how long
    until the next probe is allowed; after ``cooldown`` seconds the
    breaker half-opens — ONE caller gets through, and its outcome
    closes or re-opens the circuit.  Thread-safe.
    """

    # lock-order: _lock
    def __init__(self, threshold=5, cooldown=1.0, clock=time.monotonic):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = int(threshold)
        self.cooldown = float(cooldown)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0  # guarded-by: _lock
        self._opened_at = None  # guarded-by: _lock
        self._probing = False  # guarded-by: _lock

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self._probing:
                return "half-open"
            if self._clock() - self._opened_at >= self.cooldown:
                return "half-open"
            return "open"

    def before_request(self) -> float:
        """0.0 = proceed (and, when half-open, this caller IS the
        probe); > 0.0 = the breaker is open for that many more seconds
        and the caller must wait or fail fast."""
        with self._lock:
            if self._opened_at is None:
                return 0.0
            remaining = self.cooldown - (self._clock() - self._opened_at)
            if remaining > 0.0:
                return remaining
            if self._probing:
                # someone else holds the half-open probe slot
                return self.cooldown / 2.0
            self._probing = True
            return 0.0

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self):
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._failures >= self.threshold:
                self._opened_at = self._clock()


def run_with_timeout(fn, timeout, stats=None):
    """Run ``fn()`` under a watchdog: raises :class:`TrialTimeout` after
    ``timeout`` seconds.  The objective runs in a short-lived daemon
    thread; on timeout the thread is *abandoned* (Python cannot kill it),
    so a hung objective leaks one sleeping thread — the price of not
    hanging the whole run.  A late result from an abandoned attempt is
    discarded, never delivered."""
    if timeout is None:
        return fn()
    box = {}
    done = threading.Event()

    def _target():
        try:
            box["result"] = fn()
        except BaseException as e:  # delivered to the caller below
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(
        target=_target, name="hyperopt-trial-watchdog", daemon=True
    )
    t.start()
    if not done.wait(timeout):
        if stats is not None:
            stats.record("objective_timeout")
        raise TrialTimeout(f"objective exceeded trial_timeout={timeout}s")
    if "error" in box:
        raise box["error"]
    return box["result"]


def execute_with_retry(
    fn,
    policy: RetryPolicy,
    key=0,
    stats=None,
    first_attempt: int = 1,
    sleep=time.sleep,
    on_retry=None,
):
    """Run ``fn()`` under ``policy``: up to ``max_attempts`` executions,
    backoff+jitter between them, per-attempt watchdog when
    ``trial_timeout`` is set.

    ``first_attempt`` lets a caller that already burned attempts (a
    worker resuming a reclaimed trial with a doc-recorded attempt
    counter) start the accounting mid-way.  ``on_retry(attempt, error)``
    is called before each backoff sleep (workers use it to renew their
    lease and checkpoint the attempt counter).

    Returns ``(result, attempts_used)``.  Raises
    :class:`TrialQuarantined` (chained to the last error) when the
    budget is exhausted."""
    attempt = max(int(first_attempt), 1)
    while True:
        try:
            result = run_with_timeout(fn, policy.trial_timeout, stats=stats)
            return result, attempt
        except Exception as e:
            if stats is not None:
                stats.record("trial_failure")
            if attempt >= policy.max_attempts:
                if stats is not None:
                    stats.record("trial_quarantined")
                raise TrialQuarantined(
                    f"trial quarantined after {attempt} attempt(s): {e!r}",
                    last_error=e,
                    attempts=attempt,
                ) from e
            if on_retry is not None:
                on_retry(attempt, e)
            delay = backoff_delay(policy, attempt, key=key)
            if stats is not None:
                stats.record("trial_retried")
                stats.record_backoff(delay)
            sleep(delay)
            attempt += 1
