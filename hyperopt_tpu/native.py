"""ctypes loader/builder for the native runtime components.

Native policy (SURVEY.md §2): the reference is pure Python, so no native
code is required for parity — but the rebuild's control plane gets a C++
fast path for the FileTrials queue scan (``native/fastqueue.cpp``), built
on demand with g++ and loaded via ctypes (no pybind11 dependency).  Every
native entry point has a pure-Python fallback; a build failure degrades
gracefully to the Python implementation.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO_ROOT, "native", "fastqueue.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "_build")
_LIB = os.path.join(_BUILD_DIR, "libfastqueue.so")

_lock = threading.Lock()
# Double-checked load: _lock guards every WRITE of the two state
# globals; the lock-free fast-path reads in load_fastqueue are benign
# (each global flips exactly once, unset -> settled) and are marked
# inline where they occur.
_lib = None  # guarded-by: _lock
_lib_failed = False  # guarded-by: _lock


def _build():
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-o", _LIB, _SRC,
    ]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)


def load_fastqueue():
    """The fastqueue library handle, or None if unavailable."""
    global _lib, _lib_failed
    # lock-free fast path of the double-checked load (benign: settled
    # values never change again)
    if _lib is not None or _lib_failed:  # lint: disable=RL301
        return _lib  # lint: disable=RL301
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_LIB) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
            ):
                _build()
            lib = ctypes.CDLL(_LIB)
            lib.fq_count_states.restype = ctypes.c_int
            lib.fq_count_states.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_long),
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_long),
            ]
            lib.fq_list_state.restype = ctypes.c_int
            lib.fq_list_state.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_long),
                ctypes.c_int,
            ]
            lib.fq_try_lock.restype = ctypes.c_int
            lib.fq_try_lock.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
            _lib = lib
        except (OSError, subprocess.SubprocessError, FileNotFoundError) as e:
            logger.info("fastqueue native build unavailable: %s", e)
            _lib_failed = True
    # post-settle read outside the lock (benign, see note at the top)
    return _lib  # lint: disable=RL301


def count_states(trials_dir, n_states=8):
    """(counts list, n_docs) via the native scanner; None → use Python."""
    lib = load_fastqueue()
    if lib is None:
        return None
    counts = (ctypes.c_long * n_states)()
    unparsed = ctypes.c_long(0)
    n = lib.fq_count_states(
        trials_dir.encode(), counts, n_states, ctypes.byref(unparsed)
    )
    if n < 0 or unparsed.value > 0:
        return None  # fall back to the exact Python parser
    return list(counts), n


def list_state(trials_dir, state, max_out=1 << 16):
    lib = load_fastqueue()
    if lib is None:
        return None
    tids = (ctypes.c_long * max_out)()
    n = lib.fq_list_state(trials_dir.encode(), int(state), tids, max_out)
    if n < 0:
        return None
    return [tids[i] for i in range(n)]


def try_lock(lock_path, owner):
    """1 locked, 0 already locked, None → use the Python primitive."""
    lib = load_fastqueue()
    if lib is None:
        return None
    r = lib.fq_try_lock(lock_path.encode(), owner.encode())
    return None if r < 0 else r
