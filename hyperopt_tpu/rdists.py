"""Exact scipy-style mirrors of the DSL distributions.

Reference parity (SURVEY.md §2 #22): ``hyperopt/rdists.py`` —
``loguniform_gen``, ``lognorm_tx_gen``, ``quniform_gen``,
``qloguniform_gen``, ``qnormal_gen``, ``qlognormal_gen``: closed-form
pdfs/cdfs/pmfs for every ``hp.*`` distribution, used by the statistical
(KS / total-variation) conformance tests to pin the compiled JAX sampler to
the exact semantics.
"""

from __future__ import annotations

import numpy as np
from scipy import stats
from scipy.stats import rv_continuous


class loguniform_gen(rv_continuous):
    """x with log(x) ~ Uniform(low, high); support [e^low, e^high]."""

    def __init__(self, low=0, high=1):
        super().__init__(a=np.exp(low), b=np.exp(high), name="loguniform")
        self._low = low
        self._high = high

    def _pdf(self, x):
        return 1.0 / (x * (self._high - self._low))

    def _logpdf(self, x):
        return -np.log(x) - np.log(self._high - self._low)

    def _cdf(self, x):
        return (np.log(x) - self._low) / (self._high - self._low)


class lognorm_tx_gen:
    """exp(Normal(mu, sigma)) — thin adapter over scipy.stats.lognorm."""

    def __init__(self, mu, sigma):
        self._dist = stats.lognorm(s=sigma, scale=np.exp(mu))

    def __getattr__(self, name):
        return getattr(self._dist, name)


class _QuantizedBase:
    """Discrete distribution over the quantization grid {k·q}.

    ``pmf(v) = F(min(v+q/2, hi)) − F(max(v−q/2, lo))`` where F is the
    underlying continuous CDF — exactly the mass that rounds to v.
    """

    def __init__(self, q):
        self.q = q

    # subclasses: _base_cdf(x), support()
    def _bucket(self, v):
        v = np.asarray(v, dtype=float)
        ub = v + self.q / 2.0
        lb = v - self.q / 2.0
        return lb, ub

    def pmf(self, v):
        v = np.asarray(v, dtype=float)
        on_grid = np.isclose(np.round(v / self.q) * self.q, v, atol=1e-9)
        lb, ub = self._bucket(v)
        p = self._base_cdf(ub) - self._base_cdf(lb)
        return np.where(on_grid, np.maximum(p, 0.0), 0.0)

    def logpmf(self, v):
        with np.errstate(divide="ignore"):
            return np.log(self.pmf(v))

    def cdf(self, v):
        lb, ub = self._bucket(v)
        return self._base_cdf(ub)

    def rvs(self, size=(), random_state=None):
        rng = np.random.default_rng(random_state)
        x = self._base_rvs(size, rng)
        return np.round(x / self.q) * self.q


class quniform_gen(_QuantizedBase):
    def __init__(self, low, high, q):
        super().__init__(q)
        self.low, self.high = low, high

    def _base_cdf(self, x):
        return np.clip((np.asarray(x) - self.low) / (self.high - self.low), 0, 1)

    def _base_rvs(self, size, rng):
        return rng.uniform(self.low, self.high, size=size)

    def support(self):
        lo = np.round(self.low / self.q) * self.q
        hi = np.round(self.high / self.q) * self.q
        return np.arange(lo, hi + self.q / 2, self.q)


class qloguniform_gen(_QuantizedBase):
    def __init__(self, low, high, q):
        super().__init__(q)
        self.low, self.high = low, high  # log-space bounds

    def _base_cdf(self, x):
        x = np.asarray(x, dtype=float)
        with np.errstate(divide="ignore"):
            lx = np.where(x > 0, np.log(np.maximum(x, 1e-300)), -np.inf)
        return np.clip((lx - self.low) / (self.high - self.low), 0, 1)

    def _base_rvs(self, size, rng):
        return np.exp(rng.uniform(self.low, self.high, size=size))

    def support(self):
        lo = np.round(np.exp(self.low) / self.q) * self.q
        hi = np.round(np.exp(self.high) / self.q) * self.q
        return np.arange(max(lo, 0.0), hi + self.q / 2, self.q)


class qnormal_gen(_QuantizedBase):
    def __init__(self, mu, sigma, q):
        super().__init__(q)
        self.mu, self.sigma = mu, sigma

    def _base_cdf(self, x):
        return stats.norm.cdf(x, loc=self.mu, scale=self.sigma)

    def _base_rvs(self, size, rng):
        return rng.normal(self.mu, self.sigma, size=size)


class qlognormal_gen(_QuantizedBase):
    def __init__(self, mu, sigma, q):
        super().__init__(q)
        self.mu, self.sigma = mu, sigma

    def _base_cdf(self, x):
        x = np.asarray(x, dtype=float)
        return np.where(
            x > 0,
            stats.lognorm.cdf(np.maximum(x, 1e-300), s=self.sigma, scale=np.exp(self.mu)),
            0.0,
        )

    def _base_rvs(self, size, rng):
        return np.exp(rng.normal(self.mu, self.sigma, size=size))
