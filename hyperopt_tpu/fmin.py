"""The fmin driver loop.

Reference parity (SURVEY.md §2 #7): ``hyperopt/fmin.py`` —
``fmin_pass_expr_memo_ctrl`` (~L30-60), ``generate_trial``/
``generate_trials_to_calculate`` (~L60-130), ``FMinIter`` (~L130-500),
``fmin`` full signature (~L500-700), ``space_eval`` (~L700-730).

The driver is host-side orchestration by design: suggest runs on device
(jitted), the objective is arbitrary user Python, and this loop shuttles
sparse trial docs between them.  Async backends (JaxTrials/FileTrials) set
``trials.asynchronous`` and the loop becomes enqueue + poll, exactly like
the reference's Spark/Mongo paths.
"""

from __future__ import annotations

import contextlib
import logging
import os
import pickle
import sys
import time
from timeit import default_timer as timer

import numpy as np

from . import progress
from .base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    STATUS_OK,
    Ctrl,
    Domain,
    Trials,
    spec_from_misc,
    trials_from_docs,
    validate_loss_threshold,
    validate_timeout,
)
from .utils import coarse_utcnow
from .vectorize import CompiledSpace

logger = logging.getLogger(__name__)

# Default speculation depth for the pipelined suggest engine (see
# hyperopt_tpu.pipeline): while the objective for trial t evaluates in a
# worker thread, the device suggest program for trial t+1..t+k runs
# speculatively against the current history.  0 = the strictly serial
# loop (suggest and evaluate times add).  Overridable per call via
# ``fmin(max_speculation=...)`` or globally via the env var — read at
# call time, so setting it after import still takes effect.
def _default_max_speculation():
    return int(os.environ.get("HYPEROPT_MAX_SPECULATION", "1"))


def fmin_pass_expr_memo_ctrl(f):
    """Decorator: mark ``f`` as wanting (expr, memo, ctrl) instead of a
    sampled point (reference: ``hyperopt/fmin.py — fmin_pass_expr_memo_ctrl``)."""
    f.fmin_pass_expr_memo_ctrl = True
    return f


def generate_trial(tid, space):
    """Build one warm-start trial document from a {label: value} point."""
    variables = space.keys()
    idxs = {v: [tid] for v in variables}
    vals = {v: [space[v]] for v in variables}
    return {
        "state": JOB_STATE_NEW,
        "tid": tid,
        "spec": None,
        "result": {"status": "new"},
        "misc": {
            "tid": tid,
            "cmd": ("domain_attachment", "FMinIter_Domain"),
            "idxs": idxs,
            "vals": vals,
        },
        "exp_key": None,
        "owner": None,
        "version": 0,
        "book_time": None,
        "refresh_time": None,
    }


def generate_trials_to_calculate(points):
    """Trials pre-loaded with explicit points (``points_to_evaluate``)."""
    return trials_from_docs(
        [generate_trial(tid, x) for tid, x in enumerate(points)]
    )


class FMinIter:
    """The suggest → evaluate → refresh loop, sync or async."""

    catch_eval_exceptions = False
    pickle_protocol = -1
    is_cancelled = False

    def __init__(
        self,
        algo,
        domain,
        trials,
        rstate,
        asynchronous=None,
        max_queue_len=1,
        poll_interval_secs=None,
        max_evals=sys.maxsize,
        timeout=None,
        loss_threshold=None,
        verbose=False,
        show_progressbar=True,
        early_stop_fn=None,
        trials_save_file="",
        orbax_ckpt=None,
        max_speculation=None,
        retry_policy=None,
        fault_stats=None,
        search_stats=None,
    ):
        self.algo = algo
        self.domain = domain
        self.trials = trials
        self.retry_policy = retry_policy
        if max_speculation is None:
            max_speculation = _default_max_speculation()
        self.max_speculation = max_speculation
        self._engine = None
        if asynchronous is None:
            self.asynchronous = trials.asynchronous
        else:
            self.asynchronous = asynchronous
        if poll_interval_secs is None:
            # in-process async backends (JaxTrials) advertise a fast poll;
            # remote queues (FileTrials) a slower one
            poll_interval_secs = getattr(trials, "poll_interval_secs", 1.0)
        self.poll_interval_secs = poll_interval_secs
        self.max_queue_len = max_queue_len
        self.max_evals = max_evals
        self.timeout = timeout
        self.loss_threshold = loss_threshold
        self.start_time = timer()
        self.rstate = rstate
        self.verbose = verbose
        self.show_progressbar = show_progressbar
        self.early_stop_fn = early_stop_fn
        self.early_stop_args = []
        self.trials_save_file = trials_save_file
        self._orbax_ckpt = orbax_ckpt
        if orbax_ckpt is None and trials_save_file != "":
            from .checkpoint import TrialsCheckpointer, is_orbax_path

            if is_orbax_path(trials_save_file):
                # direct FMinIter construction (no fmin() wrapper)
                self._orbax_ckpt = TrialsCheckpointer(trials_save_file)
        from .observability import FaultStats, PhaseTimings, SpeculationStats

        self.timings = PhaseTimings()
        self.speculation_stats = SpeculationStats()
        self.fault_stats = fault_stats if fault_stats is not None else FaultStats()
        if search_stats is None:
            from .diagnostics import SearchStats

            # best-effort startup horizon: a partial-as-config algo
            # (partial(tpe.suggest, n_startup_jobs=...)) declares it in
            # its keywords; plain algos get the TPE default
            n_startup = getattr(algo, "keywords", None) or {}
            search_stats = SearchStats(
                n_startup_jobs=int(n_startup.get("n_startup_jobs", 20)),
                fault_stats=self.fault_stats,
            )
        self.search_stats = search_stats
        from .resilience.device import DeviceRecovery

        # wraps every suggest-program dispatch: XLA/TPU runtime errors
        # trigger bounded re-initialization, then a CPU-backend fallback
        # (see hyperopt_tpu.resilience.device) — the run survives device
        # preemption instead of aborting
        self.device_recovery = DeviceRecovery(stats=self.fault_stats)

        if self.asynchronous:
            if self.retry_policy is not None:
                # out-of-process workers inherit the driver's retry
                # policy through this attachment (backoff, timeouts,
                # lease TTL, attempt budget all agree across the run)
                try:
                    trials.attachments["FMinIter_RetryPolicy"] = (
                        self.retry_policy.to_json()
                    )
                except Exception:
                    logger.info(
                        "could not persist retry policy attachment; "
                        "workers fall back to their own defaults",
                        exc_info=True,
                    )
                if getattr(trials, "jobs", None) is not None:
                    # the policy's lease_ttl IS the run's lease TTL:
                    # apply it to this queue handle so the reaper's
                    # expiry clock and stale-lock aging agree with the
                    # leases workers will grant under the same policy
                    trials.jobs.lease_ttl = self.retry_policy.lease_ttl
            else:
                # a resumed run without a policy must not leave workers
                # obeying a previous run's attachment
                try:
                    del trials.attachments["FMinIter_RetryPolicy"]
                except KeyError:
                    pass
                except Exception:
                    logger.info(
                        "could not clear stale retry policy attachment",
                        exc_info=True,
                    )
            if "FMinIter_Domain" not in trials.attachments:
                # out-of-process workers (FileTrials) unpickle the domain
                # from this attachment; in-process backends (JaxTrials)
                # don't need it, so unpicklable objectives are fine there
                try:
                    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
                except (pickle.PicklingError, AttributeError, TypeError) as e:
                    logger.info(
                        "domain not picklable (%s); out-of-process workers "
                        "will not be able to fetch it",
                        e,
                    )

    def _evaluate_trial(self, spec, ctrl, trial):
        """One objective evaluation under the run's retry policy (when
        set): backoff + deterministic jitter between attempts, per-trial
        watchdog timeout, :class:`~hyperopt_tpu.resilience.retry.
        TrialQuarantined` after ``max_attempts`` — which the callers
        translate to ``JOB_STATE_ERROR`` and keep running (quarantine is
        the catch, independent of ``catch_eval_exceptions``)."""
        if self.retry_policy is None:
            return self.domain.evaluate(spec, ctrl)
        from .resilience.retry import execute_with_retry

        result, attempts = execute_with_retry(
            lambda: self.domain.evaluate(spec, ctrl),
            self.retry_policy,
            key=trial["tid"],
            stats=self.fault_stats,
        )
        trial["misc"]["attempts"] = attempts
        return result

    def serial_evaluate(self, N=-1):
        from .resilience.retry import TrialQuarantined

        for trial in self.trials._dynamic_trials:
            if trial["state"] == JOB_STATE_NEW:
                trial["state"] = JOB_STATE_RUNNING
                now = coarse_utcnow()
                trial["book_time"] = now
                trial["refresh_time"] = now
                spec = spec_from_misc(trial["misc"])
                ctrl = Ctrl(self.trials, current_trial=trial)
                try:
                    result = self._evaluate_trial(spec, ctrl, trial)
                except TrialQuarantined as e:
                    # the retry budget is exhausted: quarantine the trial
                    # (error state excludes it from the TPE fit) and keep
                    # the run alive — that is the policy's whole point
                    logger.error("trial %s quarantined: %s", trial["tid"], e)
                    trial["state"] = JOB_STATE_ERROR
                    trial["misc"]["attempts"] = e.attempts
                    trial["misc"]["error"] = (
                        str(type(e.last_error)), str(e.last_error)
                    )
                    trial["refresh_time"] = coarse_utcnow()
                except Exception as e:
                    logger.error("job exception: %s", str(e))
                    trial["state"] = JOB_STATE_ERROR
                    trial["misc"]["error"] = (str(type(e)), str(e))
                    trial["refresh_time"] = coarse_utcnow()
                    if not self.catch_eval_exceptions:
                        raise
                else:
                    trial["state"] = JOB_STATE_DONE
                    trial["result"] = result
                    trial["refresh_time"] = coarse_utcnow()
                N -= 1
                if N == 0:
                    break
        self.trials.refresh()

    def _serial_evaluate_pipelined(self, engine, budget):
        """serial_evaluate with suggest/evaluate overlap: the objective for
        each NEW trial runs in a short-lived daemon worker thread while
        this (main) thread speculatively launches the suggest program(s)
        for the next trial(s) through ``engine`` (at most ``budget`` more
        suggestions will ever be consumed this run, so speculation is
        capped there too).  Doc mutations mirror serial_evaluate exactly;
        on an objective exception the pending speculations are discarded
        (their in-flight device work is abandoned) and the exception
        propagates unless ``catch_eval_exceptions``.  The worker is a
        daemon and the main thread's join is signal-interruptible, so
        Ctrl-C still aborts fmin mid-objective just like the serial loop.
        """
        import threading

        from .resilience.retry import TrialQuarantined

        for trial in self.trials._dynamic_trials:
            if trial["state"] != JOB_STATE_NEW:
                continue
            trial["state"] = JOB_STATE_RUNNING
            now = coarse_utcnow()
            trial["book_time"] = now
            trial["refresh_time"] = now
            spec = spec_from_misc(trial["misc"])
            ctrl = Ctrl(self.trials, current_trial=trial)
            box = {}

            def _evaluate(spec=spec, ctrl=ctrl, box=box, trial=trial):
                try:
                    box["result"] = self._evaluate_trial(spec, ctrl, trial)
                except BaseException as e:
                    box["error"] = e

            worker = threading.Thread(
                target=_evaluate, name="hyperopt-eval", daemon=True
            )
            worker.start()
            try:
                try:
                    # overlap window: launch speculative suggests while
                    # the objective runs; device compute proceeds in
                    # background
                    engine.speculate(limit=budget)
                except Exception as spec_err:
                    # speculation is an optimization — a dispatch failure
                    # (device error, bucket-growth compile OOM) must not
                    # discard the objective's result or wedge the trial
                    # in RUNNING; drop the speculations and run serially.
                    # A device error additionally re-inits through the
                    # recovery (else the synchronous recompute hits the
                    # same dead executable).
                    logger.exception(
                        "speculative dispatch failed; continuing serially"
                    )
                    self.device_recovery.absorb(spec_err)
                    engine.discard()
            finally:
                # even a non-Exception failure must not abandon the
                # trial mid-flight
                worker.join()
            if "error" in box:
                e = box["error"]
                if not isinstance(e, Exception):
                    # BaseException (SystemExit, ...): serial_evaluate
                    # would not catch it either — propagate unconditionally
                    engine.discard()
                    raise e
                if isinstance(e, TrialQuarantined):
                    # retry budget exhausted: quarantine and continue —
                    # the pending speculations hypothesized this trial
                    # completing into the above set, so the validity
                    # check will re-issue them against the error outcome
                    logger.error("trial %s quarantined: %s", trial["tid"], e)
                    trial["state"] = JOB_STATE_ERROR
                    trial["misc"]["attempts"] = e.attempts
                    trial["misc"]["error"] = (
                        str(type(e.last_error)), str(e.last_error)
                    )
                    trial["refresh_time"] = coarse_utcnow()
                    continue
                logger.error("job exception: %s", str(e))
                trial["state"] = JOB_STATE_ERROR
                trial["misc"]["error"] = (str(type(e)), str(e))
                trial["refresh_time"] = coarse_utcnow()
                if not self.catch_eval_exceptions:
                    engine.discard()
                    self.trials.refresh()
                    raise e
            else:
                trial["state"] = JOB_STATE_DONE
                trial["result"] = box["result"]
                trial["refresh_time"] = coarse_utcnow()
        self.trials.refresh()

    def block_until_done(self):
        already_printed = False
        if self.asynchronous:
            unfinished_states = [JOB_STATE_NEW, JOB_STATE_RUNNING]

            def get_queue_len():
                return self.trials.count_by_state_unsynced(unfinished_states)

            qlen = get_queue_len()
            while qlen > 0:
                if not already_printed and self.verbose:
                    logger.info("Waiting for %d jobs to finish ...", qlen)
                    already_printed = True
                time.sleep(self.poll_interval_secs)
                qlen = get_queue_len()
            self.trials.refresh()
        else:
            self.serial_evaluate()

    def run(self, N, block_until_done=True):
        """Enqueue and run up to ``N`` new trials."""
        trials = self.trials
        algo = self.algo
        n_queued = 0

        def get_queue_len():
            return self.trials.count_by_state_unsynced(JOB_STATE_NEW)

        def get_n_done():
            return self.trials.count_by_state_unsynced(JOB_STATE_DONE)

        def get_n_unfinished():
            unfinished_states = [JOB_STATE_NEW, JOB_STATE_RUNNING]
            return self.trials.count_by_state_unsynced(unfinished_states)

        # pipelined suggest engine (max_speculation > 0): overlap the
        # device suggest program with objective evaluation.  k=0 keeps
        # the original strictly-serial path below, bit-for-bit.  In the
        # synchronous driver the engine only engages at queue length 1
        # (the fmin default): a wider queue enqueues several ids through
        # ONE algo call with ONE seed, which a 1-id speculation plus an
        # (n-1)-id sync call would silently re-seed — batched enqueues
        # keep the serial path instead.  The asynchronous plane has no
        # serial trajectory to preserve and always gets the prefetch.
        # Ctrl-receiving objectives (pass_expr_memo_ctrl) can mutate the
        # trials store from the evaluation worker while this thread
        # speculates against it — those keep the serial loop, where
        # driver and objective never run concurrently.
        engine = None
        use_engine = (
            self.max_speculation
            and self.max_speculation > 0
            and (self.asynchronous or self.max_queue_len == 1)
            and not getattr(self.domain, "pass_expr_memo_ctrl", False)
        )
        if use_engine:
            from .pipeline import SpeculativeSuggestEngine

            if self._engine is None:
                self._engine = SpeculativeSuggestEngine(
                    algo,
                    self.domain,
                    trials,
                    self.rstate,
                    max_speculation=self.max_speculation,
                    stats=self.speculation_stats,
                    device_recovery=self.device_recovery,
                )
            engine = self._engine
            if engine.policy == "strict":
                # the engine never speculates for an algorithm without a
                # declared policy (see hyperopt_tpu.pipeline) — skip the
                # per-trial worker thread too and keep the serial loop,
                # where main-thread-only objectives also keep working
                engine = None

        stopped = False
        initial_n_done = get_n_done()
        progress_callback = (
            progress.default_callback
            if self.show_progressbar
            else progress.no_progress_callback
        )
        with contextlib.ExitStack() as _stack:
            if engine is not None:
                # on every exit path, drop speculations that will never
                # be consumed (normal completion leaves none thanks to
                # the budget cap; early stops / exceptions may)
                _stack.callback(engine.discard)
            if self.asynchronous and getattr(self.trials, "jobs", None) is not None:
                # durable-queue backend (FileTrials): run the lease
                # reaper for the duration of the run — dead workers'
                # trials are reclaimed and re-queued automatically, and
                # torn/stale lock files are GC'd (the automatic
                # replacement for the never-invoked requeue_stale)
                from .resilience.leases import LeaseReaper

                reaper = LeaseReaper(
                    self.trials,
                    policy=self.retry_policy,
                    stats=self.fault_stats,
                )
                _stack.enter_context(reaper)
            progress_ctx = _stack.enter_context(
                progress_callback(initial=0, total=N)
            )
            all_trials_complete = False
            best_loss = float("inf")
            n_displayed = 0
            while (
                # more trials to enqueue, or
                n_queued < N
                # block until all queued trials finish
                or (block_until_done and not all_trials_complete)
            ):
                qlen = get_queue_len()
                while (
                    qlen < self.max_queue_len and n_queued < N and not self.is_cancelled
                ):
                    n_to_enqueue = min(self.max_queue_len - qlen, N - n_queued)
                    if engine is not None:
                        # consumes a validated speculation when one is
                        # pending (readback only), else computes in line
                        with self.timings.phase("suggest"):
                            new_trials, new_ids = engine.next_batch(n_to_enqueue)
                    else:
                        new_ids = trials.new_trial_ids(n_to_enqueue)
                        self.trials.refresh()
                        seed = self.rstate.integers(2 ** 31 - 1)
                        with self.timings.phase("suggest"):
                            # device errors (preemption, OOM, disconnect)
                            # re-init and retry rather than abort the run
                            new_trials = self.device_recovery.run(
                                lambda: algo(
                                    new_ids, self.domain, trials, seed
                                )
                            )
                    # search-health telemetry: the fused readback's
                    # EI/Parzen snapshot was published on this thread by
                    # the suggest's finish (None on host-side/random
                    # suggests) — fold it into the run's SearchStats
                    from . import diagnostics as _search_diag

                    self.search_stats.record_suggest(
                        _search_diag.last_suggest_diag()
                    )
                    if new_trials is None:
                        stopped = True
                        break
                    assert len(new_ids) >= len(new_trials)
                    if len(new_trials):
                        self.trials.insert_trial_docs(new_trials)
                        self.trials.refresh()
                        n_queued += len(new_trials)
                        qlen = get_queue_len()
                    else:
                        stopped = True
                        break

                if self.is_cancelled:
                    break

                if self.asynchronous:
                    if engine is not None:
                        try:
                            # prefetch the next suggestion(s) while the
                            # backend's workers evaluate — the batched
                            # plane rides the same speculation machinery
                            # as the serial loop instead of a suggest
                            # barrier
                            engine.speculate(limit=N - n_queued)
                        except Exception as spec_err:
                            # same contract as the sync plane: a failed
                            # speculative dispatch degrades to the
                            # serial protocol, it doesn't abort the run
                            logger.exception(
                                "speculative dispatch failed; continuing "
                                "without prefetch"
                            )
                            self.device_recovery.absorb(spec_err)
                            engine.discard()
                    # wait for workers to fill in the trials
                    time.sleep(self.poll_interval_secs)
                else:
                    # run the trials synchronously in this process
                    with self.timings.phase("evaluate"):
                        if engine is not None:
                            self._serial_evaluate_pipelined(
                                engine, budget=N - n_queued
                            )
                        else:
                            self.serial_evaluate()

                self.trials.refresh()
                # fold this round's completions (OK losses incl. NaN,
                # error-state count) into the run's search health
                self.search_stats.observe_trials(self.trials)
                if self.trials_save_file != "":
                    if self._orbax_ckpt is not None:
                        self._orbax_ckpt.save(self.trials)
                    else:
                        # fsync'd write-then-rename: a crash mid-save can
                        # never tear the checkpoint the next run resumes
                        # from (see hyperopt_tpu.checkpoint)
                        from .checkpoint import atomic_pickle_dump

                        atomic_pickle_dump(
                            self.trials,
                            self.trials_save_file,
                            protocol=self.pickle_protocol,
                        )
                if self.early_stop_fn is not None:
                    stop, kwargs = self.early_stop_fn(
                        self.trials, *self.early_stop_args
                    )
                    self.early_stop_args = kwargs
                    if stop:
                        logger.info(
                            "Early stop triggered from %s", self.early_stop_fn.__name__
                        )
                        stopped = True

                n_unfinished = get_n_unfinished()
                if n_unfinished == 0:
                    all_trials_complete = True

                n_done = get_n_done()
                n_okay = n_done - initial_n_done
                progress_ctx.update(n_okay - n_displayed)
                n_displayed = n_okay

                # update progress bar with the best loss so far
                losses = [
                    loss
                    for loss, status in zip(
                        self.trials.losses(), self.trials.statuses()
                    )
                    if status == STATUS_OK and loss is not None
                ]
                if losses:
                    new_best = min(losses)
                    if new_best < best_loss:
                        best_loss = new_best
                        progress_ctx.postfix = f"best loss: {best_loss}"
                    if (
                        self.loss_threshold is not None
                        and best_loss <= self.loss_threshold
                    ):
                        stopped = True

                if self.timeout is not None and (
                    timer() - self.start_time >= self.timeout
                ):
                    stopped = True

                if stopped:
                    break

            if block_until_done:
                self.block_until_done()
            self.trials.refresh()
            if self.verbose:
                self.timings.log_summary(logging.DEBUG)
                if engine is not None:
                    self.speculation_stats.log_summary(logging.DEBUG)
                self.fault_stats.log_summary(logging.DEBUG)
            logger.debug("Queue empty, exiting run.")

    def exhaust(self):
        n_done = len(self.trials)
        self.run(self.max_evals - n_done, block_until_done=self.asynchronous)
        self.trials.refresh()
        return self


def fmin(
    fn,
    space,
    algo=None,
    max_evals=None,
    timeout=None,
    loss_threshold=None,
    trials=None,
    rstate=None,
    allow_trials_fmin=True,
    pass_expr_memo_ctrl=None,
    catch_eval_exceptions=False,
    verbose=True,
    return_argmin=True,
    points_to_evaluate=None,
    max_queue_len=1,
    show_progressbar=True,
    early_stop_fn=None,
    trials_save_file="",
    max_speculation=None,
    validate_space=False,
    retry_policy=None,
    fault_stats=None,
    search_stats=None,
):
    """Minimize ``fn`` over ``space`` — the reference's full signature.

    ``algo`` defaults to TPE.  ``rstate`` (a ``np.random.Generator``) makes
    the whole run deterministic, including the device-side jitted sampling
    (per-suggest seeds are drawn from it and turned into JAX PRNG keys).

    ``max_speculation``: speculation depth ``k`` of the pipelined suggest
    engine (:mod:`hyperopt_tpu.pipeline`) — while the objective for trial
    *t* evaluates in a worker thread, the device suggest program for
    trials *t+1…t+k* runs speculatively under the lands-above branch
    prediction (the pending trial's known parameters join g(x); its
    unknown loss only matters through γ-split membership), and is
    re-issued against the completed history when the prediction fails.
    ``0`` forces the strictly serial loop (suggest and evaluate times
    add, trajectories bit-for-bit reproduce the pre-pipeline driver).
    ``None`` (default) resolves to 1, or to ``HYPEROPT_MAX_SPECULATION``
    when set.  Runs are deterministic under a fixed ``rstate`` for every
    ``k``; at ``k=1`` with a deterministic objective the trajectory is
    trial-for-trial IDENTICAL to the serial loop (consumed speculations
    equal the post-completion serial suggestion exactly), while ``k>=2``
    additionally misses not-yet-resolved intermediate suggestions —
    bounded staleness TPE tolerates by design, traded for more overlap.
    With ``k >= 1`` the objective runs in a short-lived worker thread
    per trial; objectives that must run on the main thread (installing
    signal handlers, ``signal.alarm`` timeouts, some GUI/event-loop
    work) need ``max_speculation=0``.

    ``retry_policy``: a :class:`hyperopt_tpu.resilience.RetryPolicy`
    enabling fault-tolerant trial execution — each trial gets up to
    ``max_attempts`` executions with exponential backoff and
    deterministic jitter between them, an optional per-trial
    ``trial_timeout`` watchdog (distinct from the global ``timeout``
    above, which bounds the whole run), and quarantine on exhaustion:
    the trial lands in ``JOB_STATE_ERROR``, is excluded from the TPE
    fit, and the run continues.  With a FileTrials backend the policy
    also configures the heartbeat-lease reaper (dead-worker reclamation
    runs with default settings even when ``retry_policy`` is None) and
    is published to out-of-process workers through the
    ``FMinIter_RetryPolicy`` queue attachment.  See
    ``docs/resilience.md``.

    ``fault_stats``: a shared
    :class:`~hyperopt_tpu.observability.FaultStats` to record recovery
    events into (pass one to aggregate driver + worker + chaos
    accounting across a campaign); by default the driver owns a private
    instance, exposed as ``FMinIter.fault_stats``.

    ``search_stats``: a shared
    :class:`~hyperopt_tpu.diagnostics.SearchStats` to accumulate
    search-health telemetry into (running best / regret curve, fault
    rates, and each fused suggest's EI/Parzen snapshot — the SH5xx
    health classifier's input; see ``docs/observability.md``); by
    default the driver owns a private instance, exposed as
    ``FMinIter.search_stats``.

    ``validate_space=True`` runs the static space linter
    (:func:`hyperopt_tpu.analysis.lint_space`) before the first trial:
    error-severity findings (duplicate labels, inverted bounds,
    float32-overflowing log ranges, ...) raise
    :class:`~hyperopt_tpu.exceptions.InvalidSpaceError` immediately —
    instead of a device-side NaN many trials in — and warnings are
    logged.  Off by default: the lint walks the whole space graph,
    which is wasted work for the common already-validated space.
    """
    if validate_space:
        from .analysis import Severity, lint_space
        from .exceptions import InvalidSpaceError

        diags = lint_space(space)
        errors = [d for d in diags if d.severity == Severity.ERROR]
        for d in diags:
            if d.severity != Severity.ERROR:
                logger.warning("space lint: %s", d.format())
        if errors:
            raise InvalidSpaceError(
                "search space failed validation:\n"
                + "\n".join(d.format() for d in errors),
                diagnostics=diags,
            )

    if algo is None:
        from .algos import tpe

        algo = tpe.suggest
        logger.warning("fmin: algo not specified, defaulting to TPE")

    validate_timeout(timeout)
    validate_loss_threshold(loss_threshold)

    if rstate is None:
        env_rseed = os.environ.get("HYPEROPT_FMIN_SEED", "")
        if env_rseed:
            rstate = np.random.default_rng(int(env_rseed))
        else:
            rstate = np.random.default_rng()
    if isinstance(rstate, np.random.RandomState):  # legacy numpy API
        rstate = np.random.default_rng(rstate.randint(2 ** 31))

    if max_evals is None:
        max_evals = sys.maxsize

    orbax_ckpt = None
    if trials_save_file != "":
        from .checkpoint import TrialsCheckpointer, is_orbax_path

        if is_orbax_path(trials_save_file):
            # structured orbax checkpoint (versioned/atomic/retained):
            # resume from the latest step if the directory has one.  One
            # manager serves restore AND the run's saves (FMinIter), and
            # is closed when the run ends — orbax managers hold
            # background threads.  Restoring ``into`` a user-passed
            # trials object preserves its subclass and attachments.
            orbax_ckpt = TrialsCheckpointer(trials_save_file)
            restored = orbax_ckpt.restore(into=trials)
            if restored is not None:
                trials = restored
        elif os.path.exists(trials_save_file):
            with open(trials_save_file, "rb") as f:
                trials = pickle.load(f)

    if allow_trials_fmin and trials is not None and hasattr(trials, "fmin"):
        assert not isinstance(trials, list)
        if orbax_ckpt is not None:
            # the re-entered fmin opens its own manager on this directory
            orbax_ckpt.close()
        return trials.fmin(
            fn,
            space,
            algo=algo,
            max_evals=max_evals,
            timeout=timeout,
            loss_threshold=loss_threshold,
            max_queue_len=max_queue_len,
            rstate=rstate,
            pass_expr_memo_ctrl=pass_expr_memo_ctrl,
            verbose=verbose,
            catch_eval_exceptions=catch_eval_exceptions,
            return_argmin=return_argmin,
            show_progressbar=show_progressbar,
            early_stop_fn=early_stop_fn,
            trials_save_file=trials_save_file,
            points_to_evaluate=points_to_evaluate,
            max_speculation=max_speculation,
            retry_policy=retry_policy,
            fault_stats=fault_stats,
            search_stats=search_stats,
        )

    if trials is None:
        if points_to_evaluate is None:
            trials = Trials()
        else:
            assert isinstance(points_to_evaluate, list)
            trials = generate_trials_to_calculate(points_to_evaluate)
    elif points_to_evaluate is not None:
        if len(trials) > 0:
            raise ValueError(
                "points_to_evaluate requires an empty trials object"
            )
        for doc in (generate_trial(tid, x) for tid, x in enumerate(points_to_evaluate)):
            trials.insert_trial_doc(doc)
        trials.refresh()

    domain = Domain(fn, space, pass_expr_memo_ctrl=pass_expr_memo_ctrl)

    rval = FMinIter(
        algo,
        domain,
        trials,
        max_evals=max_evals,
        timeout=timeout,
        loss_threshold=loss_threshold,
        rstate=rstate,
        verbose=verbose,
        max_queue_len=max_queue_len,
        show_progressbar=show_progressbar,
        early_stop_fn=early_stop_fn,
        trials_save_file=trials_save_file,
        orbax_ckpt=orbax_ckpt,
        max_speculation=max_speculation,
        retry_policy=retry_policy,
        fault_stats=fault_stats,
        search_stats=search_stats,
    )
    rval.catch_eval_exceptions = catch_eval_exceptions
    try:
        rval.exhaust()
    finally:
        if orbax_ckpt is not None:
            orbax_ckpt.close()

    if return_argmin:
        if len(trials.trials) == 0:
            raise Exception(
                "There are no evaluation tasks, cannot return argmin of task losses."
            )
        return trials.argmin
    return None


def space_eval(space, hp_assignment):
    """Evaluate a search space at the point ``hp_assignment``.

    Inverse of sampling: plugs per-label values into the graph's
    hyperopt_param nodes and evaluates only the active branches (lazy
    switch), yielding the nested structure the objective would have seen.
    """
    from .pyll.base import GarbageCollected, as_apply, dfs, rec_eval

    space = as_apply(space)
    memo = {}
    for node in dfs(space):
        if node.name == "hyperopt_param":
            label = node.pos_args[0].obj
            if label in hp_assignment:
                memo[node] = hp_assignment[label]
            else:
                memo[node] = GarbageCollected
    return rec_eval(space, memo=memo)
