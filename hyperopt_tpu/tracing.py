"""End-to-end request tracing: trace ids, nested spans, a crash-tolerant log.

``BENCH_SERVE.json`` shows suggest p50 at tens of milliseconds and p99 at
tens of *seconds*, and the ROADMAP blames first-touch XLA compiles in the
request path — but endpoint-level percentiles cannot *prove* that per
request.  This module makes the service's distributed-asynchronous
evaluation model (Bergstra et al., ICML 2013) observable end-to-end:
every client call gets a **trace id** (propagated via the
``X-Hyperopt-Trace`` header and accepted from callers), each hop opens a
named **span** with monotonic timestamps, and a finished trace lands as
ONE appended record in a bounded, crash-tolerant JSONL log that
``scripts/trace_report.py`` aggregates into a phase-attributed latency
breakdown (``TRACE_SERVE.json``).

Design constraints, in priority order:

1. **Off means off.**  With sampling disabled the hot path must be a
   measurable no-op: :func:`span` costs one thread-local read and
   returns a shared null singleton — no allocation, no lock, no clock
   read.  (Acceptance: loadgen suggest p50 within 5% of untraced.)
2. **Spans never leak across threads.**  The current trace binds to a
   thread only through :func:`use_trace`; a thread that never bound one
   sees ``None`` (a new thread starts clean — ``threading.local``).
   Cross-thread handoff (HTTP handler → scheduler worker) is explicit:
   the carrier object (``_PendingSuggest``) holds the
   :class:`Trace` + parent :class:`Span`, and the worker re-binds.
3. **Crash-tolerant, bounded log.**  Every finished trace is ONE
   ``O_APPEND`` write of ``\\n<crc32 hex> <json>`` — the response
   journal's proven resync discipline (a torn tail garbles at most the
   record being written; the next record's leading newline
   re-synchronizes the reader).  The log rotates once (``<path>.1``)
   past ``max_bytes``, so it is bounded at ~2x that.
4. **Tail-latency traces are never lost to sampling.**  Head sampling
   (deterministic in the trace id, so one decision holds across layers)
   picks the steady-state fraction; ``slow_threshold_s`` additionally
   writes ANY trace whose root exceeds it — the p99 request is always in
   the log, whatever ``--trace-sample`` says.

Span taxonomy and the header contract are documented in
``docs/observability.md``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
import zlib

logger = logging.getLogger(__name__)

TRACE_HEADER = "X-Hyperopt-Trace"

# trace/span ids are opaque tokens; these bounds keep a hostile or buggy
# caller's header from bloating every span record
_MAX_ID_LEN = 64


def new_trace_id() -> str:
    return uuid.uuid4().hex


def _clean_id(trace_id) -> str:
    tid = str(trace_id)
    if not tid or len(tid) > _MAX_ID_LEN or not tid.isprintable():
        return new_trace_id()
    return tid


class Span:
    """One named, timed region of one trace.

    Created through :func:`span` / :meth:`Trace.record_span`, never
    directly.  ``t0``/``t1`` are ``time.monotonic()`` seconds; the log
    record stores offsets from the trace start so readers never compare
    monotonic clocks across processes.
    """

    __slots__ = ("name", "span_id", "parent_id", "t0", "t1", "attrs")

    def __init__(self, name, span_id, parent_id, t0, attrs=None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1 = None
        self.attrs = attrs

    def set_attr(self, key, value):
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def update_attrs(self, mapping):
        """Bulk attribute attach (e.g. the roofline attrs the scheduler
        adds to a ``device.dispatch`` span after the profiler's record
        lands).  ``None`` values are kept — a null roofline field is
        information (the cost model declined to attribute)."""
        if not mapping:
            return
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(mapping)

    @property
    def duration_s(self):
        return None if self.t1 is None else self.t1 - self.t0


class _NullSpan:
    """The shared no-op span: what every span call returns when no trace
    is bound (or the tracer is disabled).  Accepts the full Span surface
    so call sites never branch."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, key, value):
        pass

    def update_attrs(self, mapping):
        pass

    name = None
    span_id = None
    parent_id = None
    duration_s = None


NULL_SPAN = _NullSpan()


class Trace:
    """One request's span tree, buffered until :meth:`Tracer.finish`.

    Thread-safe append: the HTTP handler thread and the scheduler worker
    both add spans to the same trace.
    """

    # lock-order: _lock
    __slots__ = ("trace_id", "head_sampled", "t_start", "wall_start",
                 "_lock", "_spans", "_next_span", "root")

    def __init__(self, trace_id, head_sampled):
        self.trace_id = trace_id
        self.head_sampled = bool(head_sampled)
        self.t_start = time.monotonic()
        self.wall_start = time.time()
        self._lock = threading.Lock()
        self._spans = []  # guarded-by: _lock
        self._next_span = 0  # guarded-by: _lock
        self.root = None  # the first span opened; set once by _new_span

    def _new_span(self, name, parent_id, t0, attrs):
        with self._lock:
            self._next_span += 1
            sp = Span(name, self._next_span, parent_id, t0, attrs or None)
            self._spans.append(sp)
        if self.root is None:
            self.root = sp
        return sp

    def record_span(self, name, t0, t1, parent=None, **attrs):
        """Append an already-measured span (retroactive intervals like
        queue wait, or batch-wide intervals shared by every request in a
        coalesced batch)."""
        parent_id = parent.span_id if parent is not None else None
        sp = self._new_span(name, parent_id, t0, attrs)
        sp.t1 = t1
        return sp

    def add_event(self, name, parent=None, **attrs):
        """A zero-duration marker span (e.g. one XLA compile event)."""
        now = time.monotonic()
        return self.record_span(name, now, now, parent=parent, **attrs)

    def spans(self):
        with self._lock:
            return list(self._spans)

    def to_record(self) -> dict:
        """The JSON-able log record: root summary + flat span list with
        start offsets relative to the trace start."""
        root = self.root
        spans = []
        for sp in self.spans():
            rec = {
                "name": sp.name,
                "id": sp.span_id,
                "parent": sp.parent_id,
                "t0_s": round(sp.t0 - self.t_start, 6),
                "dur_s": round(
                    (sp.t1 if sp.t1 is not None else time.monotonic())
                    - sp.t0, 6,
                ),
            }
            if sp.attrs:
                rec["attrs"] = sp.attrs
            spans.append(rec)
        return {
            "trace_id": self.trace_id,
            "start_unix": round(self.wall_start, 6),
            "root": root.name if root is not None else None,
            "root_attrs": (root.attrs or {}) if root is not None else {},
            "duration_s": (
                round(root.duration_s, 6)
                if root is not None and root.duration_s is not None
                else None
            ),
            "spans": spans,
        }


# ---------------------------------------------------------------------
# thread binding
# ---------------------------------------------------------------------

_tls = threading.local()


def current_trace():
    """The trace bound to THIS thread (None when unbound — a fresh
    thread always starts unbound; traces never leak across threads)."""
    return getattr(_tls, "trace", None)


def current_trace_id():
    tr = getattr(_tls, "trace", None)
    return tr.trace_id if tr is not None else None


def current_span():
    """The innermost open span on this thread (None when unbound)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


class _TraceBinding:
    """Context manager binding ``trace`` (and a base parent span) to the
    current thread for the block.  Re-entrant across threads: the
    scheduler binds a request's trace around that request's share of the
    batch work, then unbinds — restoring whatever was bound before."""

    __slots__ = ("trace", "parent", "_saved")

    def __init__(self, trace, parent):
        self.trace = trace
        self.parent = parent
        self._saved = None

    def __enter__(self):
        self._saved = (
            getattr(_tls, "trace", None), getattr(_tls, "stack", None)
        )
        _tls.trace = self.trace
        _tls.stack = [self.parent] if self.parent is not None else []
        return self.trace

    def __exit__(self, *exc):
        _tls.trace, _tls.stack = self._saved
        return False


def use_trace(trace, parent=None):
    """Bind ``trace`` to this thread for a ``with`` block; spans created
    inside (on this thread) attach to it, nested under ``parent`` when
    given.  ``use_trace(None)`` is a cheap no-op binding (call sites
    never branch on 'is tracing on')."""
    return _TraceBinding(trace, parent)


class _SpanCM:
    __slots__ = ("trace", "name", "attrs", "span")

    def __init__(self, trace, name, attrs):
        self.trace = trace
        self.name = name
        self.attrs = attrs
        self.span = None

    def __enter__(self):
        parent = current_span()
        self.span = self.trace._new_span(
            self.name,
            parent.span_id if parent is not None else None,
            time.monotonic(),
            self.attrs or None,
        )
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb):
        self.span.t1 = time.monotonic()
        if exc_type is not None:
            self.span.set_attr("error", exc_type.__name__)
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self.span:
            stack.pop()
        return False


def span(name, **attrs):
    """Open a named child span under this thread's current trace.

    The hot-path contract: with no trace bound this returns the shared
    :data:`NULL_SPAN` singleton — no allocation, no lock, no clock read.
    """
    tr = getattr(_tls, "trace", None)
    if tr is None:
        return NULL_SPAN
    return _SpanCM(tr, name, attrs)


def add_event(name, **attrs):
    """Zero-duration marker on this thread's current trace (no-op when
    unbound) — e.g. a device-recovery action or a chaos injection."""
    tr = getattr(_tls, "trace", None)
    if tr is None:
        return NULL_SPAN
    parent = current_span()
    return tr.add_event(name, parent=parent, **attrs)


# ---------------------------------------------------------------------
# the tracer (sampling + log)
# ---------------------------------------------------------------------


def head_sampled(trace_id: str, sample: float) -> bool:
    """Deterministic head-sampling decision: a pure function of the
    trace id, so every layer that sees the id makes the SAME call."""
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    h = zlib.crc32(str(trace_id).encode()) & 0xFFFFFFFF
    return h / 2 ** 32 < sample


def format_record(payload: dict, default=None) -> bytes:
    """One log record: ``\\n<crc32 hex> <json>`` in ONE buffer — the
    response journal's resync discipline (leading newline + per-record
    CRC), so a torn append garbles at most itself.  ``default`` passes
    through to ``json.dumps`` (the flight recorder stringifies
    non-JSON evidence leaves; trace records never need it)."""
    body = json.dumps(payload, sort_keys=True, default=default).encode()
    return b"\n%08x %s" % (zlib.crc32(body) & 0xFFFFFFFF, body)


def parse_trace_log(raw: bytes):
    """(records, n_torn) from raw trace-log bytes.  Lines failing their
    CRC or JSON parse count as torn and are skipped — after a mid-write
    SIGKILL only the final append can legitimately be torn."""
    records, torn = [], 0
    for line in raw.split(b"\n"):
        line = line.strip()
        if not line:
            continue
        try:
            crc_hex, body = line.split(b" ", 1)
            if (zlib.crc32(body) & 0xFFFFFFFF) != int(crc_hex, 16):
                raise ValueError("crc mismatch")
            records.append(json.loads(body.decode()))
        except (ValueError, json.JSONDecodeError, UnicodeDecodeError):
            torn += 1
    return records, torn


def read_trace_log(path):
    """(records, n_torn) for a trace log file (rotated sibling
    ``<path>.1`` read first when present, so records stay in rough
    append order across one rotation)."""
    records, torn = [], 0
    for p in (f"{path}.1", path):
        try:
            with open(p, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        r, t = parse_trace_log(raw)
        records.extend(r)
        torn += t
    return records, torn


DEFAULT_MAX_BYTES = 64 * 1024 * 1024


class Tracer:
    """Sampling policy + the bounded trace log for one server process.

    ``sample`` is the head-sampling rate in [0, 1]; ``slow_threshold_s``
    additionally writes any trace whose root span exceeds it (tail-based
    rescue for exactly the requests worth explaining).  With ``sample``
    0 and no slow threshold the tracer is **disabled**: :meth:`begin`
    returns None and every downstream span call no-ops.

    Thread-safe: handler threads begin/finish traces concurrently; the
    log write is one O_APPEND syscall under ``_io_lock``.
    """

    # lock-order: _io_lock
    def __init__(self, path=None, sample=0.0, slow_threshold_s=None,
                 max_bytes=DEFAULT_MAX_BYTES):
        self.path = path
        self.sample = float(sample)
        self.slow_threshold_s = (
            None if slow_threshold_s is None else float(slow_threshold_s)
        )
        self.max_bytes = int(max_bytes)
        # optional flight-recorder ring fed EVERY finished trace before
        # the head-sampling keep/drop decision (None = not installed —
        # the common case, one attribute read in finish()).  The
        # sample-0 fast path is untouched: a disabled tracer begins no
        # traces, so there is nothing to retain.
        self._recorder = None
        self._io_lock = threading.Lock()
        self._bytes_written = 0  # guarded-by: _io_lock
        self._n_rotations = 0  # guarded-by: _io_lock
        self._counts_lock = threading.Lock()
        self._n_begun = 0  # guarded-by: _counts_lock
        self._n_written = 0  # guarded-by: _counts_lock
        self._n_dropped = 0  # guarded-by: _counts_lock
        self._n_unlogged = 0  # guarded-by: _counts_lock  (kept, no path)
        if self.path:
            parent = os.path.dirname(os.path.abspath(self.path))
            try:
                os.makedirs(parent, exist_ok=True)
            except OSError:
                logger.warning(
                    "cannot create trace-log dir %s", parent, exc_info=True
                )
            try:
                self._bytes_written = os.path.getsize(self.path)
            except OSError:
                pass

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0 or self.slow_threshold_s is not None

    def begin(self, trace_id=None):
        """Start (or adopt) a trace.  Returns None when disabled — the
        null value flows through ``use_trace(None)`` and every span call
        no-ops, which IS the sampling-off hot path.

        A head-DROPPED request is also None **unless** a slow threshold
        is set (tail rescue needs the buffered spans to know the
        duration): at sample 0.01 the other 99% of requests must not
        pay for Trace allocation and span bookkeeping they will never
        serialize."""
        if not self.enabled:
            return None
        tid = _clean_id(trace_id) if trace_id is not None else new_trace_id()
        sampled = head_sampled(tid, self.sample)
        if not sampled and self.slow_threshold_s is None:
            with self._counts_lock:
                self._n_dropped += 1
            return None
        trace = Trace(tid, sampled)
        with self._counts_lock:
            self._n_begun += 1
        return trace

    def set_recorder(self, recorder):
        """Install (or with None, remove) a flight recorder whose ring
        retains every finished trace regardless of head-sampling."""
        self._recorder = recorder

    def finish(self, trace):
        """Close out a trace: decide head-sample OR slow, then append
        its record.  Never raises — tracing must not fail a request."""
        if trace is None:
            return False
        try:
            recorder = self._recorder
            if recorder is not None:
                # retention happens BEFORE the sampling decision: the
                # recorder's window is "last N finished traces", and a
                # head-dropped p99 outlier is exactly the evidence a
                # breach bundle exists to carry
                recorder.record_trace(trace)
            keep = trace.head_sampled
            if not keep and self.slow_threshold_s is not None:
                root = trace.root
                dur = root.duration_s if root is not None else None
                keep = dur is not None and dur >= self.slow_threshold_s
            if not keep or self.path is None:
                with self._counts_lock:
                    if not keep:
                        self._n_dropped += 1
                    else:
                        # kept but nowhere to land (no log path
                        # configured) — account for it so n_begun
                        # always reconciles against the other counters
                        self._n_unlogged += 1
                return False
            line = format_record(trace.to_record())
            with self._io_lock:
                if self._bytes_written + len(line) > self.max_bytes:
                    self._rotate()
                fd = os.open(
                    self.path,
                    os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644,
                )
                try:
                    os.write(fd, line)
                finally:
                    os.close(fd)
                self._bytes_written += len(line)
            with self._counts_lock:
                self._n_written += 1
            return True
        except Exception:
            logger.warning("trace write failed", exc_info=True)
            return False

    def _rotate(self):
        """One-deep rotation (caller holds ``_io_lock``): the previous
        generation is overwritten, bounding the log at ~2x max_bytes."""
        try:
            os.replace(self.path, f"{self.path}.1")
        except OSError:
            logger.warning("trace log rotation failed", exc_info=True)
        self._bytes_written = 0  # lint: disable=RL301  caller holds _io_lock
        self._n_rotations += 1  # lint: disable=RL301  caller holds _io_lock

    def summary(self) -> dict:
        with self._counts_lock:
            begun, written, dropped, unlogged = (
                self._n_begun, self._n_written, self._n_dropped,
                self._n_unlogged,
            )
        with self._io_lock:
            rotations = self._n_rotations
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "slow_threshold_s": self.slow_threshold_s,
            "path": self.path,
            "n_begun": begun,
            "n_written": written,
            "n_dropped": dropped,
            "n_unlogged": unlogged,
            "n_rotations": rotations,
        }


# A permanently-disabled tracer for call sites that want a non-None
# default (OptimizationService without tracing configured).
DISABLED = Tracer(sample=0.0)
