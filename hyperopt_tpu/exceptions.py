"""Framework exceptions.

Reference parity (SURVEY.md §2 #13): ``hyperopt/exceptions.py`` —
``AllTrialsFailed``, ``InvalidTrial``, ``InvalidResultStatus``,
``InvalidLoss``, ``DuplicateLabel``.
"""


class BadSearchSpace(Exception):
    """The search space is malformed."""


class DuplicateLabel(BadSearchSpace):
    """The same hyperparameter label is used by two distinct nodes."""


class InvalidTrial(ValueError):
    """A trial document does not have the required structure."""

    def __init__(self, msg, trial):
        super().__init__(msg, trial)
        self.trial = trial


class InvalidResultStatus(ValueError):
    """An objective returned a result dict with an invalid status."""

    def __init__(self, result):
        super().__init__(result)
        self.result = result


class InvalidLoss(ValueError):
    """An objective returned a non-finite or non-numeric loss."""

    def __init__(self, result):
        super().__init__(result)
        self.result = result


class AllTrialsFailed(Exception):
    """Every trial errored or failed; there is no argmin."""


class InvalidAnnotatedParameter(ValueError):
    """fn has a parameter with an unsupported annotation."""
