"""Framework exceptions.

Reference parity (SURVEY.md §2 #13): ``hyperopt/exceptions.py`` —
``AllTrialsFailed``, ``InvalidTrial``, ``InvalidResultStatus``,
``InvalidLoss``, ``DuplicateLabel``.
"""


class BadSearchSpace(Exception):
    """The search space is malformed."""


class InvalidSpaceError(BadSearchSpace):
    """A space parameter is statically invalid (inverted bounds,
    non-positive q/sigma, ...), caught at ``hp.*`` construction time or
    by the ``fmin(..., validate_space=True)`` pre-flight — instead of a
    device-side NaN many trials later.

    ``label`` is the offending hyperparameter's label (None when the
    failure is not tied to one label); ``diagnostics`` carries the
    structured findings when raised by the pre-flight."""

    def __init__(self, msg, label=None, diagnostics=()):
        super().__init__(msg)
        self.label = label
        self.diagnostics = tuple(diagnostics)


class DuplicateLabel(BadSearchSpace):
    """The same hyperparameter label is used by two distinct nodes."""


class InvalidTrial(ValueError):
    """A trial document does not have the required structure."""

    def __init__(self, msg, trial):
        super().__init__(msg, trial)
        self.trial = trial


class InvalidResultStatus(ValueError):
    """An objective returned a result dict with an invalid status."""

    def __init__(self, result):
        super().__init__(result)
        self.result = result


class InvalidLoss(ValueError):
    """An objective returned a non-finite or non-numeric loss."""

    def __init__(self, result):
        super().__init__(result)
        self.result = result


class AllTrialsFailed(Exception):
    """Every trial errored or failed; there is no argmin."""


class InvalidAnnotatedParameter(ValueError):
    """fn has a parameter with an unsupported annotation."""
