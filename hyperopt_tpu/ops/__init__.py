"""TPU numeric kernels: distribution samplers, Parzen fits, GMM scoring.

Everything in this package is JAX: pure functions over arrays, designed to
be jitted/vmapped/shard_mapped.  Host-side orchestration lives elsewhere.
"""
