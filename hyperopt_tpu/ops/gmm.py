"""Truncated (log-)GMM sampling and log-density scoring kernels.

Reference parity (SURVEY.md §2 #11): ``hyperopt/tpe.py`` — ``GMM1``,
``GMM1_lpdf``, ``LGMM1``, ``LGMM1_lpdf`` and the q-variants via
``normal_cdf``/``lognormal_cdf`` erf sums (~L200-520).

Semantics notes (match the reference exactly, by construction):
- Truncation: the reference rejection-samples the *mixture* restricted to
  ``[low, high)``, i.e. density ∝ Σ wᵢ N(x; μᵢ, σᵢ) on the interval with a
  single global normalizer ``p_accept = Σ wᵢ (Φᵢ(high) − Φᵢ(low))``.  The
  XLA-friendly equivalent here: re-weight components by their in-bounds
  mass (``wᵢ·Zᵢ``), then draw an exact truncated normal within the chosen
  component — same joint density, zero rejection loops.
- Log-scale (``LGMM1``): the mixture lives in log space; truncation bounds
  are log-space bounds; samples are exponentiated.
- Quantization: ``round(x/q)·q`` buckets; lpdf integrates the bucket via
  CDF differences (the reference's two-sided erf sum).

These are THE hot kernels: scoring is O(candidates × mixture components) =
O(candidates × history), evaluated as one fused ``[C, K]`` broadcast that
XLA tiles across the VPU — and, for pod-scale history, sharded over the
mesh's history axis (see ``hyperopt_tpu.parallel.sharding``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.scipy.special import logsumexp, ndtr

_SQRT_2PI = 2.5066282746310002
EPS = 1e-12


def _safe_log(x):
    return jnp.log(jnp.maximum(x, EPS))


def _log_weights(w):
    """Component log-weights with exact-zero weights mapped to -inf.

    Padding components (weight exactly 0, from the padded Parzen fit) must
    contribute zero mass — ``_safe_log`` alone would give them a spurious
    ~1e-12 density floor visible deep in the tails."""
    return jnp.where(w > 0, jnp.log(jnp.maximum(w, EPS)), -jnp.inf)


def _cdf(v, mu, sigma):
    """Normal CDF Φ((v−μ)/σ), safe for ±inf v."""
    z = (v - mu) / jnp.maximum(sigma, EPS)
    return ndtr(jnp.clip(z, -40.0, 40.0))


def _log_cdf_arg(v):
    """log of a raw-space quantized bound, mapping v<=0 to -inf (CDF 0)."""
    return jnp.where(v > 0, jnp.log(jnp.maximum(v, EPS)), -jnp.inf)


def _p_accept(w, mu, sigma, low, high):
    """Global in-bounds mixture mass (the reference's rejection acceptance)."""
    return jnp.sum(w * (_cdf(high, mu, sigma) - _cdf(low, mu, sigma)))


@partial(jax.jit, static_argnames=("n_samples", "log_scale"))
def gmm_sample(key, w, mu, sigma, low, high, q, n_samples: int, log_scale: bool):
    """Draw ``n_samples`` from the truncated (log-)GMM.

    ``low``/``high`` are (log-space if ``log_scale``) truncation bounds —
    pass ±inf for unbounded.  ``q <= 0`` disables quantization.

    Component selection is inverse-CDF (cumsum + searchsorted), O(n log K)
    — NOT ``jax.random.categorical``, whose Gumbel trick materializes an
    [n, K] noise matrix: at a 10k-trial history that is ~10⁸ random draws
    per suggest and dominates the whole suggest cost.  Zero-probability
    (padding) components occupy zero-width CDF intervals, which
    ``side='right'`` search never selects.
    """
    k_comp, k_val = jax.random.split(key)
    a = (low - mu) / jnp.maximum(sigma, EPS)
    b = (high - mu) / jnp.maximum(sigma, EPS)
    a = jnp.clip(a, -30.0, 30.0)
    b = jnp.clip(b, -30.0, 30.0)
    Z = ndtr(b) - ndtr(a)
    p = jnp.maximum(w * Z, 0.0)
    cdf = jnp.cumsum(p)
    total = cdf[-1]
    u = jax.random.uniform(k_comp, (n_samples,), dtype=cdf.dtype)
    # clamp strictly below total: f32 rounding of u*total can hit total
    # exactly, and searchsorted would then step past the last
    # positive-weight component onto a zero-weight padding slot
    t = jnp.minimum(u * total, total * (1.0 - 1e-6))
    comp = jnp.searchsorted(cdf, t, side="right")
    comp = jnp.clip(comp, 0, w.shape[0] - 1)
    u2 = jax.random.truncated_normal(k_val, a[comp], b[comp])
    x = mu[comp] + sigma[comp] * u2
    if log_scale:
        x = jnp.exp(x)
    x = jnp.where(q > 0, jnp.round(x / jnp.maximum(q, EPS)) * q, x)
    return x


@partial(jax.jit, static_argnames=("log_scale", "quantized"))
def gmm_lpdf(x, w, mu, sigma, low, high, q, log_scale: bool, quantized: bool):
    """Log-density of ``x`` ([C]) under the truncated (log-)GMM ([K]).

    The [C, K] broadcast below is the O(candidates × history) hot loop.
    """
    sigma = jnp.maximum(sigma, EPS)
    logw = _log_weights(w)
    p_accept = _p_accept(w, mu, sigma, low, high)

    if not quantized:
        if log_scale:
            z = jnp.where(x > 0, jnp.log(jnp.maximum(x, EPS)), -jnp.inf)
            jacobian = _safe_log(x)  # d(log x)/dx term of the lognormal pdf
        else:
            z = x
            jacobian = jnp.zeros_like(x)
        mahal = ((z[:, None] - mu[None, :]) / sigma[None, :]) ** 2
        comp_ll = -0.5 * mahal - jnp.log(sigma * _SQRT_2PI)[None, :] + logw[None, :]
        ll = logsumexp(comp_ll, axis=1) - jacobian - _safe_log(p_accept)
        # out-of-bounds or non-positive (log-scale) points have density 0
        if log_scale:
            in_bounds = (z >= low) & (z < high) & (x > 0)
        else:
            in_bounds = (x >= low) & (x < high)
        return jnp.where(in_bounds, ll, -jnp.inf)

    # quantized: integrate the bucket [x - q/2, x + q/2] ∩ bounds
    qq = jnp.maximum(q, EPS)
    if log_scale:
        raw_low = jnp.where(jnp.isfinite(low), jnp.exp(low), 0.0)
        raw_high = jnp.where(jnp.isfinite(high), jnp.exp(high), jnp.inf)
        ub = jnp.minimum(x + qq / 2.0, raw_high)
        lb = jnp.maximum(jnp.maximum(x - qq / 2.0, raw_low), 0.0)
        ub_z = _log_cdf_arg(ub)
        lb_z = _log_cdf_arg(lb)
    else:
        ub_z = jnp.minimum(x + qq / 2.0, high)
        lb_z = jnp.maximum(x - qq / 2.0, low)
    prob = jnp.sum(
        w[None, :]
        * (
            _cdf(ub_z[:, None], mu[None, :], sigma[None, :])
            - _cdf(lb_z[:, None], mu[None, :], sigma[None, :])
        ),
        axis=1,
    )
    return _safe_log(prob) - _safe_log(p_accept)


# ---------------------------------------------------------------------
# Categorical posterior kernels
# ---------------------------------------------------------------------


@partial(jax.jit, static_argnames=("upper", "lf"))
def categorical_posterior(obs, n_obs, prior_p, prior_weight, upper: int, lf: int):
    """Posterior category probabilities: forgetting-weighted counts plus
    ``upper · prior_weight · prior_p`` pseudocounts (reference:
    ``hyperopt/tpe.py`` — categorical posterior ~L520-570)."""
    from .parzen import linear_forgetting_weights_padded

    pad = obs.shape[0]
    w_chrono = linear_forgetting_weights_padded(n_obs, lf, pad)
    obs_idx = jnp.clip(obs.astype(jnp.int32), 0, upper - 1)
    counts = jnp.zeros(upper, jnp.float32).at[obs_idx].add(w_chrono)
    pseudocounts = counts + upper * prior_weight * prior_p
    return pseudocounts / jnp.sum(pseudocounts)


@partial(jax.jit, static_argnames=("n_samples",))
def categorical_sample(key, p, n_samples: int):
    return jax.random.categorical(key, _log_weights(p), shape=(n_samples,)).astype(
        jnp.int32
    )


@jax.jit
def categorical_lpdf(x, p):
    return _log_weights(p)[jnp.clip(x.astype(jnp.int32), 0, p.shape[0] - 1)]
