"""Pallas TPU kernel: fused pair-mixture scoring with online logsumexp.

The O(candidates × history) hot loop of TPE
(`log l(x) − log g(x)`, see ``ops.score`` for the quadratic-feature
formulation) as a hand-tiled TPU kernel:

- grid over candidate tiles (``TC`` per step); the full ``[3, 2K]``
  parameter block stays **resident in VMEM** across the whole grid (≤ a
  few hundred KB even at 10k-trial history), so HBM traffic is O(C + K)
  instead of O(C·K);
- per candidate tile: one ``[TC, 3] × [3, TK]`` `pl.dot` per component
  tile (MXU) followed by a flash-attention-style running
  (max, sum·exp) update (VPU) — the logsumexp never materializes the
  [C, K] matrix anywhere;
- padding components carry ``logcoef = −inf`` (from
  ``ops.score.prepare_mixture``) and contribute exactly zero mass; the
  running max starts at −1e30 so all-padding tiles are safe in any order.

CPU/testing: pass ``interpret=True`` (Pallas interpreter). Production
entry point is :func:`pair_score_pallas`; numeric contract is identical
to ``ops.score.pair_score``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

NEG_BIG = -1e30


def _kernel(z_ref, p_ref, out_ref, *, K: int, TK: int):
    """One candidate tile vs all 2K components of both mixtures."""
    z = z_ref[0, :]  # [TC]
    TC = z.shape[0]
    f = jnp.stack([z * z, z, jnp.ones_like(z)], axis=1)  # [TC, 3]

    n_tiles = K // TK

    def mix_update(comp, m, s):
        tile_max = jnp.max(comp, axis=1)
        new_m = jnp.maximum(m, tile_max)
        s = s * jnp.exp(m - new_m) + jnp.sum(
            jnp.exp(comp - new_m[:, None]), axis=1
        )
        return new_m, s

    def body(j, carry):
        mb, sb, ma, sa = carry
        pb = p_ref[:, pl.ds(j * TK, TK)]          # below-mixture tile [3, TK]
        pa = p_ref[:, pl.ds(K + j * TK, TK)]      # above-mixture tile [3, TK]
        comp_b = jax.lax.dot_general(
            f, pb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        comp_a = jax.lax.dot_general(
            f, pa, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        mb, sb = mix_update(comp_b, mb, sb)
        ma, sa = mix_update(comp_a, ma, sa)
        return mb, sb, ma, sa

    init = (
        jnp.full((TC,), NEG_BIG, jnp.float32),
        jnp.zeros((TC,), jnp.float32),
        jnp.full((TC,), NEG_BIG, jnp.float32),
        jnp.zeros((TC,), jnp.float32),
    )
    mb, sb, ma, sa = jax.lax.fori_loop(0, n_tiles, body, init)
    ll_b = mb + jnp.log(jnp.maximum(sb, 1e-300))
    ll_a = ma + jnp.log(jnp.maximum(sa, 1e-300))
    out_ref[0, :] = ll_b - ll_a


@partial(jax.jit, static_argnames=("tc", "tk", "interpret"))
def pair_score_pallas(z, params_pair, tc: int = 256, tk: int = 512, interpret=False):
    """``log l − log g`` for candidates ``z`` ([C]) given ``params_pair``
    ([3, 2K]); same contract as ``ops.score.pair_score``."""
    C = z.shape[0]
    K2 = params_pair.shape[1]
    assert K2 % 2 == 0
    K = K2 // 2

    # pad candidate axis to the tile size, component axis to the K tile
    tk = min(tk, max(128, K))
    k_pad = (-K) % tk
    if k_pad:
        neg = jnp.full((1, 1), jnp.float32(NEG_BIG))
        pb = jnp.pad(params_pair[:, :K], ((0, 0), (0, k_pad)))
        pa = jnp.pad(params_pair[:, K:], ((0, 0), (0, k_pad)))
        # padded components: zero quadratic/linear terms, -inf constant
        pb = pb.at[2, K:].set(-jnp.inf)
        pa = pa.at[2, K:].set(-jnp.inf)
        params_pair = jnp.concatenate([pb, pa], axis=1)
        K = K + k_pad
    c_pad = (-C) % tc
    zp = jnp.pad(z, (0, c_pad))
    n_c = zp.shape[0] // tc
    zp = zp.reshape(n_c, tc)

    out = pl.pallas_call(
        partial(_kernel, K=K, TK=tk),
        out_shape=jax.ShapeDtypeStruct((n_c, tc), jnp.float32),
        grid=(n_c,),
        in_specs=[
            pl.BlockSpec((1, tc), lambda i: (i, 0)),
            pl.BlockSpec((3, 2 * K), lambda i: (0, 0)),  # resident in VMEM
        ],
        out_specs=pl.BlockSpec((1, tc), lambda i: (i, 0)),
        interpret=interpret,
    )(zp, params_pair)
    return out.reshape(-1)[:C]
