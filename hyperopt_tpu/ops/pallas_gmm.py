"""Pallas TPU kernel: fused pair-mixture scoring with online logsumexp.

The O(candidates × history) hot loop of TPE
(`log l(x) − log g(x)`, see ``ops.score`` for the quadratic-feature
formulation) as a hand-tiled TPU kernel:

- grid over candidate tiles (``TC`` per step); the full ``[3, Kb+Ka]``
  parameter block stays **resident in VMEM** across the whole grid (≤ a
  few hundred KB even at 10k-trial history), so HBM traffic is O(C + K)
  instead of O(C·K);
- per candidate tile: one ``[TC, 3] × [3, TK]`` matmul per component tile
  (MXU) followed by a flash-attention-style running (max, sum·exp) update
  (VPU) — the logsumexp never materializes the [C, K] matrix anywhere;
- the below/above mixtures have *different* sizes (below is capped at
  ``linear_forgetting``; above grows with history), so each region is
  tiled independently from its static boundary — no wasted columns;
- padding components carry ``logcoef = NEG_BIG`` (−1e30 — finite, because
  infinities poison the HIGHEST-precision multi-pass matmul) and
  contribute exactly zero mass against any real component; the running
  max starts at −1e30 so all-padding tiles are safe in any order.

Mosaic layout notes (the TPU lowering requires every block's last two
dims to be multiples of (8, 128) or equal to the array dims):

- the candidate features ``F = [z², z, 1]`` are computed *outside* the
  kernel (XLA fuses the three elementwise ops into the pad/reshape), so
  the streamed operand is ``[C_pad, 3]`` with ``(TC, 3)`` blocks —
  TC is a multiple of 8 and 3 equals the array dim;
- scores come back as a ``[C_pad, 1]`` column with ``(TC, 1)`` blocks
  (1 equals the array dim);
- the parameter block is mapped whole (block dims == array dims) and so
  stays VMEM-resident across the grid;
- each mixture region is padded to a multiple of 128 so the in-kernel
  ``pl.ds`` lane slices are tile-aligned.

CPU/testing: pass ``interpret=True`` (Pallas interpreter).  Numeric
contract is identical to ``ops.score.pair_score``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

NEG_BIG = -1e30


def env_bool(name: str):
    """Tri-state env flag: True/False when set (truthy strings are
    ``1/true/yes/on``), None when unset — the one parser every kernel
    resolver shares."""
    import os

    v = os.environ.get(name)
    if v is None:
        return None
    return v.strip().lower() in ("1", "true", "yes", "on")


def _mix_update(comp, m, s):
    tile_max = jnp.max(comp, axis=1)
    new_m = jnp.maximum(m, tile_max)
    s = s * jnp.exp(m - new_m) + jnp.sum(jnp.exp(comp - new_m[:, None]), axis=1)
    return new_m, s


def _region_logsumexp(f, p_ref, start: int, size: int, tk: int, lead=None,
                      fma: bool = False):
    """Online logsumexp of ``f @ P[:, start:start+size]`` tiled by ``tk``.

    ``fma=False``: MXU dot_general. The contraction dim is 3, which the
    MXU pads to 128 (≈43× wasted lanes), and HIGHEST forces multi-pass
    true-f32 — default bf16 passes lose ~1e0 absolute on 10k-component
    logsumexps, which would randomize the EI argmax.
    ``fma=True``: the same quadratic as two broadcast FMAs + add on the
    VPU — exact f32 with no multi-pass and no dead MXU lanes. Bitwise
    different summation order but ≤1 ulp-class difference; selected via
    the measured A/B in ``bench.py _device_scorer_bench`` (the
    ``scorer_ab`` output keys).

    The FMA branch REQUIRES ``f[:, 2] == 1`` (it adds the constant row
    unscaled) — true for :func:`_features` rows; zero-padded candidate
    rows get a wrong-but-sliced-off score. The MXU branch is a general
    ``f @ P``.
    """
    TC = f.shape[0]

    def body(j, carry):
        m, s = carry
        if lead is None:
            tile = p_ref[:, pl.ds(start + j * tk, tk)]
        else:
            tile = p_ref[lead, :, pl.ds(start + j * tk, tk)]
        if fma:
            comp = (
                f[:, 0:1] * tile[0:1, :]
                + f[:, 1:2] * tile[1:2, :]
                + tile[2:3, :]
            )
        else:
            comp = jax.lax.dot_general(
                f,
                tile,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
        return _mix_update(comp, m, s)

    init = (jnp.full((TC,), NEG_BIG, jnp.float32), jnp.zeros((TC,), jnp.float32))
    m, s = jax.lax.fori_loop(0, size // tk, body, init)
    return m + jnp.log(jnp.maximum(s, 1e-300))


def _kernel(f_ref, p_ref, out_ref, *, KB: int, KA: int, TKB: int, TKA: int,
            fma: bool):
    f = f_ref[...]  # [TC, 3]
    ll_b = _region_logsumexp(f, p_ref, 0, KB, TKB, fma=fma)
    ll_a = _region_logsumexp(f, p_ref, KB, KA, TKA, fma=fma)
    out_ref[...] = (ll_b - ll_a)[:, None]


def _kernel_batched(f_ref, p_ref, out_ref, *, KB: int, KA: int, TKB: int,
                    TKA: int, fma: bool):
    f = f_ref[0]  # [TC, 3]
    ll_b = _region_logsumexp(f, p_ref, 0, KB, TKB, lead=0, fma=fma)
    ll_a = _region_logsumexp(f, p_ref, KB, KA, TKA, lead=0, fma=fma)
    out_ref[...] = (ll_b - ll_a).reshape(out_ref.shape)


def _region_tile(k: int, tk: int) -> int:
    """Per-region tile size: at most ``tk``, at least one 128-lane tile."""
    return min(tk, ((k + 127) // 128) * 128)


def _pad_regions(params_pair, k_below: int, tkb: int, tka: int):
    """Pad each mixture region to a multiple of its tile size with
    NEG_BIG logcoef columns (zero mass).  Works for [3, K] and [L, 3, K]
    blocks."""
    kb, ka = k_below, params_pair.shape[-1] - k_below
    pb_pad = (-kb) % tkb
    pa_pad = (-ka) % tka
    below = params_pair[..., :kb]
    above = params_pair[..., kb:]

    def pad(block, n):
        # NEG_BIG, not −inf: infinities break the HIGHEST-precision
        # multi-pass matmul (see ops.score.prepare_mixture)
        if n == 0:
            return block
        widths = [(0, 0)] * (block.ndim - 1) + [(0, n)]
        block = jnp.pad(block, widths)
        return block.at[..., 2, -n:].set(NEG_BIG)

    return (
        jnp.concatenate([pad(below, pb_pad), pad(above, pa_pad)], axis=-1),
        kb + pb_pad,
        ka + pa_pad,
    )


def _features(z, c_pad: int):
    """[z², z, 1] feature rows, padded along candidates: [C + c_pad, 3]."""
    f = jnp.stack([z * z, z, jnp.ones_like(z)], axis=-1)
    if c_pad:
        widths = [(0, 0)] * (f.ndim - 2) + [(0, c_pad), (0, 0)]
        f = jnp.pad(f, widths)
    return f


# process-wide measured defaults, set by the timing probe in
# hyperopt_tpu.algos.tpe (None until a probe or set_default_fma call).
# Kept PER KERNEL: the batched kernel's (L, n_c) grid and per-label VMEM
# residency differ from the unbatched kernel's, so the faster mode can
# legitimately differ between them (ADVICE r4 tpe.py:256).
_fma_measured_default = None  # pair_score_pallas_batched
_fma_measured_default_unbatched = None  # pair_score_pallas


def set_default_fma(value: bool, kernel: str = "both") -> None:
    """Set the process-wide kernel-mode default for ``kernel`` in
    ``{"batched", "unbatched", "both"}`` (used by the once-per-process
    timing probe on real TPUs; the env var still wins)."""
    global _fma_measured_default, _fma_measured_default_unbatched
    v = bool(value)
    if kernel not in ("batched", "unbatched", "both"):
        raise ValueError(kernel)
    if kernel in ("batched", "both"):
        _fma_measured_default = v
    if kernel in ("unbatched", "both"):
        _fma_measured_default_unbatched = v


def resolve_fma(kernel: str = "batched") -> bool:
    """THE kernel-mode resolver: VPU FMA vs MXU dot for the quadratic
    evaluation, for ``kernel`` in ``{"batched", "unbatched"}``.  Both
    public entry points (:func:`pair_score_pallas_batched` /
    :func:`pair_score_pallas`) and every reporting surface (bench
    smoke fields) resolve through this one function, so the default
    can never silently diverge between the two scorer paths again
    (the ROADMAP's ``pallas_fma_default`` inconsistency).

    Resolution order:

    1. ``HYPEROPT_TPU_PALLAS_FMA=0/1`` env override (both kernels);
    2. THIS kernel's measured default (:func:`set_default_fma`,
       written by the per-kernel TPU timing probe);
    3. the OTHER kernel's measured default — a single-kernel probe
       (or a partial ``set_default_fma`` call) applies to both paths
       rather than leaving them split between measured-FMA and
       silent-MXU;
    4. the MXU path.
    """
    if kernel not in ("batched", "unbatched"):
        raise ValueError(kernel)
    v = env_bool("HYPEROPT_TPU_PALLAS_FMA")
    if v is not None:
        return v
    own, other = (
        (_fma_measured_default, _fma_measured_default_unbatched)
        if kernel == "batched"
        else (_fma_measured_default_unbatched, _fma_measured_default)
    )
    if own is not None:
        return own
    if other is not None:
        return other
    return False


def resolve_fma_basis(kernel: str = "batched") -> str:
    """WHERE :func:`resolve_fma`'s answer for ``kernel`` comes from:
    ``"env"`` (HYPEROPT_TPU_PALLAS_FMA pin), ``"measured"`` (this
    kernel's own timing probe), ``"other_kernel"`` (the single-probe
    fallback — only the sibling kernel was measured), or
    ``"default_mxu"`` (nothing probed).  Reported next to the resolved
    booleans in the bench smoke block so two artifacts showing
    different defaults are EXPLAINABLE (probe outcomes can legitimately
    differ per kernel and per capture host) instead of silently
    contradictory — the ISSUE-14 ``pallas_fma_default`` satellite."""
    import os

    if kernel not in ("batched", "unbatched"):
        raise ValueError(kernel)
    if os.environ.get("HYPEROPT_TPU_PALLAS_FMA") is not None:
        return "env"
    own, other = (
        (_fma_measured_default, _fma_measured_default_unbatched)
        if kernel == "batched"
        else (_fma_measured_default_unbatched, _fma_measured_default)
    )
    if own is not None:
        return "measured"
    if other is not None:
        return "other_kernel"
    return "default_mxu"


def _default_fma(batched: bool = True) -> bool:
    """Back-compat alias for :func:`resolve_fma` (kept for callers
    that predate the unified resolver)."""
    return resolve_fma("batched" if batched else "unbatched")


def pair_score_pallas(
    z, params_pair, k_below: int, tc: int = 1024, tk: int = 512, interpret=False,
    fma=None,
):
    """``log l − log g`` for candidates ``z`` ([C]); same contract as
    ``ops.score.pair_score``.

    ``fma=None`` resolves the env default HERE, outside jit, so flipping
    ``HYPEROPT_TPU_PALLAS_FMA`` mid-process takes effect on the next call
    (the resolved bool is the static cache key, never ``None``)."""
    if fma is None:
        fma = resolve_fma("unbatched")
    return _pair_score_pallas(z, params_pair, k_below, tc, tk, interpret, fma)


@partial(jax.jit, static_argnames=("k_below", "tc", "tk", "interpret", "fma"))
def _pair_score_pallas(z, params_pair, k_below: int, tc, tk, interpret, fma):
    C = z.shape[0]
    tkb = _region_tile(k_below, tk)
    tka = _region_tile(params_pair.shape[1] - k_below, tk)
    params_pair, KB, KA = _pad_regions(params_pair, k_below, tkb, tka)
    c_pad = (-C) % tc
    fp = _features(z, c_pad)  # [C_pad, 3]
    n_c = fp.shape[0] // tc

    out = pl.pallas_call(
        partial(_kernel, KB=KB, KA=KA, TKB=tkb, TKA=tka, fma=fma),
        out_shape=jax.ShapeDtypeStruct((n_c * tc, 1), jnp.float32),
        grid=(n_c,),
        in_specs=[
            pl.BlockSpec((tc, 3), lambda i: (i, 0)),
            pl.BlockSpec((3, KB + KA), lambda i: (0, 0)),  # resident in VMEM
        ],
        out_specs=pl.BlockSpec((tc, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(fp, params_pair)
    return out.reshape(-1)[:C]


def pair_score_pallas_batched(
    z, params_pair, k_below: int, tc: int = 1024, tk: int = 512, interpret=False,
    fma=None,
):
    """Label-stacked variant: ``z`` [L, C], ``params_pair`` [L, 3, Kb+Ka]
    → scores [L, C].  Grid is (labels × candidate tiles).  ``fma=None``
    resolves the env default outside jit (see ``pair_score_pallas``)."""
    if fma is None:
        fma = resolve_fma("batched")
    return _pair_score_pallas_batched(z, params_pair, k_below, tc, tk, interpret, fma)


@partial(jax.jit, static_argnames=("k_below", "tc", "tk", "interpret", "fma"))
def _pair_score_pallas_batched(z, params_pair, k_below: int, tc, tk, interpret, fma):
    L, C = z.shape
    tkb = _region_tile(k_below, tk)
    tka = _region_tile(params_pair.shape[2] - k_below, tk)
    params_pair, KB, KA = _pad_regions(params_pair, k_below, tkb, tka)
    c_pad = (-C) % tc
    fp = _features(z, c_pad)  # [L, C_pad, 3]
    n_c = fp.shape[1] // tc

    out = pl.pallas_call(
        partial(_kernel_batched, KB=KB, KA=KA, TKB=tkb, TKA=tka, fma=fma),
        out_shape=jax.ShapeDtypeStruct((L, n_c * tc, 1), jnp.float32),
        grid=(L, n_c),
        in_specs=[
            pl.BlockSpec((1, tc, 3), lambda l, i: (l, i, 0)),
            pl.BlockSpec((1, 3, KB + KA), lambda l, i: (l, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tc, 1), lambda l, i: (l, i, 0)),
        interpret=interpret,
    )(fp, params_pair)
    return out.reshape(L, -1)[:, :C]
