"""Fused l(x)/g(x) score kernels — the MXU formulation.

The TPE score for candidate x is ``log l(x) − log g(x)`` where each term is
a logsumexp over mixture components of
``−½((z−μ)/σ)² + log w − log(σ√2π)``.  The quadratic expands to

    comp_ll = z²·(−½inv²) + z·(μ·inv²) + (logcoef − ½μ²·inv²)

i.e. a **rank-3 matmul**: features ``F = [z², z, 1]`` of shape [C, 3]
against a parameter matrix ``P`` of shape [3, K] — exactly the shape the
MXU wants.  Both mixtures are concatenated into one ``[3, Kb+Ka]`` matrix
(the halves may have different sizes — the below mixture is capped at
``linear_forgetting`` components while above grows with history — so the
boundary ``k_below`` is carried explicitly) and a single matmul feeds both
logsumexps.

The additive constants the suggest path may drop (global ``p_accept``
normalizers, the lognormal ``−log x`` Jacobian which cancels in l−g) do
not affect the argmax; ``hyperopt_tpu.ops.gmm.gmm_lpdf`` remains the exact
normalized density for the public API.

Two implementations with identical semantics:
- :func:`pair_score` — jnp, chunked over candidates (runs everywhere;
  XLA maps the matmul to the MXU on TPU);
- :mod:`hyperopt_tpu.ops.pallas_gmm` — a Pallas kernel with online
  (flash-style) logsumexp accumulation over component tiles, keeping the
  whole mixture resident in VMEM.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_LOG_SQRT_2PI = 0.9189385332046727
NEG_BIG = -1e30


def prepare_mixture(w, mu, sigma, eps=1e-12):
    """Mixture → the 3-row parameter block of the quadratic formulation.

    Zero-weight (padding) components get logcoef = NEG_BIG (−1e30, finite
    — see the comment below) so they contribute exactly 0 mass against any
    real component; their mu/inv entries are finite so no NaNs arise.
    """
    sigma = jnp.maximum(sigma, eps)
    inv = 1.0 / sigma
    inv2 = inv * inv
    # NEG_BIG (finite) instead of −inf: infinities poison the HIGHEST-
    # precision multi-pass matmul (hi/lo operand splits hit inf−inf=NaN);
    # a −1e30 logcoef still contributes exp(−1e30 − m) = 0 exactly
    # against any real component.
    logcoef = jnp.where(
        w > 0, jnp.log(jnp.maximum(w, eps)) - jnp.log(sigma) - _LOG_SQRT_2PI, NEG_BIG
    )
    # rows: coefficient of z², coefficient of z, constant
    return jnp.stack([-0.5 * inv2, mu * inv2, logcoef - 0.5 * mu * mu * inv2])


def pair_params(wb, mb, sb, wa, ma, sa):
    """Both mixtures stacked into one [3, Kb+Ka] block.

    Returns the parameter block only; the boundary is the static
    ``wb.shape[0]`` — pass it to the scorers as ``k_below``.
    """
    return jnp.concatenate(
        [prepare_mixture(wb, mb, sb), prepare_mixture(wa, ma, sa)], axis=1
    )


# Below this total component count the XLA scorer's [chunk, K] comp
# intermediate fits in VMEM and XLA's own tiling beats the hand kernel
# (measured on v5e: K=4130 xla 95 vs pallas 75 GEI/s; K=8226 xla 51 vs
# pallas 87 — the flip is the HBM spill of the comp matrix, which the
# Pallas online logsumexp never materializes).
PALLAS_MIN_K = 6144


def effective_scorer(scorer: str, k_total: int) -> str:
    """Static scorer choice per mixture size (shapes are trace-time).

    Tiers (docs/API.md "Scorer tiers"): ``xla`` (chunked MXU matmul +
    full-row logsumexp), ``pallas`` (hand-tiled online-logsumexp
    kernel), ``fused`` (the :mod:`~hyperopt_tpu.ops.pallas_fused`
    mega-kernel — draw → score → top-k in one launch), ``exact``
    (normalized lpdf path).  The K-crossover only applies to the
    *auto-selected* scorer — below ``PALLAS_MIN_K`` both hand kernels
    lose to XLA's own tiling (the [chunk, K] intermediate still fits
    VMEM), so ``pallas``/``fused`` demote to ``xla``; an explicit
    HYPEROPT_TPU_SCORER force is honored verbatim (so the hand kernels
    can be exercised on small histories deliberately).
    """
    import os

    if os.environ.get("HYPEROPT_TPU_SCORER"):
        return scorer
    if scorer in ("pallas", "fused") and k_total < PALLAS_MIN_K:
        return "xla"
    return scorer


def pair_score_cost(n_cand: int, k_total: int, scorer: str) -> dict:
    """{flops, mxu_flops, bytes} model of one pair-scorer invocation at
    C candidates x K total mixture components — the memory-behavior
    knowledge lives here because it differs per implementation:

    - both scorers: the rank-3 matmul is 2*3*C*K FLOPs (``mxu_flops`` —
      the subset MFU is defined against) and the two logsumexps add
      ~4 FLOPs/cell (max pass, subtract, exp, add);
    - the **XLA** scorer materializes the [C, K] component matrix
      (chunked, but each chunk round-trips when [chunk, K] exceeds
      VMEM — the measured PALLAS_MIN_K crossover above is exactly that
      spill), so its traffic model charges a write + read of the full
      matrix: at production K this makes it **bandwidth-bound**;
    - the **Pallas** kernels accumulate the logsumexp online in VMEM
      and never materialize comp: traffic is just candidates, params,
      and output;
    - the **fused** mega-kernel (:mod:`hyperopt_tpu.ops.pallas_gmm`'s
      online logsumexp extended with in-launch draw + top-k selection,
      :mod:`hyperopt_tpu.ops.pallas_fused`) additionally keeps the
      candidate and score vectors in VMEM between stages: ZERO [C, K]
      round trips AND no candidate/score round trip — traffic is the
      u-streams (or streamed candidates), the params block, and the
      [k]-winner accumulators.  The draw/select stages add ~O(C)
      transform flops.

    ``hyperopt_tpu.profiling`` uses this for its analytical per-family
    cost fallback; the XLA model is an upper bound XLA's fusion may
    beat at small K (where the chunk fits in cache/VMEM).
    """
    C, K = float(n_cand), float(k_total)
    mxu = 2.0 * 3.0 * C * K
    flops = mxu + 4.0 * C * K
    eff = effective_scorer(scorer, int(k_total))
    if eff == "fused":
        # truncated-normal transform + inverse-CDF select + running
        # winner/EI updates, all O(C)
        flops += 40.0 * C
        # two u-streams in, params in, [k] winner accumulators out
        # (negligible) — the candidates/scores never touch HBM
        nbytes = 4.0 * (2.0 * C + 3.0 * K)
        return {"flops": flops, "mxu_flops": mxu, "bytes": nbytes}
    # z read + features + output, params [3, K]
    nbytes = 4.0 * (3.0 * C + 3.0 * K)
    if eff != "pallas":
        nbytes += 2.0 * C * K * 4.0  # comp matrix write + read
    return {"flops": flops, "mxu_flops": mxu, "bytes": nbytes}


def _features(z):
    return jnp.stack([z * z, z, jnp.ones_like(z)], axis=1)  # [C, 3]


def _logsumexp_rows(comp):
    m = jnp.max(comp, axis=1)
    m_safe = jnp.maximum(m, NEG_BIG)
    s = jnp.sum(jnp.exp(comp - m_safe[:, None]), axis=1)
    return m_safe + jnp.log(jnp.maximum(s, 1e-300))


@partial(jax.jit, static_argnames=("k_below", "chunk"))
def pair_score(z, params_pair, k_below: int, chunk=4096):
    """``log l − log g`` (up to additive constant) for candidates ``z``.

    ``params_pair``: [3, Kb+Ka] from :func:`pair_params`; ``k_below`` is
    the Kb boundary.  Chunked over candidates so the [chunk, Kb+Ka]
    intermediate stays small at 10k+ histories.
    """
    C = z.shape[0]

    def score_block(zb):
        # rank-3 matmul on the MXU; HIGHEST keeps true-f32 accumulation
        # (default bf16 passes lose ~1e0 absolute at 10k components —
        # enough to randomize the EI argmax; the op is bandwidth-bound so
        # the extra passes are ~free)
        comp = jnp.matmul(
            _features(zb), params_pair, precision=jax.lax.Precision.HIGHEST
        )  # [chunk, Kb+Ka]
        return _logsumexp_rows(comp[:, :k_below]) - _logsumexp_rows(
            comp[:, k_below:]
        )

    if C <= chunk:
        return score_block(z)
    n_chunks = -(-C // chunk)
    pad = n_chunks * chunk - C
    zp = jnp.pad(z, (0, pad)).reshape(n_chunks, chunk)
    out = jax.lax.map(score_block, zp)
    return out.reshape(-1)[:C]
