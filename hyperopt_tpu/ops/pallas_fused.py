"""Pallas TPU mega-kernel: sampling → scoring → top-k in ONE launch.

The fused-suggest inner loop of Bergstra et al.'s TPE (draw candidates
from the below mixture l(x), rank them by ``log l(x) − log g(x)``, keep
the per-label winner) currently runs as a chain of XLA ops with the
candidate and score vectors round-tripping through HBM between stages,
and — on the Pallas scorer tier — a separate ``pallas_call`` for the
scoring alone.  ``DEVICE_PROFILE.json`` shows that chain compute-bound
at ~1.9% of its roofline: the headroom is in the kernel, not the
memory system.  This module fuses the whole loop into one
``pl.pallas_call`` so candidates, scores, and EI reductions live
entirely in VMEM/registers between stages:

- **draw** (per candidate tile, opt-in — see below): inverse-CDF
  component selection against the below mixture's VMEM-resident
  ``cdf`` (searchsorted computed as a ``count(cdf <= t)`` reduction —
  exactly ``jnp.searchsorted(..., side="right")`` on a monotone
  cumsum), then the truncated-normal inverse transform.  The raw
  uniforms are drawn OUTSIDE the kernel with the same ``jax.random``
  key discipline as :func:`hyperopt_tpu.ops.gmm.gmm_sample` (split →
  uniform, f32), and the in-kernel transform mirrors
  ``jax.random.truncated_normal``'s op chain term for term (erf bounds
  precomputed per component, ``max(a, u·(b−a)+a)`` → ``√2·erf_inv`` →
  nextafter clamp);
- **score**: the flash-style online logsumexp of
  :mod:`hyperopt_tpu.ops.pallas_gmm` (same ``_region_logsumexp``, same
  region padding, same tile sizes) over the ``[3, Kb+Ka]`` parameter
  block resident in VMEM — the ``[C, K]`` comp matrix never exists,
  and the per-candidate scores never leave registers;
- **select**: a running (best score, best value, best index) per
  (label, suggestion) accumulated across candidate tiles with strict-
  ``>`` updates (ties keep the earliest index — ``jnp.argmax``
  semantics), plus the EI-telemetry reductions
  (:func:`hyperopt_tpu.algos.tpe_device._ei_diag` parity): a running
  (max, sum-exp) pair and a running top-``n_top`` score set, merged
  tile by tile in-kernel and combined across segments by
  :func:`ei_from_partials` outside.

Tiling: the grid is ``(L, k, candidate-tiles)`` and the component axis
is tiled INSIDE the kernel by ``pl.ds`` lane slices over the
VMEM-resident parameter block (``tk``-sized steps, the
``pallas_gmm`` pattern) — at a 100k-trial history the block is
``[3, ~131k]`` ≈ 1.6 MB, comfortably VMEM-resident, and the inner loop
walks it in 512-lane tiles.  Candidate padding (``n_cand`` rounded up
to the tile) consumes NO extra uniforms — the u-streams are generated
at exactly ``k·n_cand`` and padded after — so the draw stream stays
aligned with the unfused path.

Numeric contract: in the DEFAULT exact-draw mode the candidates are
``gmm_ops.gmm_sample``'s own values (drawn inside the same fused XLA
program and streamed through the kernel — bit-identical to the unfused
draw by construction), and the scores are bit-identical to
``pair_score_pallas_batched`` at the same tile sizes (same online
accumulation): the winner matches the Pallas scorer tier bit-for-bit
and the XLA tier up to float-associativity near-ties in the score.
The full in-kernel draw is a further opt-in (:func:`resolve_fused_draw`
— ``HYPEROPT_TPU_FUSED_DRAW=1``): measured on this jax build, XLA's
FMA contraction inside ``gmm_sample``'s jit rounds ``μ + σ·u`` once
while a separate context rounds it twice, so in-kernel-drawn candidate
values differ from the unfused draw in the last 1-2 ulp — hence
default off, with the tolerance documented here and in docs/API.md.

CPU/testing: ``interpret=None`` resolves to the Pallas interpreter
off-TPU, so forcing the fused tier on CPU (``HYPEROPT_TPU_SCORER=
fused``) runs interpret-mode automatically.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .pallas_gmm import (
    NEG_BIG,
    _pad_regions,
    _region_logsumexp,
    _region_tile,
    env_bool,
    resolve_fma,
)

EPS = 1e-12
_SQRT2 = np.float32(np.sqrt(2.0))

# accumulator lane layout (row 0 of the [8, 128] per-(label, suggestion)
# block); row 1 carries the running top-k score set in lanes [0, n_top)
_ACC_BEST, _ACC_VAL, _ACC_ARG, _ACC_M, _ACC_S = 0, 1, 2, 3, 4


def draw_param_rows(w, mu, sigma, low, high):
    """The below-mixture draw, precomputed to the 7-row per-component
    block the kernel's sampling stage reads ([7, K]):

    ``cdf`` (cumsum of in-bounds mass — the inverse-CDF table),
    ``mu``, ``sigma``, ``erf(a/√2)``, ``erf(b/√2)`` (the
    truncated-normal uniform bounds), ``nextafter(a, +inf)``,
    ``nextafter(b, −inf)`` (its clamp bounds) — every term computed
    with the exact op chain of ``gmm_ops.gmm_sample`` +
    ``jax.random.truncated_normal`` so the in-kernel transform
    reproduces the unfused draw bit-for-bit.
    """
    from jax.scipy.special import ndtr

    a = (low - mu) / jnp.maximum(sigma, EPS)
    b = (high - mu) / jnp.maximum(sigma, EPS)
    a = jnp.clip(a, -30.0, 30.0)
    b = jnp.clip(b, -30.0, 30.0)
    Z = ndtr(b) - ndtr(a)
    p = jnp.maximum(w * Z, 0.0)
    cdf = jnp.cumsum(p)
    return jnp.stack([
        cdf,
        mu,
        sigma,
        jax.lax.erf(a / _SQRT2),
        jax.lax.erf(b / _SQRT2),
        jnp.nextafter(a, jnp.float32(np.inf)),
        jnp.nextafter(b, jnp.float32(-np.inf)),
    ])


def _fused_kernel(uv_ref, dp_ref, p_ref, acc_ref, *, KD, KB, KA, TKB, TKA,
                  k_real, n_cand, tc, n_top, log_scale, draw_in_kernel, fma):
    i = pl.program_id(2)
    uv = uv_ref[0, 0]                      # [TC, 2]

    if draw_in_kernel:
        # --- draw: inverse-CDF component pick + truncated-normal ------
        u1, u2 = uv[:, 0], uv[:, 1]
        dp = dp_ref[0]                     # [8, KD]
        cdf = dp[0]
        total = cdf[KD - 1]                # KD pads cdf with its edge value
        t = jnp.minimum(u1 * total, total * jnp.float32(1.0 - 1e-6))
        # searchsorted(cdf, t, side="right") on a monotone cumsum is the
        # count of entries <= t; padding entries equal total > t and are
        # never counted (exact integer equivalence, no binary search)
        ik = jax.lax.broadcasted_iota(jnp.int32, (tc, KD), 1)
        comp = jnp.sum((cdf[None, :] <= t[:, None]).astype(jnp.float32),
                       axis=1).astype(jnp.int32)
        comp = jnp.minimum(comp, k_real - 1)
        sel = (comp[:, None] == ik).astype(jnp.float32)  # exact one-hot

        def pick(row):
            # one-hot masked sum: exactly one term is 1·v, the rest 0·v
            # — an exact gather however Mosaic vectorizes the reduction
            return jnp.sum(sel * row[None, :], axis=1)

        mu_s, sig_s = pick(dp[1]), pick(dp[2])
        ae, be = pick(dp[3]), pick(dp[4])
        lo_n, hi_n = pick(dp[5]), pick(dp[6])
        # jax.random.truncated_normal's op chain, term for term.  NOTE
        # (the documented tolerance of the in-kernel draw): XLA is free
        # to contract mul+add chains into FMAs differently here than
        # inside gmm_sample's jit, so the drawn values can differ from
        # the unfused draw in the last ulp — which is why this mode is
        # an explicit opt-in (resolve_fused_draw) and the default
        # streams gmm_sample's own candidates through the kernel.
        u = jnp.maximum(ae, u2 * (be - ae) + ae)
        xt = _SQRT2 * jax.lax.erf_inv(u)
        xt = jnp.clip(xt, lo_n, hi_n)
        xf = mu_s + sig_s * xt             # fit-space candidate
        if log_scale:
            x = jnp.exp(xf)                # raw candidate (gmm_sample)
        else:
            x = xf
    else:
        # exact-draw mode (the default): lane 0 carries the candidates
        # gmm_sample drew inside the same fused program — bit-identical
        # to the unfused path by construction
        x = uv[:, 0]
    if log_scale:
        z = jnp.log(jnp.maximum(x, jnp.float32(EPS)))  # scorer z (tpe)
    else:
        z = x

    # --- score: online logsumexp over both mixture regions ------------
    f = jnp.stack([z * z, z, jnp.ones_like(z)], axis=-1)  # [TC, 3]
    ll_b = _region_logsumexp(f, p_ref, 0, KB, TKB, lead=0, fma=fma)
    ll_a = _region_logsumexp(f, p_ref, KB, KA, TKA, lead=0, fma=fma)
    score = ll_b - ll_a

    # --- select: running winner + EI partials --------------------------
    neg_inf = jnp.float32(-jnp.inf)
    cidx = jax.lax.broadcasted_iota(jnp.int32, (tc, 1), 0)[:, 0] + i * tc
    valid = cidx < n_cand
    big_i = jnp.int32(2 ** 30)
    sw = jnp.where(valid, score, neg_inf)
    tile_best = jnp.max(sw)
    tile_arg = jnp.min(jnp.where(sw == tile_best, cidx, big_i))
    tile_val = jnp.sum(jnp.where(cidx == tile_arg, x, 0.0))
    # sanitized scores for the EI reductions (tpe_device._ei_diag parity);
    # padding lanes are -inf so they contribute exactly zero mass
    sd = jnp.clip(
        jnp.nan_to_num(score, nan=-1e30, posinf=1e30, neginf=-1e30),
        -1e30, 1e30,
    )
    tile_m = jnp.max(jnp.where(valid, sd, jnp.float32(NEG_BIG)))

    prev = acc_ref[0, 0]                   # [8, 128]
    first = i == 0
    best0 = jnp.where(first, neg_inf, prev[0, _ACC_BEST])
    val0 = jnp.where(first, 0.0, prev[0, _ACC_VAL])
    arg0 = jnp.where(first, 0.0, prev[0, _ACC_ARG])
    m0 = jnp.where(first, jnp.float32(NEG_BIG), prev[0, _ACC_M])
    s0 = jnp.where(first, 0.0, prev[0, _ACC_S])
    top0 = jnp.where(first, neg_inf, prev[1, :])  # [128]

    upd = tile_best > best0                # strict: ties keep the earlier
    best1 = jnp.where(upd, tile_best, best0)
    val1 = jnp.where(upd, tile_val, val0)
    arg1 = jnp.where(upd, tile_arg.astype(jnp.float32), arg0)
    m1 = jnp.maximum(m0, tile_m)
    s1 = s0 * jnp.exp(m0 - m1) + jnp.sum(
        jnp.where(valid, jnp.exp(sd - m1), 0.0)
    )

    # running top-n_top: merge the carried set with this tile's
    # sanitized scores by n_top rounds of (max, first-index mask-out) —
    # no lax.top_k/sort inside the kernel (Mosaic-safe)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)[0]
    carried = jnp.where(lane < n_top, top0, neg_inf)
    combined = jnp.concatenate([carried, jnp.where(valid, sd, neg_inf)])
    M = combined.shape[0]
    mi = jax.lax.broadcasted_iota(jnp.int32, (M, 1), 0)[:, 0]

    def sel_step(n, carry):
        vals, tops = carry
        cur = jnp.max(vals)
        firsti = jnp.min(jnp.where(vals == cur, mi, big_i))
        vals = jnp.where(mi == firsti, neg_inf, vals)
        tops = jnp.where(lane == n, cur, tops)
        return vals, tops

    _, top1 = jax.lax.fori_loop(
        0, n_top, sel_step, (combined, jnp.full((128,), neg_inf))
    )

    row = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    lane2 = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)
    row0 = jnp.where(
        lane2 == _ACC_BEST, best1,
        jnp.where(lane2 == _ACC_VAL, val1,
                  jnp.where(lane2 == _ACC_ARG, arg1,
                            jnp.where(lane2 == _ACC_M, m1,
                                      jnp.where(lane2 == _ACC_S, s1, 0.0)))),
    )
    acc_ref[0, 0] = jnp.where(
        row == 0, row0, jnp.where(row == 1, top1[None, :], 0.0)
    )


def _default_interpret() -> bool:
    """Interpreter off-TPU (the CPU/CI path), Mosaic on real TPUs.
    ``HYPEROPT_TPU_FUSED_INTERPRET=0/1`` overrides — the partition
    audit traces with 0 so the ``pallas_call`` primitive (and its
    operand shardings) stay visible in the jaxpr, and the bench quick
    smoke forces 1."""
    v = env_bool("HYPEROPT_TPU_FUSED_INTERPRET")
    if v is not None:
        return v
    return jax.default_backend() != "tpu"


def fused_suggest_pallas(
    u_comp,        # [L, C] f32: raw component-selection uniforms
                   #   (draw_in_kernel) or gmm_sample's candidates (not)
    u_val,         # [L, C] f32: raw truncated-normal uniforms
                   #   (draw_in_kernel only; pass zeros otherwise)
    draw_params,   # [L, 7, Kb] f32 from draw_param_rows (vmapped);
                   #   zeros when draw_in_kernel=False
    params_pair,   # [L, 3, Kb+Ka] f32 from ops.score.pair_params
    k_below: int,  # static: Kb — the draw mixture's component count too
    k: int,        # static: suggestions per label (C = k * n_cand)
    n_top: int = 16,
    tc: int = 512,
    tk: int = 512,
    log_scale: bool = False,
    draw_in_kernel: bool = False,
    interpret=None,
    fma=None,
):
    """The fused suggest inner loop as ONE Pallas launch.

    ``draw_in_kernel=False`` (the bit-exact default): ``u_comp`` carries
    the candidates ``gmm_sample`` drew inside the same fused program and
    the kernel fuses scoring → top-k → EI reductions over them.
    ``draw_in_kernel=True`` (opt-in, :func:`resolve_fused_draw`): the
    kernel also performs the draw from raw uniforms — candidate values
    then match the unfused draw only up to FMA-contraction ulps (the
    documented tolerance).

    Returns ``(win, best_idx, seg_m, seg_s, seg_top)``:

    - ``win`` ``[L, k]`` — the winning candidate VALUES (raw space),
      exactly ``cands[argmax(score)]`` of the unfused path;
    - ``best_idx`` ``[L, k]`` i32 — the winning candidate's index
      within its ``n_cand`` segment (tests/debugging);
    - ``seg_m``/``seg_s`` ``[L, k]`` — per-segment online-logsumexp
      partials over the sanitized scores;
    - ``seg_top`` ``[L, k, n_top]`` — per-segment top-``n_top``
      sanitized scores (−inf padded).

    Combine the partials with :func:`ei_from_partials` for the
    ``_ei_diag``-parity per-label reductions.
    """
    if fma is None:
        fma = resolve_fma("batched")
    if interpret is None:
        interpret = _default_interpret()
    return _fused_suggest_pallas(
        u_comp, u_val, draw_params, params_pair, k_below, k, n_top, tc, tk,
        log_scale, draw_in_kernel, interpret, fma,
    )


@partial(jax.jit, static_argnames=(
    "k_below", "k", "n_top", "tc", "tk", "log_scale", "draw_in_kernel",
    "interpret", "fma",
))
def _fused_suggest_pallas(
    u_comp, u_val, draw_params, params_pair, k_below: int, k: int,
    n_top: int, tc, tk, log_scale, draw_in_kernel, interpret, fma,
):
    L, C = u_comp.shape
    if C % k:
        raise ValueError(f"candidate count {C} not divisible by k={k}")
    n_cand = C // k
    n_top = min(int(n_top), n_cand * k)
    if n_top > 128:
        raise ValueError(f"n_top={n_top} exceeds the accumulator row")

    # scoring regions: the pallas_gmm pad/tile scheme, bit-compatible
    # with pair_score_pallas_batched at the same (tc, tk)
    tkb = _region_tile(k_below, tk)
    tka = _region_tile(params_pair.shape[2] - k_below, tk)
    params_pair, KB, KA = _pad_regions(params_pair, k_below, tkb, tka)

    # draw block: rows padded 7 → 8 (f32 sublane tile), components
    # lane-padded with the cdf's edge value (total — never selected,
    # since t < total strictly) and zeros elsewhere (never gathered,
    # comp is clipped to k_real-1 < Kb)
    KD = max(128, -(-k_below // 128) * 128)
    pad_k = KD - k_below
    dp = jnp.pad(draw_params, ((0, 0), (0, 1), (0, 0)))        # [L, 8, Kb]
    if pad_k:
        cdf_tail = jnp.repeat(dp[:, :1, -1:], pad_k, axis=2)    # edge value
        tail = jnp.concatenate(
            [cdf_tail, jnp.zeros((L, 7, pad_k), dp.dtype)], axis=1
        )
        dp = jnp.concatenate([dp, tail], axis=2)                # [L, 8, KD]

    # candidate tiles: pad each n_cand segment up to the tile multiple
    # AFTER the u-streams were drawn at exactly k*n_cand — padding
    # consumes no uniforms, keeping the draw aligned with gmm_sample
    tc_eff = min(tc, -(-n_cand // 8) * 8)
    n_t = -(-n_cand // tc_eff)
    cp = n_t * tc_eff - n_cand
    uv = jnp.stack([u_comp, u_val], axis=-1).reshape(L, k, n_cand, 2)
    if cp:
        uv = jnp.pad(uv, ((0, 0), (0, 0), (0, cp), (0, 0)))

    acc = pl.pallas_call(
        partial(
            _fused_kernel, KD=KD, KB=KB, KA=KA, TKB=tkb, TKA=tka,
            k_real=k_below, n_cand=n_cand, tc=tc_eff, n_top=n_top,
            log_scale=log_scale, draw_in_kernel=draw_in_kernel, fma=fma,
        ),
        out_shape=jax.ShapeDtypeStruct((L, k, 8, 128), jnp.float32),
        grid=(L, k, n_t),
        in_specs=[
            pl.BlockSpec((1, 1, tc_eff, 2), lambda l, j, i: (l, j, i, 0)),
            pl.BlockSpec((1, 8, KD), lambda l, j, i: (l, 0, 0)),
            pl.BlockSpec((1, 3, KB + KA), lambda l, j, i: (l, 0, 0)),
        ],
        # constant over the candidate-tile dim: the block stays resident
        # and accumulates across tiles (the flash-attention revisit
        # pattern) — written back once per (l, j)
        out_specs=pl.BlockSpec((1, 1, 8, 128), lambda l, j, i: (l, j, 0, 0)),
        interpret=interpret,
    )(uv, dp, params_pair)

    win = acc[:, :, 0, _ACC_VAL]
    best_idx = acc[:, :, 0, _ACC_ARG].astype(jnp.int32)
    seg_m = acc[:, :, 0, _ACC_M]
    seg_s = acc[:, :, 0, _ACC_S]
    seg_top = acc[:, :, 1, :n_top]
    return win, best_idx, seg_m, seg_s, seg_top


def ei_from_partials(seg_m, seg_s, seg_top, n_cand_total: int, n_top: int):
    """Combine the kernel's per-(label, segment) partials into the
    per-label EI reductions of ``tpe_device._ei_diag``: ``(max,
    log-mean-exp, top-k softmax mass)`` each ``[L]``.

    ``seg_m``/``seg_s`` are per-segment online-logsumexp states over the
    sanitized scores; the cross-segment combine is the standard
    max-rebased merge (exact for the max, standard fp association for
    the sum — the EI columns are telemetry, compared with tolerance).
    ``seg_top`` per-segment top sets contain the global top set as a
    subset, so a top-k over their concatenation is the global top-k.
    """
    m_star = jnp.max(seg_m, axis=1)                       # [L]
    s_tot = jnp.sum(seg_s * jnp.exp(seg_m - m_star[:, None]), axis=1)
    lse = m_star + jnp.log(jnp.maximum(s_tot, 1e-300))
    lme = lse - jnp.float32(np.log(n_cand_total))
    L = seg_m.shape[0]
    flat = seg_top.reshape(L, -1)
    kk = min(int(n_top), n_cand_total, flat.shape[1])
    topk = jax.lax.top_k(flat, kk)[0]
    mass = jnp.sum(jnp.exp(topk - lse[:, None]), axis=1)
    return m_star, lme, mass


# ---------------------------------------------------------------------
# Tier resolution (resolve_fma-style; see ops.score.effective_scorer)
# ---------------------------------------------------------------------

# process-wide measured default, set by the TPU timing probe in
# hyperopt_tpu.algos.tpe (None until a probe or set_default_fused call)
_fused_measured_default = None


def set_default_fused(value) -> None:
    """Record the TPU probe's verdict (True/False) — or ``None`` to
    clear it (tests)."""
    global _fused_measured_default
    _fused_measured_default = None if value is None else bool(value)


def resolve_fused() -> bool:
    """Should the auto-selected scorer use the fused mega-kernel?

    Resolution order (the ``resolve_fma`` pattern):

    1. ``HYPEROPT_TPU_FUSED=0/1`` env override;
    2. the measured default (:func:`set_default_fused`, written by the
       per-process TPU probe in ``algos.tpe``);
    3. off — the fused tier is **opt-in**: its winner can differ from
       the XLA tier's at float-associativity near-ties, so the default
       path stays bit-exact (docs/API.md "Scorer tiers").

    An explicit ``HYPEROPT_TPU_SCORER=fused`` bypasses this resolver
    entirely (forced scorers are honored verbatim).
    """
    v = env_bool("HYPEROPT_TPU_FUSED")
    if v is not None:
        return v
    if _fused_measured_default is not None:
        return _fused_measured_default
    return False


def resolve_fused_draw() -> bool:
    """Should the fused kernel ALSO perform the candidate draw in-kernel
    (``HYPEROPT_TPU_FUSED_DRAW=1``)?  Default off: the in-kernel draw's
    values can differ from ``gmm_sample``'s in the last ulp (XLA FMA
    contraction differs between program contexts), so the bit-exact
    default streams ``gmm_sample``'s own candidates through the kernel
    instead.  Tolerance when on: candidate values within 1-2 ulp of the
    unfused draw; at score near-ties the winner index may differ."""
    return bool(env_bool("HYPEROPT_TPU_FUSED_DRAW"))
