"""JAX samplers for the search-space DSL distributions.

The numpy implementations in ``hyperopt_tpu.pyll.stochastic`` define the
semantics (support + quantization rule); these are the XLA lowerings the
compiled sampler uses — same distributions, ``jax.random`` key-splitting
instead of a shared mutable rng (reference:
``hyperopt/pyll/stochastic.py`` ~L20-130).

Every sampler has signature ``f(key, params: dict, n: int) -> jnp.ndarray``
with static ``params``/``n`` so a whole-space sampler jits into one fused
program.  Quantization matches the reference rule ``round(x / q) * q``
(round-half-to-even, numpy semantics) exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_FLOAT = jnp.float32
_INT = jnp.int32


def _quantize(x, q):
    # jnp.round is round-half-to-even, matching np.round in the reference
    return jnp.round(x / q) * q


def uniform(key, p, n):
    return jax.random.uniform(
        key, (n,), dtype=_FLOAT, minval=p["low"], maxval=p["high"]
    )


def quniform(key, p, n):
    return _quantize(uniform(key, p, n), p["q"])


def loguniform(key, p, n):
    return jnp.exp(uniform(key, p, n))


def qloguniform(key, p, n):
    return _quantize(loguniform(key, p, n), p["q"])


def uniformint(key, p, n):
    # reference semantics: round(uniform(low, high) / q) * q, as integer —
    # endpoints get half weight (NOT the same as randint(low, high))
    return _quantize(uniform(key, p, n), p.get("q", 1.0)).astype(_INT)


def normal(key, p, n):
    return p["mu"] + p["sigma"] * jax.random.normal(key, (n,), dtype=_FLOAT)


def qnormal(key, p, n):
    return _quantize(normal(key, p, n), p["q"])


def lognormal(key, p, n):
    return jnp.exp(normal(key, p, n))


def qlognormal(key, p, n):
    return _quantize(lognormal(key, p, n), p["q"])


def randint(key, p, n):
    low = p.get("low", 0)
    high = p["high"]
    return jax.random.randint(key, (n,), low, high, dtype=_INT)


def categorical(key, p, n):
    logits = jnp.log(jnp.asarray(p["p"], dtype=_FLOAT))
    return jax.random.categorical(key, logits, shape=(n,)).astype(_INT)


SAMPLERS = {
    "uniform": uniform,
    "quniform": quniform,
    "loguniform": loguniform,
    "qloguniform": qloguniform,
    "uniformint": uniformint,
    "normal": normal,
    "qnormal": qnormal,
    "lognormal": lognormal,
    "qlognormal": qlognormal,
    "randint": randint,
    "categorical": categorical,
}

# distributions whose values are integer-valued indices/counts
INT_DISTS = {"uniformint", "randint", "categorical"}
