"""Adaptive-Parzen estimator fit as a fixed-shape XLA kernel.

Reference parity (SURVEY.md §2 #11): ``hyperopt/tpe.py`` —
``adaptive_parzen_normal`` / ``linear_forgetting_weights`` (~L40-200): the
per-observation sigma heuristic (max of neighbor gaps in sorted order),
prior-as-extra-component insertion at the sorted position, sigma clamping to
``[prior_sigma/min(100, 1+K), prior_sigma]``, the one-observation special
case (``sigma = prior_sigma/2``), and linear-forgetting ramp weights over
chronological order.

TPU-first redesign: the reference refits with numpy per label per suggest
(O(history log history) Python).  Here the fit is one jitted program over a
**padded** observation buffer (``PAD`` static, ``n_obs`` dynamic) so history
growth never recompiles within a bucket; invalid slots carry weight 0.
Sorting, prior insertion (scatter), neighbor gaps, and ramp weights are all
fixed-shape array ops that fuse into the downstream GMM scoring kernel.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def bucket(n: int, minimum: int = 8) -> int:
    """Power-of-two padding bucket: bounds jit recompiles to O(log history)."""
    n = max(int(n), 1)
    return max(minimum, 1 << (n - 1).bit_length())


def linear_forgetting_weights_padded(n_obs, lf: int, pad: int):
    """Chronological observation weights, padded to ``pad``.

    Oldest ``n_obs - lf`` observations get a linear ramp from ``1/n_obs`` to
    1; the newest ``lf`` get weight 1.  ``lf <= 0`` disables forgetting.
    """
    i = jnp.arange(pad, dtype=jnp.float32)
    n = jnp.maximum(n_obs, 1).astype(jnp.float32)
    ramp_len = n_obs - lf  # dynamic
    denom = jnp.maximum(ramp_len - 1, 1).astype(jnp.float32)
    ramp = 1.0 / n + (1.0 - 1.0 / n) * i / denom
    w = jnp.where(i < ramp_len, ramp, 1.0)
    use_ramp = (lf > 0) & (n_obs > lf)
    w = jnp.where(use_ramp, w, 1.0)
    return jnp.where(i < n_obs, w, 0.0)


@partial(jax.jit, static_argnames=("lf",))
def adaptive_parzen_normal_padded(
    obs, n_obs, prior_weight, prior_mu, prior_sigma, lf: int
):
    """Fit the adaptive Parzen mixture on a padded observation buffer.

    Args:
      obs: ``[PAD]`` observation values in *chronological* order; only the
        first ``n_obs`` entries are valid.
      n_obs: dynamic count of valid observations.
      prior_weight / prior_mu / prior_sigma: the prior component.
      lf: linear-forgetting horizon (static; 0 disables).

    Returns:
      ``(weights, mus, sigmas)`` each ``[PAD+1]`` — the mixture in sorted-mu
      order with the prior inserted at its sorted position; the first
      ``n_obs + 1`` entries are valid, the rest have weight exactly 0.
    """
    pad = obs.shape[0]
    K = pad + 1
    f32 = jnp.float32
    obs = obs.astype(f32)
    i_pad = jnp.arange(pad)
    i_out = jnp.arange(K)
    valid = i_pad < n_obs

    big = jnp.where(valid, obs, jnp.inf)
    order = jnp.argsort(big)  # valid obs sorted to the front
    srtd = big[order]

    # searchsorted-left position of the prior among valid observations
    prior_pos = jnp.sum(jnp.where(valid, obs < prior_mu, False))

    # scatter sorted obs around the prior slot
    out_pos = i_pad + (i_pad >= prior_pos)
    srtd_mus = (
        jnp.zeros(K, f32)
        .at[out_pos]
        .set(jnp.where(i_pad < n_obs, srtd, 0.0))
        .at[prior_pos]
        .set(prior_mu)
    )

    n_tot = n_obs + 1
    prev = srtd_mus[jnp.maximum(i_out - 1, 0)]
    nxt = srtd_mus[jnp.minimum(i_out + 1, K - 1)]
    left_gap = srtd_mus - prev
    right_gap = nxt - srtd_mus
    sigma = jnp.maximum(left_gap, right_gap)
    sigma = jnp.where(i_out == 0, right_gap, sigma)
    sigma = jnp.where(i_out == n_tot - 1, left_gap, sigma)
    # one observation: the non-prior component gets prior_sigma/2
    sigma = jnp.where(
        (n_obs == 1) & (i_out != prior_pos), 0.5 * prior_sigma, sigma
    )

    maxsigma = prior_sigma
    minsigma = prior_sigma / jnp.minimum(100.0, 1.0 + n_tot.astype(f32))
    sigma = jnp.clip(sigma, minsigma, maxsigma)
    sigma = sigma.at[prior_pos].set(prior_sigma)

    # chronological forgetting weights -> sorted order -> prior inserted
    w_chrono = linear_forgetting_weights_padded(n_obs, lf, pad)
    w_sorted = w_chrono[order]
    srtd_w = (
        jnp.zeros(K, f32)
        .at[out_pos]
        .set(jnp.where(i_pad < n_obs, w_sorted, 0.0))
        .at[prior_pos]
        .set(prior_weight)
    )
    srtd_w = jnp.where(i_out < n_tot, srtd_w, 0.0)
    srtd_w = srtd_w / jnp.sum(srtd_w)

    return srtd_w, srtd_mus, sigma
