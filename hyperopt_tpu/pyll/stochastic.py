"""Stochastic scope symbols + graph sampling.

Reference parity (SURVEY.md §2 #2): ``hyperopt/pyll/stochastic.py`` —
``@implicit_stochastic`` registry, distribution scope symbols (~L20-130),
``recursive_set_rng_kwarg`` (~L130-155), ``sample`` (~L155-170).

These numpy implementations define the *semantics* of every distribution
(support, quantization rule) and serve the interpreted fallback path and the
statistical test suite.  The TPU execution path does not call them per trial:
``hyperopt_tpu.vectorize`` lowers the same distributions onto ``jax.random``
(see ``hyperopt_tpu.ops.dists``) with key-splitting replacing the mutable
``rng`` literal injected here.
"""

from __future__ import annotations

import numpy as np

from .base import Apply, Literal, as_apply, clone, dfs, rec_eval, scope

# names of scope symbols that consume an `rng` keyword implicitly
implicit_stochastic_symbols = set()


def implicit_stochastic(f):
    implicit_stochastic_symbols.add(f.__name__)
    return f


def _rng(rng):
    if rng is None:
        raise ValueError(
            "stochastic node evaluated without an rng; use "
            "hyperopt_tpu.pyll.stochastic.sample() or inject one with "
            "recursive_set_rng_kwarg()"
        )
    return rng


def _quantize(x, q):
    return np.round(x / q) * q


@implicit_stochastic
@scope.define
def uniform(low, high, rng=None, size=()):
    return _rng(rng).uniform(low, high, size=size)


@implicit_stochastic
@scope.define
def loguniform(low, high, rng=None, size=()):
    # low/high are bounds in log space, as in the reference DSL
    return np.exp(_rng(rng).uniform(low, high, size=size))


@implicit_stochastic
@scope.define
def quniform(low, high, q, rng=None, size=()):
    return _quantize(_rng(rng).uniform(low, high, size=size), q)


@implicit_stochastic
@scope.define
def qloguniform(low, high, q, rng=None, size=()):
    return _quantize(np.exp(_rng(rng).uniform(low, high, size=size)), q)


@implicit_stochastic
@scope.define
def uniformint(low, high, q=1.0, rng=None, size=()):
    return _quantize(_rng(rng).uniform(low, high, size=size), q).astype(np.int64)


@implicit_stochastic
@scope.define
def normal(mu, sigma, rng=None, size=()):
    return _rng(rng).normal(mu, sigma, size=size)


@implicit_stochastic
@scope.define
def qnormal(mu, sigma, q, rng=None, size=()):
    return _quantize(_rng(rng).normal(mu, sigma, size=size), q)


@implicit_stochastic
@scope.define
def lognormal(mu, sigma, rng=None, size=()):
    return np.exp(_rng(rng).normal(mu, sigma, size=size))


@implicit_stochastic
@scope.define
def qlognormal(mu, sigma, q, rng=None, size=()):
    return _quantize(np.exp(_rng(rng).normal(mu, sigma, size=size)), q)


@implicit_stochastic
@scope.define
def randint(low, high=None, rng=None, size=()):
    """``randint(upper)`` draws from [0, upper); ``randint(low, high)``
    from [low, high) — both reference DSL forms."""
    if high is None:
        low, high = 0, low
    return _rng(rng).integers(low, high, size=size)


@implicit_stochastic
@scope.define
def randint_via_categorical(p, rng=None, size=()):
    """Categorical draw used by TPE's posterior over integer/choice params."""
    p = np.asarray(p, dtype=np.float64)
    p = p / p.sum()
    rng = _rng(rng)
    if size == () or size is None:
        return np.argmax(rng.multinomial(1, p))
    n = int(np.prod(size))
    draws = np.array([np.argmax(rng.multinomial(1, p)) for _ in range(n)])
    return draws.reshape(size)


@implicit_stochastic
@scope.define
def categorical(p, upper=None, rng=None, size=()):
    """Draw an index according to probability vector ``p``."""
    p = np.asarray(p, dtype=np.float64)
    if p.ndim == 2 and p.shape[0] == 1:
        p = p[0]
    p = p / p.sum()
    rng = _rng(rng)
    if size == () or size is None:
        return np.argmax(rng.multinomial(1, p))
    n = int(np.prod(size))
    draws = np.array([np.argmax(rng.multinomial(1, p)) for _ in range(n)])
    return draws.reshape(size)


def recursive_set_rng_kwarg(expr, rng=None):
    """Inject an rng literal into every implicit-stochastic node in place."""
    if rng is None:
        rng = np.random.default_rng()
    rng_lit = rng if isinstance(rng, Apply) else Literal(rng)
    for node in dfs(as_apply(expr)):
        if node.name in implicit_stochastic_symbols:
            if not any(k == "rng" for k, _ in node.named_args):
                node.named_args.append(["rng", rng_lit])
                node.named_args.sort(key=lambda kv: kv[0])
    return expr


def sample(expr, rng=None, **kwargs):
    """Draw one realization of a stochastic expression graph.

    Clones the graph (so the caller's space is untouched), injects the rng,
    and evaluates.  This is the interpreted reference path; the compiled path
    is ``CompiledSpace.sample`` in ``hyperopt_tpu.vectorize``.
    """
    if rng is None:
        rng = np.random.default_rng()
    if isinstance(rng, np.random.RandomState):  # legacy numpy API
        rng = np.random.default_rng(rng.randint(2 ** 31))
    foo = recursive_set_rng_kwarg(clone(as_apply(expr)), rng)
    return rec_eval(foo, **kwargs)
