"""Stochastic expression graph — the ``pyll`` equivalent.

Reference parity (see SURVEY.md §2 #1): ``hyperopt/pyll/base.py`` —
``SymbolTable``/``scope`` (~L60-180), ``Apply`` (~L180-450), ``Literal``
(~L450-520), ``as_apply`` (~L520-560), ``dfs``/``toposort`` (~L560-640),
``rec_eval`` (~L640-830), ``clone``/``clone_merge`` (~L830-900), arithmetic
and container scope functions (~L900-1200).

TPU-first redesign note: in the reference this graph is *interpreted per
trial* (``rec_eval`` runs in the hot loop of every ``Domain.evaluate`` and
every TPE suggest).  Here the graph is only a declarative *frontend*: the
search space it describes is compiled once by ``hyperopt_tpu.vectorize`` into
a jitted ``jax.random`` sampler, and ``rec_eval`` survives solely for
(a) evaluating the user's objective wiring (``Domain.evaluate``) and
(b) exotic spaces the compiler cannot lower.  Nothing in this module touches
JAX; it is host-side Python by design.
"""

from __future__ import annotations

import numbers
from collections import deque

import numpy as np


class PyllImportError(ImportError):
    """Raised when a symbol is not found in the scope symbol table."""


# =====================================================================
# Symbol table
# =====================================================================


class SymbolTable:
    """Registry of named functions usable as graph nodes.

    ``scope.<name>(*args, **kwargs)`` builds an :class:`Apply` node; the
    implementation is looked up at evaluation time by :func:`rec_eval`.
    """

    def __init__(self):
        self._impls = {}
        self._pure = set()

    # -- introspection ------------------------------------------------
    def __contains__(self, name):
        return name in self._impls

    def impl(self, name):
        try:
            return self._impls[name]
        except KeyError:
            raise PyllImportError(f"no scope function named {name!r}")

    # -- registration -------------------------------------------------
    def define(self, f, name=None, pure=False):
        """Register ``f`` under ``name`` (default ``f.__name__``).

        Returns a builder so that ``scope.define``-decorated functions can
        still be called to create graph nodes: ``scope.uniform(0, 1)``.
        """
        name = name or f.__name__
        if hasattr(self, name):
            raise ValueError(f"Cannot override existing symbol: {name}")
        self._impls[name] = f
        if pure:
            self._pure.add(name)

        def apply_builder(*args, **kwargs):
            return Apply(
                name,
                [as_apply(a) for a in args],
                {k: as_apply(v) for k, v in kwargs.items()},
                o_len=None,
                pure=name in self._pure,
            )

        apply_builder.__name__ = name
        apply_builder.fn = f
        setattr(self, name, apply_builder)
        return apply_builder

    def define_pure(self, f):
        return self.define(f, pure=True)

    def define_info(self, o_len=None):
        """Decorator variant that records the output length of the node."""

        def wrapper(f):
            builder = self.define(f)
            orig = builder

            def with_o_len(*args, **kwargs):
                node = orig(*args, **kwargs)
                node.o_len = o_len
                return node

            with_o_len.__name__ = f.__name__
            with_o_len.fn = f
            setattr(self, f.__name__, with_o_len)
            self._impls[f.__name__] = f
            return with_o_len

        return wrapper


scope = SymbolTable()


def undefined(*args, **kwargs):  # pragma: no cover - defensive
    raise NotImplementedError("this scope symbol is evaluated specially")


# =====================================================================
# Graph nodes
# =====================================================================


class Apply:
    """A function application node in the expression graph.

    ``name`` is a key into :data:`scope`; ``pos_args`` and ``named_args``
    hold child nodes.  Identity semantics: nodes hash/compare by object
    identity (the graph is a DAG of shared nodes, not a value tree).
    """

    def __init__(self, name, pos_args, named_args, o_len=None, pure=False):
        self.name = name
        self.pos_args = list(pos_args)
        if isinstance(named_args, dict):
            named_args = sorted(named_args.items())
        # list of [kw, node], kept sorted by kw for deterministic traversal
        self.named_args = [[k, v] for k, v in named_args]
        self.o_len = o_len
        self.pure = pure
        assert all(isinstance(v, Apply) for v in self.pos_args)
        assert all(isinstance(v, Apply) for _, v in self.named_args)

    # -- structure ----------------------------------------------------
    def inputs(self):
        """All child nodes, positional then keyword (deterministic order)."""
        rval = self.pos_args + [v for _, v in self.named_args]
        assert all(isinstance(arg, Apply) for arg in rval)
        return rval

    @property
    def arg(self):
        """Mapping from argument name to node, best-effort for builtins."""
        rval = dict(self.named_args)
        try:
            code = scope.impl(self.name).__code__
            varnames = code.co_varnames[: code.co_argcount]
            for i, a in enumerate(self.pos_args):
                rval[varnames[i]] = a
        except (PyllImportError, AttributeError, IndexError):
            for i, a in enumerate(self.pos_args):
                rval[f"arg:{i}"] = a
        return rval

    def set_kwarg(self, name, value):
        """Set/overwrite a keyword argument (used to inject rng handles)."""
        for kv in self.named_args:
            if kv[0] == name:
                kv[1] = as_apply(value)
                return
        # try to convert a positional arg if the impl signature has `name`
        try:
            code = scope.impl(self.name).__code__
            varnames = code.co_varnames[: code.co_argcount]
            if name in varnames:
                pos = varnames.index(name)
                if pos < len(self.pos_args):
                    self.pos_args[pos] = as_apply(value)
                    return
        except PyllImportError:
            pass
        self.named_args.append([name, as_apply(value)])
        self.named_args.sort(key=lambda kv: kv[0])

    def clone_from_inputs(self, inputs, o_len="same"):
        if len(inputs) != len(self.inputs()):
            raise TypeError("inputs must match", (inputs, self.inputs()))
        L = len(self.pos_args)
        pos_args = list(inputs[:L])
        named_args = [
            [kw, inputs[L + ii]] for ii, (kw, _) in enumerate(self.named_args)
        ]
        if o_len == "same":
            o_len = self.o_len
        return self.__class__(self.name, pos_args, named_args, o_len)

    def replace_input(self, old_node, new_node):
        rval = []
        for ii, aa in enumerate(self.pos_args):
            if aa is old_node:
                self.pos_args[ii] = new_node
                rval.append(ii)
        for ii, (_, aa) in enumerate(self.named_args):
            if aa is old_node:
                self.named_args[ii][1] = new_node
                rval.append(ii + len(self.pos_args))
        return rval

    # -- pretty printing ----------------------------------------------
    def pprint(self, memo=None, depth=0, max_depth=8):
        if memo is None:
            memo = {}
        if self in memo:
            return memo[self]
        if depth > max_depth:
            return f"{self.name}(...)"
        parts = [a.pprint(memo, depth + 1, max_depth) for a in self.pos_args]
        parts += [
            f"{k}={v.pprint(memo, depth + 1, max_depth)}"
            for k, v in self.named_args
        ]
        s = f"{self.name}({', '.join(parts)})"
        memo[self] = s
        return s

    def __str__(self):
        return self.pprint()

    def __repr__(self):
        return f"<Apply {self.name} at {hex(id(self))}>"

    # -- len / indexing ------------------------------------------------
    def __len__(self):
        if self.o_len is None:
            return object.__len__(self)
        return self.o_len

    def __getitem__(self, idx):
        if isinstance(idx, Apply):
            return scope.getitem(self, idx)
        return scope.getitem(self, as_apply(idx))

    # -- arithmetic sugar ----------------------------------------------
    def __add__(self, other):
        return scope.add(self, other)

    def __radd__(self, other):
        return scope.add(other, self)

    def __sub__(self, other):
        return scope.sub(self, other)

    def __rsub__(self, other):
        return scope.sub(other, self)

    def __mul__(self, other):
        return scope.mul(self, other)

    def __rmul__(self, other):
        return scope.mul(other, self)

    def __truediv__(self, other):
        return scope.truediv(self, other)

    def __rtruediv__(self, other):
        return scope.truediv(other, self)

    def __floordiv__(self, other):
        return scope.floordiv(self, other)

    def __rfloordiv__(self, other):
        return scope.floordiv(other, self)

    def __pow__(self, other):
        return scope.pow(self, other)

    def __rpow__(self, other):
        return scope.pow(other, self)

    def __neg__(self):
        return scope.neg(self)

    def __abs__(self):
        return scope.abs_(self)


class Literal(Apply):
    """A constant leaf node wrapping an arbitrary Python object."""

    def __init__(self, obj=None):
        try:
            o_len = len(obj)
        except TypeError:
            o_len = None
        Apply.__init__(self, "literal", [], {}, o_len, pure=True)
        self._obj = obj

    @property
    def obj(self):
        return self._obj

    def pprint(self, memo=None, depth=0, max_depth=8):
        return repr(self._obj)

    def __repr__(self):
        return f"<Literal {self._obj!r}>"

    def replace_input(self, old_node, new_node):
        return []

    def clone_from_inputs(self, inputs, o_len="same"):
        return self.__class__(self._obj)


def as_apply(obj):
    """Smart constructor: lift a Python value into the graph.

    dicts/lists/tuples become container nodes so that nested search spaces
    are themselves graphs; everything else becomes a :class:`Literal`.
    """
    if isinstance(obj, Apply):
        return obj
    if isinstance(obj, tuple):
        return Apply(
            "pos_args", [as_apply(a) for a in obj], {}, o_len=len(obj), pure=True
        )
    if isinstance(obj, list):
        return Apply("pos_args", [as_apply(a) for a in obj], {}, o_len=None, pure=True)
    if isinstance(obj, dict):
        items = sorted(obj.items())
        if all(isinstance(k, str) for k, _ in items):
            named = {k: as_apply(v) for k, v in items}
            return Apply("dict", [], named, o_len=len(named), pure=True)
        # non-string keys: keep as a literal mapping of lifted pairs
        return Apply(
            "dict_pairs",
            [as_apply((k, v)) for k, v in items],
            {},
            o_len=len(items),
            pure=True,
        )
    return Literal(obj)


# =====================================================================
# Traversal
# =====================================================================


def dfs(aa, seq=None, seqset=None):
    """Post-order depth-first traversal: inputs appear before consumers."""
    if seq is None:
        assert seqset is None
        seq = []
        seqset = {}
    if aa in seqset:
        return seq
    assert isinstance(aa, Apply)
    seqset[aa] = True
    for ii in aa.inputs():
        dfs(ii, seq, seqset)
    seq.append(aa)
    return seq


def toposort(expr):
    """Topological ordering of the graph ending at ``expr``.

    Equivalent to the reference's networkx-based toposort; DFS post-order
    is already a valid topological order for a DAG.
    """
    return dfs(expr)


def clone(expr, memo=None):
    """Deep-copy the graph, preserving internal sharing."""
    if memo is None:
        memo = {}
    nodes = dfs(expr)
    for node in nodes:
        if node not in memo:
            new_inputs = [memo[arg] for arg in node.inputs()]
            memo[node] = node.clone_from_inputs(new_inputs)
    return memo[expr]


def clone_merge(expr, memo=None, merge_literals=False):
    """Clone while merging identical pure nodes (CSE)."""
    if memo is None:
        memo = {}
    nodes = dfs(expr)
    keyed = {}
    for node in nodes:
        if node in memo:
            continue
        new_inputs = [memo[arg] for arg in node.inputs()]
        if node.pure and (merge_literals or not isinstance(node, Literal)):
            if isinstance(node, Literal):
                try:
                    key = (node.name, repr(node.obj))
                except Exception:  # unreprable literal
                    key = (node.name, id(node))
            else:
                key = (
                    node.name,
                    tuple(id(a) for a in new_inputs),
                    tuple(k for k, _ in node.named_args),
                )
            if key in keyed:
                memo[node] = keyed[key]
                continue
            new_node = node.clone_from_inputs(new_inputs)
            keyed[key] = new_node
            memo[node] = new_node
        else:
            memo[node] = node.clone_from_inputs(new_inputs)
    return memo[expr]


# =====================================================================
# Evaluation
# =====================================================================


class GarbageCollected:
    """Sentinel for memo entries that must never be used.

    ``Domain.memo_from_config`` maps inactive conditional hyperparameters to
    this class; lazy ``switch`` evaluation guarantees they are never read.
    """


def rec_eval(
    expr,
    deepcopy_inputs=False,
    memo=None,
    max_program_len=100000,
    memo_gc=True,
    print_node_on_error=True,
    return_memo=False,
):
    """Evaluate the graph iteratively (no Python recursion limit).

    ``switch`` is lazy: only the selected branch is evaluated, which is what
    makes conditional search spaces (``hp.choice``) work — inactive branches
    may reference hyperparameters that have no value in ``memo``.
    """
    if memo is None:
        memo = {}
    else:
        memo = dict(memo)
    node = as_apply(expr)
    todo = deque([node])
    steps = 0
    while todo:
        steps += 1
        if steps > max_program_len:
            raise RuntimeError("rec_eval exceeded max program length")
        current = todo[-1]
        if current in memo:
            todo.pop()
            continue
        if isinstance(current, Literal):
            memo[current] = current.obj
            todo.pop()
            continue
        if current.name == "switch":
            # lazy: index first, then only the chosen branch
            idx_node = current.pos_args[0]
            if idx_node not in memo:
                todo.append(idx_node)
                continue
            idx_val = memo[idx_node]
            if idx_val is GarbageCollected:
                raise RuntimeError("switch index was garbage-collected")
            chosen = current.pos_args[int(idx_val) + 1]
            if chosen not in memo:
                todo.append(chosen)
                continue
            memo[current] = memo[chosen]
            todo.pop()
            continue
        waiting = [n for n in current.inputs() if n not in memo]
        if waiting:
            todo.extend(waiting)
            continue
        args = [memo[a] for a in current.pos_args]
        kwargs = {k: memo[v] for k, v in current.named_args}
        if any(a is GarbageCollected for a in args) or any(
            v is GarbageCollected for v in kwargs.values()
        ):
            raise RuntimeError(
                f"node {current.name} consumed a garbage-collected input "
                "(inactive conditional hyperparameter used outside its branch?)"
            )
        try:
            memo[current] = scope.impl(current.name)(*args, **kwargs)
        except Exception:
            if print_node_on_error:
                print("=" * 60)
                print("rec_eval failed at node:")
                print(current.pprint())
                print("=" * 60)
            raise
        todo.pop()
    if return_memo:
        return memo[node], memo
    return memo[node]


# =====================================================================
# Builtin scope functions: containers, arithmetic, comparisons
# =====================================================================


# NOTE: several scope symbols share names with Python builtins (`dict`,
# `len`, `float`, `int`, `pow`).  They are registered with explicit `name=`
# on private impl functions so this module's own code never loses the
# builtins.

import builtins as _bi


@scope.define_pure
def literal(obj=None):  # placeholder; Literal nodes are handled specially
    return obj


@scope.define_pure
def pos_args(*args):
    return args


def _dict_impl(**kwargs):
    return kwargs


scope.define(_dict_impl, name="dict", pure=True)


@scope.define_pure
def dict_pairs(*pairs):
    return {k: v for k, v in pairs}


@scope.define_pure
def getitem(obj, idx):
    return obj[idx]


@scope.define_pure
def identity(obj):
    return obj


@scope.define_pure
def hyperopt_param(label, obj):
    """A named hyperparameter: evaluates to its wrapped distribution draw.

    The label rides along so the compiler / algorithms can address this node;
    at evaluation time it is the identity on ``obj``.
    """
    return obj


# `switch` is evaluated lazily inside rec_eval; the impl exists only so the
# symbol is defined (e.g. for strict evaluation of already-known branches).
@scope.define_pure
def switch(index, *options):
    return options[_bi.int(index)]


scope.define(lambda obj: _bi.len(obj), name="len", pure=True)
scope.define(lambda obj: _bi.float(obj), name="float", pure=True)
scope.define(lambda obj: _bi.int(obj), name="int", pure=True)
scope.define(lambda a, b: a ** b, name="pow", pure=True)
scope.define(lambda a: _bi.abs(a), name="abs_", pure=True)


@scope.define_pure
def add(a, b):
    return a + b


@scope.define_pure
def sub(a, b):
    return a - b


@scope.define_pure
def mul(a, b):
    return a * b


@scope.define_pure
def truediv(a, b):
    return a / b


@scope.define_pure
def floordiv(a, b):
    return a // b


@scope.define_pure
def neg(a):
    return -a


@scope.define_pure
def exp(a):
    return np.exp(a)


@scope.define_pure
def log(a):
    return np.log(a)


@scope.define_pure
def sqrt(a):
    return np.sqrt(a)


@scope.define_pure
def minimum(a, b):
    return np.minimum(a, b)


@scope.define_pure
def maximum(a, b):
    return np.maximum(a, b)


@scope.define_pure
def eq(a, b):
    return a == b


@scope.define_pure
def gt(a, b):
    return a > b


@scope.define_pure
def lt(a, b):
    return a < b


@scope.define_pure
def ge(a, b):
    return a >= b


@scope.define_pure
def le(a, b):
    return a <= b


@scope.define_pure
def array_union(a, b):
    return np.union1d(a, b)


@scope.define_pure
def asarray(a, dtype=None):
    if dtype is None:
        return np.asarray(a)
    return np.asarray(a, dtype=dtype)


@scope.define_pure
def repeat(n_times, obj):
    return [obj] * n_times


@scope.define
def call_method(obj, methodname, *args, **kwargs):
    return getattr(obj, methodname)(*args, **kwargs)


@scope.define_pure
def call_method_pure(obj, methodname, *args, **kwargs):
    return getattr(obj, methodname)(*args, **kwargs)
