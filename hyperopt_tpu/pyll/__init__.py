"""Expression-graph frontend (the ``pyll`` equivalent).

Mirrors the public surface of ``hyperopt.pyll``: ``scope``, ``Apply``,
``Literal``, ``as_apply``, ``rec_eval``, ``dfs``, ``toposort``, ``clone``,
``clone_merge``, and ``stochastic.sample``.
"""

from . import base, stochastic
from .base import (
    Apply,
    GarbageCollected,
    Literal,
    as_apply,
    clone,
    clone_merge,
    dfs,
    rec_eval,
    scope,
    toposort,
)
from .stochastic import implicit_stochastic_symbols, recursive_set_rng_kwarg, sample

__all__ = [
    "Apply",
    "GarbageCollected",
    "Literal",
    "as_apply",
    "base",
    "clone",
    "clone_merge",
    "dfs",
    "implicit_stochastic_symbols",
    "rec_eval",
    "recursive_set_rng_kwarg",
    "sample",
    "scope",
    "stochastic",
    "toposort",
]
