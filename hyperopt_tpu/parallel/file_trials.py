"""FileTrials: durable filesystem work queue for multi-worker fmin.

Reference parity (SURVEY.md §2 #17): ``hyperopt/mongoexp.py`` —
``MongoJobs`` (jobs collection + **atomic ``reserve`` via
``find_one_and_update`` owner-stamping** ~L160-500), ``MongoTrials(Trials)``
(~L500-750), ``MongoCtrl`` (~L750-800).

TPU-native redesign: TPU pods share a filesystem (NFS/GCS-fuse), not a
MongoDB deployment, so the durable queue is a directory:

    <queue>/trials/<tid>.json     one JSON doc per trial (atomic replace)
    <queue>/locks/<tid>.lock      reservation: O_CREAT|O_EXCL exclusive
                                  create IS the mutual-exclusion primitive
                                  (the find_one_and_update analog)
    <queue>/leases/<tid>.lease    renewable heartbeat lease (JSON: owner,
                                  expiry epoch, attempt) written at
                                  reservation and renewed by the worker;
                                  the driver-side reaper reclaims trials
                                  whose lease expired
    <queue>/attachments/<key>     blob store (GridFS analog) — including
                                  the pickled Domain under
                                  'FMinIter_Domain'
    <queue>/ids.counter           monotonic trial-id allocator (lock-file
                                  protected)

Durability semantics match Mongo: re-run fmin with the same queue dir (and
exp_key) to resume; workers are stateless and restartable at any time.
Recovery goes beyond the reference: a reserved-but-dead worker's job kept
its lock forever there (``owner`` stays set); here its lease expires and
the :class:`hyperopt_tpu.resilience.leases.LeaseReaper` re-queues the
trial automatically (the manual ``requeue_stale`` survives for scripted
cleanup).
"""

from __future__ import annotations

import datetime
import glob
import json
import logging
import os
import pickle
import re
import socket
import sys
import threading
import time
import zlib
from collections.abc import MutableMapping

from .. import native as _native
from .. import tracing
from ..base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    JOB_STATES,
    Ctrl,
    Trials,
)
from ..utils import coarse_utcnow

logger = logging.getLogger(__name__)

_DT_KEY = "$datetime"

# Reservation lease time-to-live.  A worker heartbeats at ttl/3; the
# driver-side reaper reclaims a RUNNING trial once its lease has been
# silent this long.  Must comfortably exceed worst-case heartbeat jitter
# (NFS attribute-cache latency + a descheduled worker thread).
DEFAULT_LEASE_TTL = 30.0


def _active_chaos():
    """The process-wide chaos monkey, at zero import cost when the chaos
    harness was never loaded (a sys.modules miss, not an import)."""
    mod = sys.modules.get("hyperopt_tpu.resilience.chaos")
    return mod.get_active() if mod is not None else None


# Process-wide storage-plane telemetry (observability.StoreStats).  The
# optimization service installs one at startup; standalone fmin/worker
# runs leave it None and every record site is a single global read.
_store_stats = None


def set_store_stats(stats):
    """Install (or with None, remove) the process-wide StoreStats every
    queue operation in this module records into."""
    global _store_stats
    _store_stats = stats


def store_stats():
    """The installed process-wide StoreStats (None when uninstalled)."""
    return _store_stats


def _json_default(o):
    if isinstance(o, datetime.datetime):
        return {_DT_KEY: o.isoformat()}
    if isinstance(o, bytes):
        return {"$bytes": o.hex()}
    if isinstance(o, tuple):
        return list(o)
    raise TypeError(o)


def _json_object_hook(d):
    if _DT_KEY in d and len(d) == 1:
        return datetime.datetime.fromisoformat(d[_DT_KEY])
    if "$bytes" in d and len(d) == 1:
        return bytes.fromhex(d["$bytes"])
    return d


def _atomic_write(path, data: bytes, fsync_kind="doc"):
    tmp = f"{path}.tmp.{os.getpid()}.{time.monotonic_ns()}"
    t0 = time.perf_counter()
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    stats = _store_stats
    if stats is not None:
        stats.record_fsync(
            time.perf_counter() - t0, kind=fsync_kind, nbytes=len(data)
        )
    os.replace(tmp, path)
    return len(data)


# Crash-consistency trailer on every trial doc: `\n#crc32:<crc>:<len>\n`
# appended after the JSON payload.  A torn disk write (power loss, a
# writer SIGKILL'd by the chaos harness mid-write) truncates or garbles
# the payload; the trailer lets `_read_doc` tell "torn" apart from
# "racing an atomic replace" and quarantine the file instead of crashing
# `all_docs`.  A JSON comment-style line after the payload is invisible
# to the native fast scanner (it greps for `"state":` textually) and to
# any legacy doc without one (the trailer is optional on read).
_DOC_TRAILER_RE = re.compile(rb"\n#crc32:([0-9a-f]{8}):(\d+)\n?$")


def _encode_doc(doc) -> bytes:
    payload = json.dumps(doc, default=_json_default, sort_keys=True).encode()
    return payload + b"\n#crc32:%08x:%d\n" % (
        zlib.crc32(payload) & 0xFFFFFFFF, len(payload)
    )


class DocCorrupt(ValueError):
    """A trial doc failed its CRC/length trailer or does not parse."""


def _decode_doc(raw: bytes):
    """Parse one doc blob, verifying the CRC trailer when present.
    Raises :class:`DocCorrupt` for torn/garbled payloads."""
    m = _DOC_TRAILER_RE.search(raw)
    if m is not None:
        length = int(m.group(2))
        payload = raw[:m.start()]
        if len(payload) != length or (
            zlib.crc32(payload) & 0xFFFFFFFF
        ) != int(m.group(1), 16):
            raise DocCorrupt("doc payload fails its length/CRC32 trailer")
    else:
        payload = raw  # legacy doc written before the trailer existed
    try:
        return json.loads(payload.decode(), object_hook=_json_object_hook)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise DocCorrupt(str(e))


def quarantine_path(path) -> str:
    """Destination a corrupt doc is renamed to (never re-globbed as a
    trial doc: the ``.corrupt`` suffix defeats both the ``*.json`` glob
    and the native scanner's name filter)."""
    dest = f"{path}.corrupt"
    if os.path.exists(dest):  # a second tear of the same tid
        dest = f"{path}.corrupt.{time.monotonic_ns()}"
    return dest


def attachment_filename(key) -> str:
    """THE attachment-key → filename sanitization.  Shared with
    resilience.fsck, which must read exactly the files the queue
    writes — a second copy of this mapping could silently diverge."""
    return str(key).replace("/", "_").replace(":", "_")


def _write_doc(path, doc, fsync_kind="doc"):
    return _atomic_write(path, _encode_doc(doc), fsync_kind=fsync_kind)


def _read_doc(path, quarantine=True):
    for _ in range(5):
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            time.sleep(0.01)  # racing an atomic replace; retry
            continue
        try:
            return _decode_doc(raw)
        except DocCorrupt:
            time.sleep(0.01)  # a re-write may be landing; re-read
    if quarantine and os.path.exists(path):
        # persistently corrupt: a torn write, not a race.  Move it aside
        # so all_docs/fsck stop tripping on it; the service journal (or
        # an operator) can restore the doc from its own record.
        dest = quarantine_path(path)
        try:
            os.replace(path, dest)
            logger.warning("quarantined corrupt doc %s -> %s", path, dest)
            stats = _store_stats
            if stats is not None:
                stats.record_quarantine()
        except OSError:
            logger.warning("could not quarantine corrupt doc %s", path)
    return None


def default_backend(root) -> str:
    """Which trial-store backend a queue directory carries.

    - a ``segments/MANIFEST.json`` marker → ``"segment"``;
    - legacy per-doc layout (``trials/*.json`` present, no manifest) →
      ``"doc"`` — old queues keep working untouched;
    - a fresh directory → the ``HYPEROPT_TPU_STORE_BACKEND`` env var if
      set, else ``"segment"`` (the default backend: the per-doc layout
      does one fsync'd atomic replace per transition and an O(N)
      directory scan per refresh; the segmented log group-commits and
      replays O(delta) tails — see ``parallel.segment_store``).
    """
    from . import segment_store

    root = os.path.abspath(root)
    if segment_store.SegmentStore.is_segmented(root):
        return "segment"
    if glob.glob(os.path.join(root, "trials", "*.json")):
        return "doc"
    return os.environ.get("HYPEROPT_TPU_STORE_BACKEND", "segment")


class FileJobs:
    """Low-level queue operations (the MongoJobs analog).

    Two interchangeable trial-doc backends behind one API:

    - ``"segment"`` (default for new queues): the append-only segment
      log of :mod:`hyperopt_tpu.parallel.segment_store` — one
      CRC-framed ``O_APPEND`` group commit per write call, an in-memory
      materialized view served to ``all_docs``/``count_states``/
      ``reserve``, refresh = O(delta) tail replay, ZERO O(N) directory
      scans;
    - ``"doc"`` (legacy, auto-detected): one ``trials/<tid>.json`` per
      trial, atomic replace per write, directory scans on read.

    Locks, leases, attachments, and the id counter are backend-
    independent — the reservation protocol is untouched.
    """

    def __init__(self, root, lease_ttl=DEFAULT_LEASE_TTL, backend=None):
        self.root = os.path.abspath(root)
        self.lease_ttl = float(lease_ttl)
        for sub in ("trials", "locks", "leases", "attachments"):
            os.makedirs(os.path.join(self.root, sub), exist_ok=True)
        self.backend = backend or default_backend(self.root)
        if self.backend not in ("segment", "doc"):
            raise ValueError(f"unknown trial-store backend {self.backend!r}")
        self.segments = None
        if self.backend == "segment":
            from .segment_store import SegmentStore

            self.segments = SegmentStore(self.root)
        # Process-local gate in FRONT of the cross-process counter file
        # lock: threads of one process queue on a cheap mutex instead of
        # contending on the O_CREAT|O_EXCL spin loop (10 ms sleeps).
        # The guarded-by annotation below is enforced statically by
        # hyperopt_tpu.analysis.race_lint.
        self._counter_lock = threading.Lock()
        # High-water mark of ids this process allocated: a counter file
        # that reads BELOW it means the file regressed (NFS rollback,
        # manual truncation, a second queue mounted over the first) and
        # continuing would silently re-issue duplicate trial ids.
        self._last_id = -1  # guarded-by: _counter_lock

    # -- paths ---------------------------------------------------------
    def trial_path(self, tid):
        return os.path.join(self.root, "trials", f"{int(tid):012d}.json")

    def lock_path(self, tid):
        return os.path.join(self.root, "locks", f"{int(tid):012d}.lock")

    def lease_path(self, tid):
        return os.path.join(self.root, "leases", f"{int(tid):012d}.lease")

    def attachment_path(self, key):
        return os.path.join(
            self.root, "attachments", attachment_filename(key)
        )

    # -- id allocation --------------------------------------------------
    def new_trial_ids(self, n):
        counter = os.path.join(self.root, "ids.counter")
        lock = counter + ".lock"
        with self._counter_lock:
            deadline = time.monotonic() + 30.0
            while True:
                try:
                    fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.close(fd)
                    break
                except FileExistsError:
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"id-counter lock stuck: {lock}")
                    time.sleep(0.01)
            try:
                start = 0
                if os.path.exists(counter):
                    with open(counter) as f:
                        start = int(f.read().strip() or 0)
                if start < self._last_id + 1:
                    # a regressed counter would re-issue ids this process
                    # already handed out — refuse before corrupting docs
                    raise RuntimeError(
                        f"id counter {counter} regressed to {start} below "
                        f"already-allocated id {self._last_id} (rolled-back "
                        f"or truncated queue directory?)"
                    )
                # atomic replace, not truncate-then-write: a writer
                # SIGKILL'd between the truncate and the write would
                # leave an EMPTY counter, and the next reader would
                # restart ids at 0 — duplicate tids
                _atomic_write(
                    counter, str(start + n).encode(), fsync_kind="counter"
                )
                self._last_id = start + n - 1
                return list(range(start, start + n))
            finally:
                os.unlink(lock)

    def reset_id_counter(self):
        """Forget the allocation high-water mark (the queue was wiped —
        ``FileTrials.delete_all`` — so restarting ids from 0 is intended,
        not a regression)."""
        with self._counter_lock:
            self._last_id = -1

    # -- docs -----------------------------------------------------------
    def insert(self, doc):
        # tracing.span is a no-op singleton unless the calling thread
        # has a request trace bound (the optimization service's store
        # writes do; driver/worker writes normally don't)
        if self.segments is not None:
            with tracing.span("store.segment_append", tid=int(doc["tid"])):
                self.segments.append(doc)
            chaos = _active_chaos()
            if chaos is not None:
                chaos.maybe_torn_lock(self, doc["tid"])
            return
        with tracing.span("store.write_doc", tid=int(doc["tid"])):
            nbytes = _write_doc(self.trial_path(doc["tid"]), doc)
        stats = _store_stats
        if stats is not None:
            stats.record_doc_write(nbytes)
        chaos = _active_chaos()
        if chaos is not None:
            chaos.maybe_torn_lock(self, doc["tid"])
            chaos.maybe_torn_doc(self.trial_path(doc["tid"]), doc["tid"])

    def insert_many(self, docs):
        """Insert a batch — ONE group-committed segment append (one
        O_APPEND write + one fsync for the whole batch) on the
        segmented backend; a per-doc loop on the legacy one."""
        if not docs:
            return
        if self.segments is not None:
            with tracing.span("store.segment_append", n_docs=len(docs)):
                self.segments.append_many(docs)
            chaos = _active_chaos()
            if chaos is not None:
                chaos.maybe_torn_lock(self, docs[0]["tid"])
            return
        for doc in docs:
            self.insert(doc)

    def write(self, doc):
        if self.segments is not None:
            with tracing.span("store.segment_append", tid=int(doc["tid"])):
                self.segments.append(doc)
            return
        with tracing.span("store.write_doc", tid=int(doc["tid"])):
            nbytes = _write_doc(self.trial_path(doc["tid"]), doc)
        stats = _store_stats
        if stats is not None:
            stats.record_doc_write(nbytes)
        chaos = _active_chaos()
        if chaos is not None:
            chaos.maybe_torn_doc(self.trial_path(doc["tid"]), doc["tid"])

    def read_doc(self, tid):
        """One trial doc by id (None if absent/unreadable)."""
        if self.segments is not None:
            return self.segments.get(tid)
        return _read_doc(self.trial_path(tid))

    def all_docs(self):
        if self.segments is not None:
            # the materialized view: an O(delta) tail replay then an
            # in-memory read — ZERO directory scans on this path
            return self.segments.all_docs()
        docs = []
        paths = sorted(glob.glob(os.path.join(self.root, "trials", "*.json")))
        stats = _store_stats
        if stats is not None:
            # THE O(N) directory scan the segmented-store roadmap item
            # exists to kill — every one is on the record
            stats.record_scan(len(paths))
        for p in paths:
            doc = _read_doc(p)
            if doc is not None:
                docs.append(doc)
        return docs

    def locked_tids(self):
        """Trial ids with a reservation lock file present."""
        out = []
        for p in glob.glob(os.path.join(self.root, "locks", "*.lock")):
            stem = os.path.basename(p)[: -len(".lock")]
            try:
                out.append(int(stem))
            except ValueError:
                continue
        return sorted(out)

    def tmp_droppings(self):
        """`*.tmp.*` files a writer killed between ``open`` and
        ``os.replace`` in ``_atomic_write`` left behind, across every
        queue subdirectory.  Invisible to doc globs (the names end in
        pid/nanosecond digits, not ``.json``) but they accumulate
        forever without a GC."""
        out = []
        for sub in ("trials", "locks", "leases", "attachments", "segments"):
            out.extend(
                glob.glob(os.path.join(self.root, sub, "*.tmp.*"))
            )
        # the id counter's atomic-replace tmp lives at the queue root
        out.extend(glob.glob(os.path.join(self.root, "*.tmp.*")))
        return sorted(out)

    def gc_tmp_droppings(self, max_age_secs=None) -> int:
        """Delete tmp droppings older than ``max_age_secs`` (default:
        the lease TTL — younger ones may be a write in flight)."""
        max_age = (
            self.lease_ttl if max_age_secs is None else float(max_age_secs)
        )
        now = time.time()
        n = 0
        for p in self.tmp_droppings():
            try:
                if now - os.path.getmtime(p) <= max_age:
                    continue
                os.unlink(p)
                n += 1
            except OSError:
                continue  # vanished under us, or unreadable mtime
        return n

    # -- leases ----------------------------------------------------------
    # Reservations are renewable heartbeat leases: ``reserve`` grants one,
    # the worker renews it (hyperopt_tpu.resilience.leases.LeaseHeartbeat)
    # while the objective runs, and the driver-side LeaseReaper reclaims
    # RUNNING trials whose lease went silent past the TTL.  The lease file
    # is advisory state *about* the lock, never the mutual-exclusion
    # primitive itself — the O_CREAT|O_EXCL lock file keeps that role.
    def grant_lease(self, tid, owner, ttl=None, attempt=1):
        ttl = self.lease_ttl if ttl is None else float(ttl)
        now = time.time()
        _write_doc(
            self.lease_path(tid),
            {
                "owner": owner,
                "granted_at": now,
                "expires_at": now + ttl,
                "attempt": int(attempt),
            },
            fsync_kind="lease",
        )
        stats = _store_stats
        if stats is not None:
            stats.record_lease("grant")

    def read_lease(self, tid):
        """The lease doc for ``tid`` (None if absent or torn)."""
        try:
            with open(self.lease_path(tid), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        try:
            return _decode_doc(raw)
        except DocCorrupt:
            return None  # torn write: the reaper treats it as expired

    def renew_lease(self, tid, owner, ttl=None):
        """Extend ``tid``'s lease iff ``owner`` still holds it; False
        means the lease was reclaimed (or never granted) and the caller
        must drop its in-flight result."""
        lease = self.read_lease(tid)
        if lease is None or lease.get("owner") != owner:
            return False
        ttl = self.lease_ttl if ttl is None else float(ttl)
        lease["expires_at"] = time.time() + ttl
        _write_doc(self.lease_path(tid), lease, fsync_kind="lease")
        stats = _store_stats
        if stats is not None:
            stats.record_lease("renew")
        return True

    def lease_owner(self, tid):
        lease = self.read_lease(tid)
        return lease.get("owner") if lease is not None else None

    def clear_lease(self, tid):
        try:
            os.unlink(self.lease_path(tid))
        except FileNotFoundError:
            return
        stats = _store_stats
        if stats is not None:
            stats.record_lease("clear")

    # -- fast queue scan (native C++ with Python fallback) ---------------
    def count_states(self):
        """{state: count} over all docs — the poll-loop primitive.

        Uses the native scanner (``native/fastqueue.cpp``) when built; a
        parse mismatch or missing toolchain falls back to exact parsing.
        On the segmented backend the materialized view answers in O(1)
        after an O(delta) tail refresh — no directory scan at all.
        """
        if self.segments is not None:
            counts = self.segments.count_states()
            return {s: counts.get(s, 0) for s in JOB_STATES}
        res = _native.count_states(os.path.join(self.root, "trials"))
        if res is not None:
            counts, _ = res
            stats = _store_stats
            if stats is not None:
                # the native scan still reads every directory entry —
                # it is FASTER, not O(1); the scan counter says so
                stats.record_scan(sum(counts.values()))
            return {s: counts[s] for s in JOB_STATES}
        counts = {s: 0 for s in JOB_STATES}
        for doc in self.all_docs():
            counts[doc["state"]] = counts.get(doc["state"], 0) + 1
        return counts

    def _new_tids(self):
        if self.segments is not None:
            return self.segments.tids_in_state(JOB_STATE_NEW)
        tids = _native.list_state(
            os.path.join(self.root, "trials"), JOB_STATE_NEW
        )
        if tids is not None:
            stats = _store_stats
            if stats is not None:
                stats.record_scan(len(tids))
            return tids
        return [
            doc["tid"] for doc in self.all_docs() if doc["state"] == JOB_STATE_NEW
        ]

    def running_tids(self):
        """Trial ids currently in JOB_STATE_RUNNING — the lease reaper's
        scan primitive (native fast path; the reaper polls every few
        seconds and must not re-parse the whole queue each time)."""
        if self.segments is not None:
            return self.segments.tids_in_state(JOB_STATE_RUNNING)
        tids = _native.list_state(
            os.path.join(self.root, "trials"), JOB_STATE_RUNNING
        )
        if tids is not None:
            stats = _store_stats
            if stats is not None:
                stats.record_scan(len(tids))
            return tids
        return [
            doc["tid"]
            for doc in self.all_docs()
            if doc["state"] == JOB_STATE_RUNNING
        ]

    @staticmethod
    def _unlock_if_owner(lock, owner):
        """Atomic rename-then-verify unlock.

        A read-then-unlink unlock has a TOCTOU hole: between our owner
        check and our unlink, ``requeue_stale`` can unlink the lock and
        another worker recreate it — our unlink then destroys THEIR
        reservation.  Instead the lock is renamed aside to a unique name
        first (rename(2) is atomic: exactly one process possesses the
        inode afterwards), the owner is verified on the private copy, and
        a lock that turns out not to be ours is restored with link(2)
        (create-iff-absent, so a newer lock at the path is never
        clobbered)."""
        # read-only gate first: a lock that is visibly not ours is never
        # touched (same as the pre-fix behavior — no displacement risk)
        try:
            with open(lock) as f:
                if f.read() != owner:
                    return False
        except FileNotFoundError:
            return False
        # it read as ours: take atomic possession, then RE-verify — this
        # closes the read→unlink window (requeue_stale can unlink our
        # lock and another worker recreate it in between; a plain unlink
        # here would destroy THEIR reservation)
        tmp = f"{lock}.unlock.{os.getpid()}.{time.monotonic_ns()}"
        try:
            os.rename(lock, tmp)
        except FileNotFoundError:
            return False
        with open(tmp) as f:
            mine = f.read() == owner
        if mine:
            os.unlink(tmp)
            return True
        # double race: the lock changed hands between read and rename —
        # restore it with link(2) (create-iff-absent never clobbers a
        # third party's even-newer lock; in that triple-race case their
        # claim stands and the displaced one is dropped with a warning)
        try:
            os.link(tmp, lock)
        except FileExistsError:
            logger.warning(
                "unlock race on %s: displaced a non-owner lock that could "
                "not be restored (a newer lock exists)", lock,
            )
        os.unlink(tmp)
        return False

    def _try_lock(self, lock, owner):
        r = _native.try_lock(lock, owner)
        if r is not None:
            return bool(r)
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            f.write(owner)
        return True

    # -- reservation -----------------------------------------------------
    def reserve(self, owner):
        """Atomically claim one JOB_STATE_NEW trial; None if none available.

        Exclusive lock-file creation is the only synchronization primitive,
        exactly as Mongo's atomic owner-stamping is the reference's.  The
        candidate scan and the lock syscall go through the native fast
        path when available; the doc rewrite stays in Python (the lock
        holder owns the doc).
        """
        for tid in self._new_tids():
            if not self._try_lock(self.lock_path(tid), owner):
                continue  # someone else owns it
            doc = self.read_doc(tid)  # re-read under the lock
            if doc is None or doc["state"] != JOB_STATE_NEW:
                # Lost a race (e.g. grabbed the lock inside requeue_stale's
                # unlink->rewrite window while the doc still reads RUNNING).
                # Release the lock we just created, or the trial would sit
                # NEW-but-locked forever once the rewrite lands — but only
                # if the lock file still carries OUR owner string: requeue
                # may already have unlinked it and another worker recreated
                # it, and deleting theirs would re-open the double-claim.
                self._unlock_if_owner(self.lock_path(tid), owner)
                continue
            # lease before doc rewrite: the lease must cover the window in
            # which the doc still reads NEW, or a crash here would strand
            # a locked trial with nothing for the reaper to expire
            attempt = int(doc.get("misc", {}).get("attempts", 0)) + 1
            self.grant_lease(tid, owner, attempt=attempt)
            doc["state"] = JOB_STATE_RUNNING
            doc["owner"] = owner
            doc.setdefault("misc", {})["attempts"] = attempt
            doc["book_time"] = coarse_utcnow()
            doc["refresh_time"] = coarse_utcnow()
            self.write(doc)
            return doc
        return None

    def requeue_stale(self, max_age_secs):
        """Re-queue RUNNING trials whose reservation is older than
        ``max_age_secs`` (recovery beyond the reference's capability —
        Mongo leaves dead workers' jobs reserved forever).  Also GCs the
        ``*.tmp.*`` droppings a writer killed mid-``_atomic_write``
        leaves behind — scripted cleanup must not strand them."""
        n = 0
        now = coarse_utcnow()
        for doc in self.all_docs():
            if doc["state"] != JOB_STATE_RUNNING:
                continue
            booked = doc.get("book_time")
            if booked is None or (now - booked).total_seconds() > max_age_secs:
                self.clear_lease(doc["tid"])
                try:
                    os.unlink(self.lock_path(doc["tid"]))
                except FileNotFoundError:
                    pass
                doc["state"] = JOB_STATE_NEW
                doc["owner"] = None
                doc["book_time"] = None
                self.write(doc)
                n += 1
        self.gc_tmp_droppings(max_age_secs)
        return n

    # -- attachments -----------------------------------------------------
    def set_attachment(self, key, value: bytes):
        _atomic_write(
            self.attachment_path(key), value, fsync_kind="attachment"
        )
        stats = _store_stats
        if stats is not None:
            stats.record_attachment_write(len(value))

    def get_attachment(self, key) -> bytes:
        with open(self.attachment_path(key), "rb") as f:
            return f.read()

    def has_attachment(self, key):
        return os.path.exists(self.attachment_path(key))

    def del_attachment(self, key):
        os.unlink(self.attachment_path(key))

    def attachment_keys(self):
        d = os.path.join(self.root, "attachments")
        return sorted(os.listdir(d))


class _FileAttachments(MutableMapping):
    def __init__(self, jobs: FileJobs):
        self._jobs = jobs

    def __getitem__(self, key):
        try:
            return self._jobs.get_attachment(key)
        except FileNotFoundError:
            raise KeyError(key)

    def __setitem__(self, key, value):
        if not isinstance(value, bytes):
            value = pickle.dumps(value)
        self._jobs.set_attachment(key, value)

    def __delitem__(self, key):
        try:
            self._jobs.del_attachment(key)
        except FileNotFoundError:
            raise KeyError(key)

    def __iter__(self):
        return iter(self._jobs.attachment_keys())

    def __len__(self):
        return len(self._jobs.attachment_keys())


class FileTrials(Trials):
    """Durable multi-process Trials store over a shared directory."""

    asynchronous = True
    poll_interval_secs = 0.25

    def __init__(self, queue_dir, exp_key=None, refresh=True,
                 lease_ttl=DEFAULT_LEASE_TTL, backend=None):
        self.jobs = FileJobs(queue_dir, lease_ttl=lease_ttl, backend=backend)
        self._seg_cursor = None  # SegmentStore.docs_since consumer cursor
        self._tid_pos = None     # tid -> index into _dynamic_trials
        super().__init__(exp_key=exp_key, refresh=False)
        self.attachments = _FileAttachments(self.jobs)
        if refresh:
            self.refresh()

    def refresh(self):
        stats = _store_stats
        if stats is not None:
            stats.record_refresh(local=False)
        segs = self.jobs.segments
        if segs is None:
            self._dynamic_trials = self.jobs.all_docs()
        else:
            # O(delta) refresh: only docs appended (anywhere — this
            # process or another) since our cursor, NOT an O(N) rebuild
            self._seg_cursor, delta = segs.docs_since(self._seg_cursor)
            if self._tid_pos is None:
                self._tid_pos = {
                    d["tid"]: i for i, d in enumerate(self._dynamic_trials)
                }
            for doc in delta:
                self._apply_dynamic_doc(doc)
        super().refresh()

    def _apply_dynamic_doc(self, doc):
        """Fold one delta doc into ``_dynamic_trials`` latest-wins."""
        pos = self._tid_pos.get(doc["tid"])
        if pos is None:
            self._tid_pos[doc["tid"]] = len(self._dynamic_trials)
            self._dynamic_trials.append(doc)
        else:
            self._dynamic_trials[pos] = doc

    def refresh_local(self):
        """Recompute the derived views (``_trials``, the SoA history)
        from the IN-MEMORY docs without re-reading the queue directory.

        For a single-writer owner — the optimization service, which
        inserts and mutates every doc itself and write-throughs each
        change via ``jobs.write`` — the in-memory docs are authoritative
        and the O(N)-file disk scan of :meth:`refresh` per report would
        dominate the serving hot path.  Multi-writer users (fmin driver
        + out-of-process workers) must keep calling :meth:`refresh`,
        which is the only way to observe other processes' writes."""
        stats = _store_stats
        if stats is not None:
            stats.record_refresh(local=True)
        super().refresh()

    def _insert_trial_docs(self, docs):
        docs = list(docs)
        # ONE group-committed segment append for the whole batch (the
        # legacy backend falls back to a per-doc loop inside insert_many)
        self.jobs.insert_many(docs)
        if self._tid_pos is not None:
            for doc in docs:
                self._apply_dynamic_doc(doc)
        else:
            self._dynamic_trials.extend(docs)
        return [doc["tid"] for doc in docs]

    def new_trial_ids(self, n):
        ids = self.jobs.new_trial_ids(n)
        self._ids.update(ids)
        return ids

    def delete_all(self):
        if self.jobs.segments is not None:
            self.jobs.segments.delete_all()
        self._seg_cursor = None
        self._tid_pos = None
        for p in glob.glob(os.path.join(self.jobs.root, "trials", "*.json")):
            os.unlink(p)
        for p in glob.glob(
            os.path.join(self.jobs.root, "trials", "*.corrupt*")
        ):
            os.unlink(p)
        for p in self.jobs.tmp_droppings():
            os.unlink(p)
        for p in glob.glob(os.path.join(self.jobs.root, "locks", "*.lock")):
            os.unlink(p)
        for p in glob.glob(os.path.join(self.jobs.root, "leases", "*.lease")):
            os.unlink(p)
        for k in list(self.attachments):
            del self.attachments[k]
        counter = os.path.join(self.jobs.root, "ids.counter")
        if os.path.exists(counter):
            os.unlink(counter)
        self.jobs.reset_id_counter()
        self._dynamic_trials = []
        from ..base import _TrialsHistory

        self._history = _TrialsHistory()
        self.refresh()

    def count_by_state_unsynced(self, arg):
        if self._exp_key is None:
            # poll fast path: native state counting, no doc materialization
            counts = self.jobs.count_states()
            if arg in JOB_STATES:
                return counts.get(arg, 0)
            if hasattr(arg, "__iter__"):
                return sum(counts.get(s, 0) for s in arg)
        self.refresh()
        return super().count_by_state_unsynced(arg)


class FileCtrl(Ctrl):
    """Ctrl whose checkpoint persists partial results to the queue
    (the MongoCtrl analog)."""

    def __init__(self, trials: FileTrials, current_trial):
        super().__init__(trials, current_trial)

    def checkpoint(self, result=None):
        if result is not None:
            self.current_trial["result"] = result
        self.current_trial["refresh_time"] = coarse_utcnow()
        self.trials.jobs.write(self.current_trial)


def default_owner():
    return f"{socket.gethostname()}:{os.getpid()}"
