"""Worker daemon for the FileTrials queue.

Reference parity (SURVEY.md §2 #17): ``hyperopt/mongoexp.py`` —
``MongoWorker.run_one`` (reserve → temp workdir → unpickle domain from the
'FMinIter_Domain' attachment → ``domain.evaluate`` → write result,
error → ``JOB_STATE_ERROR``) (~L800-1050) and the
``hyperopt-mongo-worker`` CLI (``main_worker_helper``: ``--poll-interval``,
``--max-consecutive-failures``, ``--reserve-timeout``, ``--workdir``,
``--last-job-timeout``) (~L1050-1300).

Run one worker per host/slice::

    python -m hyperopt_tpu.parallel.worker --queue /shared/q --workdir /tmp/w

Workers are stateless: kill and restart at any time; elasticity falls out
of the shared queue (SURVEY.md §5).
"""

from __future__ import annotations

import argparse
import logging
import os
import pickle
import sys
import time
from timeit import default_timer as timer

from ..base import JOB_STATE_DONE, JOB_STATE_ERROR, spec_from_misc
from ..utils import coarse_utcnow, temp_dir, working_dir
from .file_trials import FileCtrl, FileTrials, default_owner

logger = logging.getLogger(__name__)


class ReserveTimeout(Exception):
    """No job became available within --reserve-timeout."""


class FileWorker:
    poll_interval = 1.0

    def __init__(
        self,
        queue_dir,
        poll_interval=1.0,
        workdir=None,
        exp_key=None,
        logfilename=None,
    ):
        self.trials = FileTrials(queue_dir, exp_key=exp_key)
        self.poll_interval = poll_interval
        self.workdir = workdir
        self.owner = default_owner()
        self._domain = None
        self._domain_blob = None

    def _load_domain(self):
        blob = self.trials.attachments["FMinIter_Domain"]
        if blob != self._domain_blob:
            self._domain = pickle.loads(blob)
            self._domain_blob = blob
        return self._domain

    def run_one(self, host_id=None, reserve_timeout=None, erase_created_workdir=False):
        """Reserve and execute one trial; raises ReserveTimeout if none."""
        start = timer()
        job = None
        while job is None:
            job = self.trials.jobs.reserve(host_id or self.owner)
            if job is None:
                if reserve_timeout is not None and timer() - start > reserve_timeout:
                    raise ReserveTimeout(
                        f"no job within {reserve_timeout}s at {self.trials.jobs.root}"
                    )
                time.sleep(self.poll_interval)

        logger.info("worker %s reserved trial %s", self.owner, job["tid"])
        spec = spec_from_misc(job["misc"])
        ctrl = FileCtrl(self.trials, job)
        try:
            domain = self._load_domain()
            workdir = self.workdir or os.path.join(
                self.trials.jobs.root, "workdir", str(job["tid"])
            )
            with temp_dir(workdir, erase_after=erase_created_workdir), working_dir(
                workdir
            ):
                result = domain.evaluate(spec, ctrl)
        except Exception as e:
            logger.error("trial %s failed: %s", job["tid"], e)
            job["state"] = JOB_STATE_ERROR
            job["misc"]["error"] = (str(type(e)), str(e))
            job["refresh_time"] = coarse_utcnow()
            self.trials.jobs.write(job)
            raise
        job["result"] = result
        job["state"] = JOB_STATE_DONE
        job["refresh_time"] = coarse_utcnow()
        self.trials.jobs.write(job)
        return job


def main_worker_helper(options):
    if options.max_consecutive_failures <= 0:
        raise ValueError("--max-consecutive-failures must be positive")
    worker = FileWorker(
        options.queue,
        poll_interval=options.poll_interval,
        workdir=options.workdir,
        exp_key=options.exp_key,
    )
    consecutive_failures = 0
    n_done = 0
    start = timer()
    while True:
        if options.last_job_timeout is not None and (
            timer() - start > options.last_job_timeout
        ):
            logger.info("--last-job-timeout reached, exiting")
            break
        try:
            worker.run_one(reserve_timeout=options.reserve_timeout)
            consecutive_failures = 0
            n_done += 1
        except ReserveTimeout:
            logger.info("reserve timeout, exiting after %d jobs", n_done)
            break
        except Exception as e:
            consecutive_failures += 1
            logger.error(
                "job failure %d/%d: %s",
                consecutive_failures,
                options.max_consecutive_failures,
                e,
            )
            if consecutive_failures >= options.max_consecutive_failures:
                logger.error("too many consecutive failures, exiting")
                return 1
        if options.max_jobs is not None and n_done >= options.max_jobs:
            break
    return 0


def make_parser():
    p = argparse.ArgumentParser(
        prog="hyperopt-tpu-worker",
        description="Execute trials from a FileTrials queue directory.",
    )
    p.add_argument("--queue", required=True, help="shared queue directory")
    p.add_argument("--exp-key", default=None, dest="exp_key")
    p.add_argument("--poll-interval", type=float, default=1.0, dest="poll_interval")
    p.add_argument(
        "--max-consecutive-failures",
        type=int,
        default=4,
        dest="max_consecutive_failures",
    )
    p.add_argument(
        "--reserve-timeout", type=float, default=120.0, dest="reserve_timeout"
    )
    p.add_argument("--workdir", default=None)
    p.add_argument(
        "--last-job-timeout", type=float, default=None, dest="last_job_timeout"
    )
    p.add_argument("--max-jobs", type=int, default=None, dest="max_jobs")
    return p


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    options = make_parser().parse_args(argv)
    return main_worker_helper(options)


if __name__ == "__main__":
    sys.exit(main())
