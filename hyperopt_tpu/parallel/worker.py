"""Worker daemon for the FileTrials queue.

Reference parity (SURVEY.md §2 #17): ``hyperopt/mongoexp.py`` —
``MongoWorker.run_one`` (reserve → temp workdir → unpickle domain from the
'FMinIter_Domain' attachment → ``domain.evaluate`` → write result,
error → ``JOB_STATE_ERROR``) (~L800-1050) and the
``hyperopt-mongo-worker`` CLI (``main_worker_helper``: ``--poll-interval``,
``--max-consecutive-failures``, ``--reserve-timeout``, ``--workdir``,
``--last-job-timeout``) (~L1050-1300).

Fault tolerance beyond the reference (:mod:`hyperopt_tpu.resilience`):

- every reservation is a renewable **heartbeat lease** — a
  :class:`~hyperopt_tpu.resilience.leases.LeaseHeartbeat` daemon renews
  it at poll-interval cadence while the objective runs, so the
  driver-side reaper can tell a slow worker from a dead one;
- the final result write re-verifies lease ownership and **drops stale
  results** (the trial was reclaimed and re-queued while this worker
  evaluated — writing would clobber the retry);
- objective exceptions are retried **in place** with the run's
  :class:`~hyperopt_tpu.resilience.retry.RetryPolicy` (read from the
  ``FMinIter_RetryPolicy`` queue attachment, overridable per worker),
  with exponential backoff, deterministic jitter, and a per-attempt
  watchdog timeout; a trial that exhausts ``max_attempts`` is
  quarantined in ``JOB_STATE_ERROR``;
- ``--last-job-timeout`` is enforced *inside* the reserve wait too (the
  deadline caps the poll loop, so a worker cannot overshoot it by a full
  ``--reserve-timeout``), and ``--max-consecutive-failures`` ends the
  daemon with a nonzero exit as documented.

Run one worker per host/slice::

    python -m hyperopt_tpu.parallel.worker --queue /shared/q --workdir /tmp/w

Workers are stateless: kill and restart at any time; elasticity falls out
of the shared queue (SURVEY.md §5), and killed workers' trials are
re-queued automatically by the driver's lease reaper.
"""

from __future__ import annotations

import argparse
import logging
import os
import pickle
import sys
import time
from timeit import default_timer as timer

from ..base import JOB_STATE_DONE, JOB_STATE_ERROR, spec_from_misc
from ..observability import FaultStats
from ..utils import coarse_utcnow, temp_dir, working_dir
from .file_trials import (
    DEFAULT_LEASE_TTL,
    FileCtrl,
    FileTrials,
    _active_chaos,
    default_owner,
)

logger = logging.getLogger(__name__)

RETRY_POLICY_ATTACHMENT = "FMinIter_RetryPolicy"


class ReserveTimeout(Exception):
    """No job became available within --reserve-timeout."""


class FileWorker:
    poll_interval = 1.0

    def __init__(
        self,
        queue_dir,
        poll_interval=1.0,
        workdir=None,
        exp_key=None,
        logfilename=None,
        lease_ttl=None,
        retry_policy="attachment",
        stats=None,
    ):
        # lease_ttl None = defer to the driver's published retry policy
        # (FMinIter_RetryPolicy attachment), falling back to the queue
        # default; an explicit value (the --lease-ttl flag) always wins
        self._explicit_lease_ttl = lease_ttl is not None
        self.trials = FileTrials(
            queue_dir, exp_key=exp_key,
            lease_ttl=lease_ttl if lease_ttl is not None else DEFAULT_LEASE_TTL,
        )
        self.poll_interval = poll_interval
        self.workdir = workdir
        self.owner = default_owner()
        self.stats = stats if stats is not None else FaultStats()
        self._domain = None
        self._domain_blob = None
        # "attachment": read the driver's policy from the queue (re-read
        # each trial, parsed only when the blob changes — a long-lived
        # worker spanning several driver runs follows the CURRENT run's
        # policy); None: never retry in place (pre-resilience behavior);
        # a RetryPolicy: explicit per-worker override.
        self._retry_policy_arg = retry_policy
        self._retry_policy_cache = None
        self._retry_policy_blob = None

    def _load_domain(self):
        blob = self.trials.attachments["FMinIter_Domain"]
        if blob != self._domain_blob:
            self._domain = pickle.loads(blob)
            self._domain_blob = blob
        return self._domain

    def _retry_policy(self):
        if self._retry_policy_arg != "attachment":
            return self._retry_policy_arg
        try:
            blob = self.trials.attachments[RETRY_POLICY_ATTACHMENT]
        except KeyError:
            blob = None
        if blob != self._retry_policy_blob:
            self._retry_policy_blob = blob
            if blob is None:
                self._retry_policy_cache = None
            else:
                from ..resilience.retry import RetryPolicy

                try:
                    self._retry_policy_cache = RetryPolicy.from_json(blob)
                except Exception:
                    logger.exception(
                        "unreadable %s attachment; running without "
                        "in-place retries", RETRY_POLICY_ATTACHMENT,
                    )
                    self._retry_policy_cache = None
            if (
                self._retry_policy_cache is not None
                and not self._explicit_lease_ttl
            ):
                # adopt the driver's lease TTL so the heartbeat cadence,
                # the granted leases, and the reaper's clock all agree
                self.trials.jobs.lease_ttl = self._retry_policy_cache.lease_ttl
        return self._retry_policy_cache

    def _finish(self, job, heartbeat, owner):
        """Ownership-checked terminal write: land the doc and release the
        reservation, or drop a result whose lease was reclaimed while the
        objective ran (the trial is already re-queued — writing over it
        would clobber the retry).  Returns True iff the doc was written.

        Three stale signals are checked, narrowing the inherent TOCTOU
        window of a filesystem queue (no compare-and-swap) to the
        read→write gap: the heartbeat noticed the loss, the lease is no
        longer ours *or has already expired* (a stalled-but-alive worker
        whose heartbeat thread also stalled must not trust a lease the
        reaper is entitled to reclaim), or the doc itself was re-owned."""
        jobs = self.trials.jobs
        tid = job["tid"]
        lease = jobs.read_lease(tid)
        stale = (
            heartbeat.lost
            or lease is None
            or lease.get("owner") != owner
            or float(lease.get("expires_at", 0)) <= time.time()
        )
        if not stale:
            # the lease read can race the reaper: re-verify the doc is
            # still stamped with our ownership (a reclaim clears it, a
            # re-reservation re-stamps another worker's)
            current = jobs.read_doc(tid)
            stale = current is not None and current.get("owner") != owner
        if stale:
            self.stats.record("stale_result_dropped")
            logger.warning(
                "trial %s: lease reclaimed or expired while evaluating; "
                "dropping this worker's result", tid,
            )
            return False
        jobs.write(job)
        # stop the heartbeat BEFORE releasing: a renewal racing the
        # clear (read-lease before the unlink, write after it) would
        # re-create the lease file and strand it for the reaper
        heartbeat.stop()
        jobs.clear_lease(tid)
        jobs._unlock_if_owner(jobs.lock_path(tid), owner)
        return True

    def run_one(self, host_id=None, reserve_timeout=None,
                erase_created_workdir=False, deadline=None,
                stop_event=None):
        """Reserve and execute one trial; raises ReserveTimeout if none.

        ``deadline``: absolute ``timer()`` value past which the reserve
        wait gives up (the CLI's --last-job-timeout enforcement).
        ``stop_event``: a ``threading.Event`` that aborts the reserve
        wait when set (the CLI's graceful-shutdown path: a SIGTERM mid
        -poll must not strand the worker for a full --reserve-timeout).
        Once a trial IS reserved the event is ignored — the in-flight
        trial runs to completion and releases its lock+lease normally."""
        from ..resilience.leases import LeaseHeartbeat
        from ..resilience.retry import execute_with_retry

        start = timer()
        owner = host_id or self.owner
        job = None
        while job is None:
            if stop_event is not None and stop_event.is_set():
                raise ReserveTimeout("shutdown requested during reserve wait")
            job = self.trials.jobs.reserve(owner)
            if job is None:
                now = timer()
                if reserve_timeout is not None and now - start > reserve_timeout:
                    raise ReserveTimeout(
                        f"no job within {reserve_timeout}s at {self.trials.jobs.root}"
                    )
                if deadline is not None and now > deadline:
                    raise ReserveTimeout(
                        f"--last-job-timeout deadline reached at "
                        f"{self.trials.jobs.root}"
                    )
                time.sleep(self.poll_interval)

        tid = job["tid"]
        logger.info("worker %s reserved trial %s (attempt %s)",
                    owner, tid, job["misc"].get("attempts", 1))
        spec = spec_from_misc(job["misc"])
        ctrl = FileCtrl(self.trials, job)
        policy = self._retry_policy()
        chaos = _active_chaos()
        ttl = self.trials.jobs.lease_ttl
        heartbeat = LeaseHeartbeat(
            self.trials.jobs, tid, owner, ttl=ttl,
            interval=min(self.poll_interval, ttl / 3.0),
            stats=self.stats,
        ).start()
        try:
            # chaos kill points sit OUTSIDE the error-writing try below:
            # a killed worker must leave the doc RUNNING and the lock in
            # place, exactly like a SIGKILL'd process — recovery is the
            # reaper's job, not this (dead) worker's
            if chaos is not None:
                chaos.maybe_kill_worker(tid, "pre")

            try:
                domain = self._load_domain()
                workdir = self.workdir or os.path.join(
                    self.trials.jobs.root, "workdir", str(tid)
                )

                def _evaluate():
                    return domain.evaluate(spec, ctrl)

                # the workdir chdir wraps the WHOLE retry loop on this
                # thread, not the per-attempt watchdog thread: an
                # abandoned (timed-out) attempt must never chdir the
                # process out from under a live retry, and the temp-dir
                # cleanup must never delete the directory a later
                # attempt is executing in
                with temp_dir(workdir, erase_after=erase_created_workdir), \
                        working_dir(workdir):
                    if policy is None:
                        result = _evaluate()
                    else:
                        def _on_retry(attempt, err):
                            # checkpoint the attempt counter so a crash
                            # mid-backoff doesn't reset the budget, and
                            # keep the lease warm through the sleep
                            job["misc"]["attempts"] = attempt + 1
                            job["refresh_time"] = coarse_utcnow()
                            self.trials.jobs.write(job)
                            heartbeat.renew_now()

                        result, attempts = execute_with_retry(
                            _evaluate,
                            policy,
                            key=tid,
                            stats=self.stats,
                            first_attempt=int(job["misc"].get("attempts", 1)),
                            on_retry=_on_retry,
                        )
                        job["misc"]["attempts"] = attempts
            except Exception as e:
                logger.error("trial %s failed: %s", tid, e)
                job["state"] = JOB_STATE_ERROR
                job["misc"]["error"] = (str(type(e)), str(e))
                job["refresh_time"] = coarse_utcnow()
                self._finish(job, heartbeat, owner)
                raise
            if chaos is not None:
                chaos.maybe_kill_worker(tid, "post")
                if chaos.should_delay_result(tid):
                    # model a frozen worker process: the heartbeat
                    # stalls WITH the result write, so past the TTL the
                    # reaper reclaims the trial and _finish drops this
                    # (now stale) result
                    heartbeat.stop()
                    logger.info(
                        "chaos: stalling worker %.2fs before the result "
                        "write of trial %s",
                        chaos.config.delay_seconds, tid,
                    )
                    time.sleep(chaos.config.delay_seconds)
            job["result"] = result
            job["state"] = JOB_STATE_DONE
            job["refresh_time"] = coarse_utcnow()
            wrote = self._finish(job, heartbeat, owner)
            if wrote and chaos is not None and chaos.should_duplicate_result(tid):
                # at-least-once delivery: the doc write is idempotent
                self.trials.jobs.write(job)
            return job
        finally:
            heartbeat.stop()


def _install_graceful_shutdown():
    """SIGTERM/SIGINT → a threading.Event instead of abrupt death.

    The default dispositions strand state: SIGTERM kills the process
    mid-objective (the doc stays RUNNING and the lock+lease sit until
    the reaper expires them), SIGINT raises KeyboardInterrupt at an
    arbitrary bytecode.  With the handlers installed the worker finishes
    the in-flight trial (the terminal write releases lock+lease as
    usual), skips reserving another, and exits 0.  Returns the event,
    or None when handlers cannot be installed (not the main thread —
    in-process test workers keep their current behavior)."""
    import signal
    import threading

    stop_event = threading.Event()

    def _handler(signum, frame):
        if stop_event.is_set():
            # second signal: the operator means it (the in-flight
            # objective may be hung and nothing else would ever
            # interrupt it) — restore the default disposition and
            # re-deliver for the conventional hard exit; the reaper
            # reclaims the stranded lease
            logger.warning("second signal %d: exiting immediately", signum)
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        logger.info(
            "signal %d: finishing the in-flight trial, then exiting",
            signum,
        )
        stop_event.set()

    try:
        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)
    except ValueError:
        return None
    return stop_event


def main_worker_helper(options):
    if options.max_consecutive_failures <= 0:
        raise ValueError("--max-consecutive-failures must be positive")
    from ..resilience.chaos import WorkerKilled

    worker = FileWorker(
        options.queue,
        poll_interval=options.poll_interval,
        workdir=options.workdir,
        exp_key=options.exp_key,
        lease_ttl=options.lease_ttl,
    )
    stop_event = _install_graceful_shutdown()
    consecutive_failures = 0
    n_done = 0
    start = timer()
    # reference semantics: --last-job-timeout is an absolute deadline
    # (seconds since worker start) past which no new job is reserved —
    # enforced both here and inside run_one's reserve wait, so the
    # worker cannot overshoot it by a full --reserve-timeout
    deadline = (
        start + options.last_job_timeout
        if options.last_job_timeout is not None
        else None
    )
    while True:
        if stop_event is not None and stop_event.is_set():
            logger.info("shutdown requested, exiting cleanly after %d jobs",
                        n_done)
            break
        if deadline is not None and timer() > deadline:
            logger.info("--last-job-timeout reached, exiting")
            break
        try:
            worker.run_one(
                reserve_timeout=options.reserve_timeout, deadline=deadline,
                stop_event=stop_event,
            )
            consecutive_failures = 0
            n_done += 1
        except ReserveTimeout:
            logger.info("reserve timeout, exiting after %d jobs", n_done)
            break
        except WorkerKilled:
            logger.error("worker killed (chaos injection), exiting")
            return 1
        except Exception as e:
            consecutive_failures += 1
            logger.error(
                "job failure %d/%d: %s",
                consecutive_failures,
                options.max_consecutive_failures,
                e,
            )
            if consecutive_failures >= options.max_consecutive_failures:
                logger.error("too many consecutive failures, exiting")
                return 1
        if options.max_jobs is not None and n_done >= options.max_jobs:
            break
    return 0


def make_parser():
    p = argparse.ArgumentParser(
        prog="hyperopt-tpu-worker",
        description="Execute trials from a FileTrials queue directory.",
    )
    p.add_argument("--queue", required=True, help="shared queue directory")
    p.add_argument("--exp-key", default=None, dest="exp_key")
    p.add_argument("--poll-interval", type=float, default=1.0, dest="poll_interval")
    p.add_argument(
        "--max-consecutive-failures",
        type=int,
        default=4,
        dest="max_consecutive_failures",
    )
    p.add_argument(
        "--reserve-timeout", type=float, default=120.0, dest="reserve_timeout"
    )
    p.add_argument("--workdir", default=None)
    p.add_argument(
        "--last-job-timeout", type=float, default=None, dest="last_job_timeout"
    )
    p.add_argument("--max-jobs", type=int, default=None, dest="max_jobs")
    p.add_argument(
        "--lease-ttl",
        type=float,
        default=None,
        dest="lease_ttl",
        help="heartbeat lease time-to-live in seconds; the driver reaper "
        "re-queues this worker's trial if the lease goes silent this long "
        f"(default: the driver's published retry policy, else "
        f"{DEFAULT_LEASE_TTL})",
    )
    return p


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    options = make_parser().parse_args(argv)
    return main_worker_helper(options)


if __name__ == "__main__":
    sys.exit(main())
