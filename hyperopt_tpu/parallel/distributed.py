"""Multi-host initialization (ICI intra-slice, DCN inter-slice).

Reference parity (SURVEY.md §5 "distributed communication backend"): the
reference's substrate is MongoDB polling + Spark RPC.  The TPU-native
numeric plane is ``jax.distributed`` + XLA collectives: every host joins
one runtime, device collectives ride ICI within a slice and DCN across
slices, and the *control* plane (trial queue for black-box objectives)
stays host-side (:mod:`hyperopt_tpu.parallel.file_trials` — durable and
poll-based like Mongo, on a shared filesystem).

Single-controller convention: host 0 runs the fmin driver; other hosts run
workers (`python -m hyperopt_tpu.parallel.worker`) against the shared
queue, or participate purely as mesh devices for sharded suggest.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)


def initialize(
    coordinator_address=None, num_processes=None, process_id=None, **kwargs
):
    """Join the multi-host JAX runtime (no-op when single-process).

    Thin, env-var-aware wrapper over ``jax.distributed.initialize``: with
    no arguments, TPU pod metadata auto-configures everything; explicit
    arguments are for CPU/GPU clusters or tests.
    """
    import jax

    if num_processes in (None, 1) and coordinator_address is None and (
        os.environ.get("JAX_COORDINATOR_ADDRESS") is None
    ):
        # single-host: nothing to initialize, mesh uses local devices
        logger.info("distributed.initialize: single-host, skipping")
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    logger.info(
        "distributed.initialize: process %d/%d ready",
        jax.process_index(),
        jax.process_count(),
    )
    return True


def is_coordinator():
    import jax

    return jax.process_index() == 0


def global_mesh(axis_names=("dp", "sp"), shape=None):
    """Mesh over ALL devices in the distributed runtime (every host must
    call this with the same arguments — standard SPMD contract).

    Device order is process-major, so with the default (dp, sp) axes the
    trailing ``sp`` axis stays within a host's local devices: the
    component-axis psum/pmax (the scorer's only collectives) ride ICI,
    while ``dp`` — which needs no communication — spans hosts/DCN. The
    cross-process collective transport itself is exercised by the test
    suite with a deliberately transposed grid
    (``tests/distributed_score_helper.py``)."""
    from .sharding import default_mesh

    import jax

    return default_mesh(axis_names=axis_names, shape=shape, devices=jax.devices())
