"""Parallel & distributed execution backends.

The reference's parallelism inventory (SURVEY.md §2, parallelism table):
task/trial parallelism via ``SparkTrials`` (driver threads + one-task
jobs) and ``MongoTrials`` (durable poll queue + atomic reservation).

TPU-native equivalents:
- :mod:`sharding` — mesh construction + shard_map'd TPE scoring: the
  history/component axis is sharded over ``sp`` (this framework's
  sequence-parallel analog: blockwise log-sum-exp with ``psum`` over ICI)
  and the candidate axis over ``dp``.
- :mod:`jax_trials` — ``JaxTrials``: batched asynchronous trial execution
  (SparkTrials analog; thread-pool dispatcher + timeout→cancel) plus
  on-device vectorized batch evaluation for jittable objectives.
- :mod:`file_trials` — ``FileTrials``: durable filesystem-backed work queue
  with atomic reservation (MongoTrials analog) + a worker CLI.
- :mod:`distributed` — ``jax.distributed`` multi-host initialization
  helpers (ICI intra-slice, DCN inter-slice).
"""

from . import distributed, sharding
from .file_trials import FileTrials
from .jax_trials import JaxTrials

__all__ = ["FileTrials", "JaxTrials", "distributed", "sharding"]
