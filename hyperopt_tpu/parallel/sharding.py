"""Device meshes + sharded TPE scoring (the SP/DP compute plane).

The reference has no tensor parallelism to mirror (SURVEY.md §2); its
scaling axis is *trial history length* inside TPE, which it handles by
truncation (``linear_forgetting=25`` drops old trials).  The TPU-native
answer (SURVEY.md §5 "long-context"): keep the FULL history, shard the
mixture-component axis across the mesh (``sp`` — the sequence-parallel
analog), and do blockwise log-sum-exp with ``psum``/``pmax`` collectives
over ICI; candidates shard over ``dp``.  This is the same blockwise-
softmax-over-shards pattern as ring attention, minus the ring: component
blocks are resident per-device, only O(C) scalars cross the interconnect.

Everything here is pure ``shard_map`` + collectives — XLA inserts the ICI
communication; nothing is hand-scheduled.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax: pre-promotion experimental namespace
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_SQRT_2PI = 2.5066282746310002
EPS = 1e-12


def default_mesh(axis_names=("dp", "sp"), shape=None, devices=None):
    """Build a 2-D device mesh: ``dp`` (candidates/batch) × ``sp`` (history).

    With n devices and no explicit shape, uses (n // sp_size, sp_size) with
    the largest power-of-two ``sp`` ≤ √n — history sharding is the scaling
    axis, candidate sharding the throughput axis.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if shape is None:
        sp = 1
        while sp * 2 <= int(np.sqrt(n)) + 1 and (n % (sp * 2)) == 0:
            sp *= 2
        dp = n // sp
        shape = (dp, sp)
    if shape[0] * shape[1] != n:
        raise ValueError(
            f"mesh shape dp={shape[0]} x sp={shape[1]} needs "
            f"{shape[0] * shape[1]} devices; {n} local device(s) available"
        )
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names)


class DeviceMesh:
    """The production mesh-execution mode of the fused suggest plane.

    Wraps the topology decision — which local chips participate and in
    what ``dp`` (candidates/batched studies) × ``sp`` (Parzen
    components) layout — behind one object the drivers, the service
    scheduler, and the observability planes all share:

    - :meth:`auto` builds a mesh over EVERY local device with the
      :func:`default_mesh` shape heuristic;
    - :meth:`from_spec` parses the server flag grammar
      (``auto`` | ``off`` | ``"DPxSP"`` / ``"DP,SP"``);
    - :attr:`jax_mesh` is the underlying :class:`jax.sharding.Mesh` the
      device programs shard over — ``None`` in the DEGENERATE case
      (one device, or ``off``): the dispatch then runs the single-chip
      program **bit-for-bit** (no sharding constraints, same jit cache
      key as ``mesh=None`` always had);
    - :meth:`topology` is the JSON-able identity (backend, device
      count, dp, sp) the compile-ledger fingerprint and the metrics
      plane stamp, so programs compiled under one topology are never
      replayed onto another.

    Hashable/comparable by topology + device set, so it can sit in jit
    statics and cache keys exactly like the raw Mesh did.
    """

    __slots__ = ("jax_mesh", "dp", "sp", "devices")

    def __init__(self, devices=None, shape=None):
        devices = (
            list(jax.devices()) if devices is None else list(devices)
        )
        if not devices:
            raise ValueError("DeviceMesh needs at least one device")
        self.devices = tuple(devices)
        if len(devices) == 1 and shape in (None, (1, 1)):
            # degenerate: exactly today's single-chip dispatch
            self.jax_mesh = None
            self.dp, self.sp = 1, 1
        else:
            self.jax_mesh = default_mesh(shape=shape, devices=devices)
            self.dp = int(self.jax_mesh.shape["dp"])
            self.sp = int(self.jax_mesh.shape["sp"])

    # -- constructors --------------------------------------------------
    @classmethod
    def auto(cls, devices=None):
        """A mesh over every local device (degenerate on one chip)."""
        return cls(devices=devices)

    @classmethod
    def from_spec(cls, spec, devices=None):
        """Parse the ``--mesh`` flag grammar.

        ``None``/``"off"`` → None (single-chip dispatch), ``"auto"`` →
        :meth:`auto`, ``"DPxSP"`` or ``"DP,SP"`` → that explicit shape
        over the local devices (ValueError when the product does not
        match the device count).  A DeviceMesh or jax Mesh passes
        through untouched."""
        if spec is None:
            return None
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, Mesh):
            return cls(
                devices=list(np.asarray(spec.devices).flat),
                shape=tuple(int(s) for s in np.asarray(spec.devices).shape),
            )
        token = str(spec).strip().lower()
        if token in ("off", "none", ""):
            return None
        if token == "auto":
            return cls.auto(devices=devices)
        for sep in ("x", ","):
            if sep in token:
                parts = token.split(sep)
                if len(parts) != 2:
                    break
                try:
                    dp, sp = int(parts[0]), int(parts[1])
                except ValueError:
                    break
                if dp < 1 or sp < 1:
                    raise ValueError(f"mesh axes must be >= 1: {spec!r}")
                devs = (
                    list(jax.devices()) if devices is None
                    else list(devices)
                )
                if dp * sp != len(devs):
                    # never silently run on a subset: idle chips would
                    # contradict every topology identity stamped from
                    # this process (ledger fingerprint, /v1/status)
                    raise ValueError(
                        f"mesh spec {spec!r} covers {dp * sp} device(s) "
                        f"but {len(devs)} are local; use an exact shape "
                        f"or 'auto'"
                    )
                return cls(devices=devs, shape=(dp, sp))
        raise ValueError(
            f"bad mesh spec {spec!r}: expected 'auto', 'off', or 'DPxSP' "
            f"(e.g. '4x2' or '4,2')"
        )

    # -- identity ------------------------------------------------------
    @property
    def n_devices(self) -> int:
        return len(self.devices)

    @property
    def shape_str(self) -> str:
        return f"{self.dp}x{self.sp}"

    def device_labels(self):
        """Stable per-chip labels ('<platform>:<id>') for the
        per-device telemetry split."""
        return [f"{d.platform}:{d.id}" for d in self.devices]

    def topology(self) -> dict:
        """The JSON-able topology identity (the compile-ledger
        fingerprint contribution)."""
        return {
            "backend": str(self.devices[0].platform),
            "device_count": self.n_devices,
            "mesh": self.shape_str if self.jax_mesh is not None else "off",
        }

    def __eq__(self, other):
        return (
            isinstance(other, DeviceMesh)
            and self.devices == other.devices
            and (self.dp, self.sp) == (other.dp, other.sp)
        )

    def __hash__(self):
        return hash((self.devices, self.dp, self.sp))

    def __repr__(self):
        mode = "degenerate" if self.jax_mesh is None else self.shape_str
        return f"DeviceMesh({mode}, n_devices={self.n_devices})"


def resolve_mesh(mesh):
    """Normalize every accepted ``mesh=`` input to what the device
    plane dispatches on: a :class:`jax.sharding.Mesh`, or ``None`` for
    the single-chip program.

    Accepts None, a jax Mesh (passed through), a :class:`DeviceMesh`
    (its ``jax_mesh`` — None when degenerate, keeping the one-device
    case bit-for-bit on today's path), or a spec string
    (``auto``/``off``/``DPxSP``)."""
    if mesh is None or isinstance(mesh, Mesh):
        return mesh
    if isinstance(mesh, DeviceMesh):
        return mesh.jax_mesh
    dm = DeviceMesh.from_spec(mesh)
    return None if dm is None else dm.jax_mesh


def mesh_shape_str(mesh) -> str:
    """'off' | 'DPxSP' for any accepted mesh form — the label the
    dispatch spans and bench rows carry."""
    if mesh is None:
        return "off"
    if isinstance(mesh, DeviceMesh):
        return "off" if mesh.jax_mesh is None else mesh.shape_str
    return "x".join(
        str(int(mesh.shape[name])) for name in mesh.axis_names
    )


def _local_logsumexp_block(comp_ll, axis_name):
    """Distributed log-sum-exp over the sharded component axis."""
    m_loc = jnp.max(comp_ll, axis=1)
    m_glob = jax.lax.pmax(m_loc, axis_name)
    s_loc = jnp.sum(jnp.exp(comp_ll - m_glob[:, None]), axis=1)
    s_glob = jax.lax.psum(s_loc, axis_name)
    return m_glob + jnp.log(jnp.maximum(s_glob, EPS))


def _ndtr(z):
    return jax.scipy.special.ndtr(jnp.clip(z, -40.0, 40.0))


def make_sharded_score(mesh: Mesh, dp: str = "dp", sp: str = "sp"):
    """Jitted sharded l(x)/g(x) scorer.

    ``cand`` is sharded over ``dp``; both mixtures' (w, mu, sigma) over
    ``sp``.  Returns per-candidate ``log l − log g`` (sharded over dp).
    Semantics match :func:`hyperopt_tpu.ops.gmm.gmm_lpdf` (continuous).
    """

    def _lpdf_block(cand, w, mu, sigma, low, high):
        sigma = jnp.maximum(sigma, EPS)
        logw = jnp.log(jnp.maximum(w, EPS))
        comp_ll = (
            -0.5 * ((cand[:, None] - mu[None, :]) / sigma[None, :]) ** 2
            - jnp.log(sigma * _SQRT_2PI)[None, :]
            + logw[None, :]
        )
        ll = _local_logsumexp_block(comp_ll, sp)
        # in-bounds mixture mass, reduced over the sharded component axis
        p_acc_loc = jnp.sum(
            w * (_ndtr((high - mu) / sigma) - _ndtr((low - mu) / sigma))
        )
        p_acc = jax.lax.psum(p_acc_loc, sp)
        in_b = (cand >= low) & (cand < high)
        return jnp.where(in_b, ll - jnp.log(jnp.maximum(p_acc, EPS)), -jnp.inf)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(dp),          # candidates
            P(sp), P(sp), P(sp),  # below mixture
            P(sp), P(sp), P(sp),  # above mixture
            P(), P(),       # bounds (replicated)
        ),
        out_specs=P(dp),
    )
    def score(cand, wb, mb, sb, wa, ma, sa, low, high):
        ll_b = _lpdf_block(cand, wb, mb, sb, low, high)
        ll_a = _lpdf_block(cand, wa, ma, sa, low, high)
        return ll_b - ll_a

    return jax.jit(score)


def make_sharded_quantized_score(
    mesh: Mesh, log_scale: bool, dp: str = "dp", sp: str = "sp"
):
    """Sharded quantized pair scorer: ``log l − log g`` where each term
    integrates the candidate's bucket ``[x − q/2, x + q/2]`` against the
    mixture via CDF differences (``ops.gmm.gmm_lpdf`` quantized
    semantics).  Both the bucket mass and ``p_accept`` are plain sums
    over components, so sharding the component axis is a local partial
    sum + ``psum`` over ICI — no logsumexp machinery needed."""

    # one source of truth for the bucket/CDF math: ops.gmm's helpers
    # (this scorer's contract is exact parity with gmm_lpdf quantized)
    from ..ops.gmm import _cdf, _log_cdf_arg

    def _qprob_block(x, w, mu, sigma, low, high, q):
        qq = jnp.maximum(q, EPS)
        if log_scale:
            raw_low = jnp.where(jnp.isfinite(low), jnp.exp(low), 0.0)
            raw_high = jnp.where(jnp.isfinite(high), jnp.exp(high), jnp.inf)
            ub_z = _log_cdf_arg(jnp.minimum(x + qq / 2.0, raw_high))
            lb_z = _log_cdf_arg(
                jnp.maximum(jnp.maximum(x - qq / 2.0, raw_low), 0.0)
            )
        else:
            ub_z = jnp.minimum(x + qq / 2.0, high)
            lb_z = jnp.maximum(x - qq / 2.0, low)
        prob_loc = jnp.sum(
            w[None, :]
            * (
                _cdf(ub_z[:, None], mu[None, :], sigma[None, :])
                - _cdf(lb_z[:, None], mu[None, :], sigma[None, :])
            ),
            axis=1,
        )
        prob = jax.lax.psum(prob_loc, sp)
        pacc = jax.lax.psum(
            jnp.sum(w * (_cdf(high, mu, sigma) - _cdf(low, mu, sigma))), sp
        )
        return jnp.log(jnp.maximum(prob, EPS)) - jnp.log(jnp.maximum(pacc, EPS))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(dp),
            P(sp), P(sp), P(sp),
            P(sp), P(sp), P(sp),
            P(), P(), P(),
        ),
        out_specs=P(dp),
    )
    def score(cand, wb, mb, sb, wa, ma, sa, low, high, q):
        return _qprob_block(cand, wb, mb, sb, low, high, q) - _qprob_block(
            cand, wa, ma, sa, low, high, q
        )

    return jax.jit(score)


def make_sharded_pair_score_batched(mesh: Mesh, dp: str = "dp", sp: str = "sp"):
    """Label-stacked sharded pair scorer for the unified device suggest
    path (VERDICT r4 #2): the mesh analog of ``ops.score.pair_score``'s
    quadratic-matmul formulation, batched over a family's L labels.

    ``z`` [L, Cp] (Cp divisible by |dp|), ``params`` [L, 3, Kp] (Kp
    divisible by |sp|; pad columns with ``[0, 0, NEG_BIG]``), ``k_below``
    a replicated i32 scalar → ``log l − log g`` [L, Cp] (up to the same
    additive constants ``pair_score`` drops — argmax-invariant).

    Candidates shard over ``dp``; the CONCATENATED component axis shards
    over ``sp``, so a shard may straddle the below/above boundary — each
    region is reduced with a masked blockwise logsumexp keyed on global
    column index (``pmax``/``psum`` over ICI), the ring-attention-style
    pattern :func:`make_sharded_score` uses, minus separate buffers.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, dp), P(None, None, sp), P()),
        out_specs=P(None, dp),
    )
    def score(z, params, k_below):
        f = jnp.stack([z * z, z, jnp.ones_like(z)], axis=-1)  # [L, C_loc, 3]
        # rank-3 matmul per label; HIGHEST for true-f32 accumulation
        # (same reasoning as ops.score.pair_score)
        comp = jnp.einsum(
            "lcf,lfk->lck", f, params, precision=jax.lax.Precision.HIGHEST
        )  # [L, C_loc, K_loc]
        k_loc = params.shape[-1]
        gcol = jax.lax.axis_index(sp) * k_loc + jnp.arange(k_loc)
        below = gcol < k_below  # [K_loc] global-region membership

        NEG_BIG = -1e30

        def masked_lse(mask):
            m = mask[None, None, :]
            m_loc = jnp.max(jnp.where(m, comp, -jnp.inf), axis=2)
            m_glob = jax.lax.pmax(m_loc, sp)
            m_safe = jnp.maximum(m_glob, NEG_BIG)
            s_loc = jnp.sum(
                jnp.where(m, jnp.exp(comp - m_safe[..., None]), 0.0), axis=2
            )
            s_glob = jax.lax.psum(s_loc, sp)
            return m_safe + jnp.log(jnp.maximum(s_glob, 1e-300))

        return masked_lse(below) - masked_lse(~below)

    return score


def make_sharded_best(mesh: Mesh, dp: str = "dp", sp: str = "sp"):
    """Sharded score → per-id argmax → ``[k]`` winners, all on device.

    Composes :func:`make_sharded_score` with the reshape/argmax/gather so
    the only host readback per label is the ``[k]`` winning values —
    the O(k)-readback rule the device path documents
    (``tpe_device.py``), now held on the mesh path too (the [C] score
    vector never leaves the device).
    """
    score_fn = make_sharded_score(mesh, dp, sp)

    @partial(jax.jit, static_argnames=("k", "n_cand"))
    def best(cand, z_pad, wb, mb, sb, wa, ma, sa, low, high, *, k, n_cand):
        s = score_fn(z_pad, wb, mb, sb, wa, ma, sa, low, high)
        s = s[: k * n_cand].reshape(k, n_cand)
        c = cand[: k * n_cand].reshape(k, n_cand)
        idx = jnp.argmax(s, axis=1)
        return jnp.take_along_axis(c, idx[:, None], axis=1)[:, 0]

    return best


def make_sharded_best_quantized(
    mesh: Mesh, log_scale: bool, dp: str = "dp", sp: str = "sp"
):
    """Quantized-dist variant of :func:`make_sharded_best` (bucket-
    integral scorer; candidates are RAW values, not log-space)."""
    score_fn = make_sharded_quantized_score(mesh, log_scale, dp, sp)

    @partial(jax.jit, static_argnames=("k", "n_cand"))
    def best(cand, x_pad, wb, mb, sb, wa, ma, sa, low, high, q, *, k, n_cand):
        s = score_fn(x_pad, wb, mb, sb, wa, ma, sa, low, high, q)
        s = s[: k * n_cand].reshape(k, n_cand)
        c = cand[: k * n_cand].reshape(k, n_cand)
        idx = jnp.argmax(s, axis=1)
        return jnp.take_along_axis(c, idx[:, None], axis=1)[:, 0]

    return best


def make_sharded_batch_eval(mesh: Mesh, fn, dp: str = "dp"):
    """Vectorized on-device objective evaluation, batch sharded over dp.

    ``fn`` is a jittable per-config objective taking a dict of scalars;
    the returned callable evaluates a whole batch {label: [B]} with the
    batch axis laid out across the mesh's ``dp`` axis (the SparkTrials-
    executor analog, minus the serialization: one XLA program, B lanes).
    """
    batch_spec = P(dp)

    vf = jax.vmap(fn)

    def run(batch):
        shardings = {k: NamedSharding(mesh, batch_spec) for k in batch}
        placed = {
            k: jax.device_put(jnp.asarray(v), shardings[k]) for k, v in batch.items()
        }
        return jax.jit(vf)(placed)

    return run


def pad_mixture(w, mu, sigma, total):
    """Pad mixture arrays to ``total`` (weight-0 tail) for even sharding."""
    k = len(w)
    assert total >= k
    wp = np.zeros(total, np.float32)
    mp = np.zeros(total, np.float32)
    sp_ = np.ones(total, np.float32)
    wp[:k], mp[:k], sp_[:k] = w, mu, sigma
    return wp, mp, sp_
