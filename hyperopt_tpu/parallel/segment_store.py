"""Segmented append-only trial log — the O(delta) trial store.

The per-doc layout (``trials/<tid>.json``, one atomic-replace + fsync
per trial-state transition, an O(N) directory scan per refresh) is
correct and simple, but its costs scale with *total* trial count: at
the 100k-trial studies the ROADMAP targets, every refresh re-reads
100k files and every transition pays a full tmp/fsync/replace cycle.
This module promotes the battle-tested ``O_APPEND`` + CRC journal
format (the response journal / compile ledger / trace log discipline,
shared via :mod:`hyperopt_tpu.journal_io` and machine-enforced by the
DL4xx durability lint) into the PRIMARY trial store:

``<queue>/segments/seg-<seq>.log``
    Fixed-size segments of CRC-framed records (``\\n<crc32 hex>
    <json>`` via ``tracing.format_record``), one ``O_APPEND`` write —
    and one fsync — per append *call*; a batch of docs group-commits as
    ONE write + ONE fsync.  A torn tail garbles at most the record
    being written; the next append's leading newline re-synchronizes
    every reader.

``<queue>/segments/MANIFEST.json``
    The recovery protocol, in one CRC-trailed doc published by atomic
    replace: the ordered list of **sealed** (immutable) segments — each
    pinned to an exact byte length, record count, and CRC32 — plus the
    name of the single **active** segment appends go to.  Recovery =
    replay segments in manifest order; replication = ship sealed
    segments (service.replicas.SegmentMirror pulls them through
    fence-checked cut points).

Refresh is O(delta): every reader keeps a per-segment byte cursor and
replays only the unseen tail — a stat of the manifest plus a read of
the new bytes — instead of re-reading N doc files.  The in-memory
materialized view (latest doc per tid, plus per-state tid sets) is
what ``FileJobs`` serves ``all_docs``/``count_states``/``reserve``
scans from, which is how the serve hot path reaches ZERO O(N)
directory scans (StoreStats-reconciled).

Compaction folds the latest doc per tid into a fresh base segment
(atomic publish), swaps the manifest (epoch bump), re-homes any
straggler records a concurrent appender landed in the old active, and
only then unlinks the retired segments.  A SIGKILL at any point leaves
either the old manifest (old segments intact) or the new one (retired
segments at worst orphaned on disk — fsck FS412 reclaims them).

Concurrent multi-process appenders are safe on a local/NFS-close
filesystem: ``O_APPEND`` writes interleave at record granularity, and
every appender re-checks the manifest AFTER its write — if a
concurrent seal or compaction cut the segment under it, the appender
re-appends its records to the current active (replay is latest-wins
per tid, so the superseded copy is harmless).
"""

from __future__ import annotations

import copy
import glob
import json
import logging
import os
import threading
import time
import zlib

from .. import journal_io
from ..base import JOB_STATES

logger = logging.getLogger(__name__)

MANIFEST_NAME = "MANIFEST.json"
SEGMENT_GLOB = "seg-*.log"
DEFAULT_SEGMENT_MAX_BYTES = 4 * 1024 * 1024
# auto-compaction: once superseded (dead) records outnumber live tids
# by this factor AND at least one segment has sealed, fold the log
DEFAULT_COMPACT_DEAD_FACTOR = 8


def _codec():
    """(dumps-default, loads-object-hook): THE trial-doc codec, shared
    with the per-doc layout so docs round-trip datetimes/bytes
    identically whichever backend wrote them."""
    from .file_trials import _json_default, _json_object_hook

    return _json_default, _json_object_hook


def _active_chaos():
    import sys

    mod = sys.modules.get("hyperopt_tpu.resilience.chaos")
    return mod.get_active() if mod is not None else None


def _stats():
    from .file_trials import store_stats

    return store_stats()


def segment_name(seq: int) -> str:
    return f"seg-{int(seq):08d}.log"


def parse_segment_chunk(chunk: bytes, object_hook=None):
    """Incremental frame parser: ``(records, consumed, n_torn,
    n_pending)`` from a byte range of a segment file.

    ``consumed`` is the offset just past the last VALID record — a
    trailing line that fails its CRC is **left unconsumed** (``n_pending``
    counts it) because it may be a concurrent append still in flight;
    the next read re-attempts it.  Invalid lines that are *followed* by
    a valid record are genuinely torn (``n_torn``) and are consumed by
    the leading-newline resync."""
    records, consumed, torn, pending = [], 0, 0, 0
    n = len(chunk)
    start = 0
    while start < n:
        end = chunk.find(b"\n", start + 1)
        if end == -1:
            end = n
        line = chunk[start:end].strip()
        if line:
            try:
                crc_hex, body = line.split(b" ", 1)
                if (zlib.crc32(body) & 0xFFFFFFFF) != int(crc_hex, 16):
                    raise ValueError("crc mismatch")
                rec = json.loads(body.decode(), object_hook=object_hook)
            except (ValueError, json.JSONDecodeError, UnicodeDecodeError):
                pending += 1
                start = end
                continue
            records.append(rec)
            consumed = end
            torn += pending
            pending = 0
        start = end
    return records, consumed, torn, pending


class SegmentStore:
    """One study's segmented trial log + its materialized view.

    Thread-safe; cross-process safe for concurrent appenders (see the
    module docstring for the seal/compaction race protocol).  All disk
    state lives under ``<root>/segments``; the manifest's existence IS
    the "this queue is segmented" marker ``FileJobs`` detects.
    """

    # lock-order: _lock (never held across another SegmentStore's lock)
    def __init__(self, root, segment_max_bytes=DEFAULT_SEGMENT_MAX_BYTES,
                 compact_dead_factor=DEFAULT_COMPACT_DEAD_FACTOR,
                 auto_compact=True):
        self.root = os.path.abspath(root)
        self.dir = os.path.join(self.root, "segments")
        self.segment_max_bytes = int(segment_max_bytes)
        self.compact_dead_factor = int(compact_dead_factor)
        self.auto_compact = bool(auto_compact)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        # materialized view — guarded-by: _lock
        self._view = {}            # tid -> latest doc
        self._state_tids = {s: set() for s in JOB_STATES}
        self._manifest = None      # last manifest doc we loaded
        self._manifest_sig = None  # (st_mtime_ns, st_size, st_ino)
        self._offsets = {}         # segment name -> bytes applied
        self._applied_records = 0  # records replayed into the view
        # consumer-cursor log: tids in apply order, so readers with their
        # own cursor (FileTrials' delta refresh) never miss docs another
        # reader's refresh already folded into the shared view
        self._log = []             # guarded-by: _lock
        self._log_gen = 0          # bumped on every full replay
        self._load()

    # -- paths ---------------------------------------------------------
    @property
    def manifest_path(self):
        return os.path.join(self.dir, MANIFEST_NAME)

    def segment_path(self, name):
        return os.path.join(self.dir, name)

    @staticmethod
    def is_segmented(root) -> bool:
        """Does ``root`` carry a segmented store (manifest present)?"""
        return os.path.exists(
            os.path.join(os.path.abspath(root), "segments", MANIFEST_NAME)
        )

    # -- manifest ------------------------------------------------------
    def _read_manifest(self):
        """(manifest, stat-sig) from disk; (None, None) when absent or
        persistently unreadable (fsck's FS411 owns the repair)."""
        from .file_trials import _read_doc

        try:
            st = os.stat(self.manifest_path)
        except FileNotFoundError:
            return None, None
        sig = (st.st_mtime_ns, st.st_size, st.st_ino)
        doc = _read_doc(self.manifest_path, quarantine=False)
        return doc, sig

    def _write_manifest(self, manifest):
        """Publish a manifest revision by atomic replace and refresh the
        cached stat-sig so our own write is not re-read as news."""
        from .file_trials import _write_doc

        _write_doc(self.manifest_path, manifest, fsync_kind="segment")
        st = os.stat(self.manifest_path)
        self._manifest = manifest
        self._manifest_sig = (st.st_mtime_ns, st.st_size, st.st_ino)

    def _fresh_manifest(self):
        return {
            "version": 1,
            "epoch": 0,
            "next_seq": 2,
            "active": segment_name(1),
            "sealed": [],
        }

    def _load(self):
        with self._lock:
            manifest, sig = self._read_manifest()
            if manifest is None:
                # fresh store (or a pre-segment dir being initialized):
                # publish the empty manifest so every other process —
                # and fsck — sees the segmented layout marker
                manifest = self._fresh_manifest()
                self._write_manifest(manifest)
            else:
                self._manifest = manifest
                self._manifest_sig = sig
            self._replay_locked()

    # -- replay / refresh ---------------------------------------------
    def _apply(self, doc):
        tid = int(doc["tid"])
        old = self._view.get(tid)
        if old is not None:
            self._state_tids[old["state"]].discard(tid)
        self._view[tid] = doc
        self._state_tids[doc["state"]].add(tid)
        self._applied_records += 1
        self._log.append(tid)  # lint: disable=RL301  caller holds _lock

    def _segment_ranges(self):
        """(name, limit) pairs in replay order: sealed segments pinned
        to their manifest byte length, then the unbounded active."""
        out = []
        for entry in self._manifest.get("sealed", ()):
            out.append((entry["name"], int(entry["bytes"])))
        out.append((self._manifest["active"], None))
        return out

    def _replay_locked(self, full=False):
        """Replay unseen segment bytes into the view.  Returns the list
        of docs applied (the delta).  ``full`` resets the cursor and
        view first (initial load, post-compaction epoch change)."""
        _, object_hook = _codec()
        if full:
            self._view = {}
            self._state_tids = {s: set() for s in JOB_STATES}
            self._offsets = {}
            self._applied_records = 0
            self._log = []  # lint: disable=RL301  caller holds _lock
            self._log_gen += 1
        delta = []
        n_torn = 0
        for name, limit in self._segment_ranges():
            path = self.segment_path(name)
            applied = self._offsets.get(name, 0)
            if limit is None:
                try:
                    limit = os.path.getsize(path)
                except FileNotFoundError:
                    continue
            if limit <= applied:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(applied)
                    chunk = f.read(limit - applied)
            except FileNotFoundError:
                continue
            sealed = name != self._manifest["active"]
            records, consumed, torn, pending = parse_segment_chunk(
                chunk, object_hook=object_hook
            )
            if sealed:
                # nothing can be in flight in an immutable segment: a
                # pending (trailing-invalid) line is simply torn
                n_torn += torn + pending
                self._offsets[name] = limit
            else:
                n_torn += torn
                self._offsets[name] = applied + consumed
            for rec in records:
                self._apply(rec)
                delta.append(rec)
        stats = _stats()
        if stats is not None:
            if n_torn:
                stats.record_segment_torn(n_torn)
            stats.record_segment_replay(len(delta), full=full)
        return delta

    def refresh(self):
        """O(delta) tail replay: stat the manifest, reload it if it
        moved (seal/compaction), read only unseen segment bytes.
        Returns the delta docs (copies) in replay order."""
        with self._lock:
            delta = self._refresh_locked()
            return [copy.deepcopy(d) for d in delta]

    def _refresh_locked(self):
        manifest, sig = self._read_manifest()
        if manifest is not None and sig != self._manifest_sig:
            epoch_changed = manifest.get("epoch") != self._manifest.get(
                "epoch"
            )
            self._manifest = manifest
            self._manifest_sig = sig
            if epoch_changed:
                # a compaction rewrote history: replay the new lineage
                # from scratch (the folded base carries the same view)
                return self._replay_locked(full=True)
        return self._replay_locked()

    # -- reads (view) --------------------------------------------------
    def get(self, tid):
        with self._lock:
            self._refresh_locked()
            doc = self._view.get(int(tid))
            return copy.deepcopy(doc) if doc is not None else None

    def all_docs(self):
        """Every live doc, tid-ascending — from the view, ZERO directory
        scans (the whole point)."""
        with self._lock:
            self._refresh_locked()
            return [
                copy.deepcopy(self._view[tid])
                for tid in sorted(self._view)
            ]

    def count_states(self):
        with self._lock:
            self._refresh_locked()
            return {s: len(self._state_tids[s]) for s in JOB_STATES}

    def tids_in_state(self, state):
        with self._lock:
            self._refresh_locked()
            return sorted(self._state_tids.get(state, ()))

    def docs_since(self, cursor):
        """(new_cursor, delta_docs) for a consumer holding its own
        cursor — docs whose latest apply happened after ``cursor``, in
        apply order, deduped to the newest version per tid.

        The shared view advances whenever ANY reader refreshes
        (``count_states`` in a poll loop, ``get`` on the serve path), so
        a consumer that wants "everything since I last looked" cannot
        use :meth:`refresh`'s delta — it would miss docs a sibling
        reader already folded in.  Cursors are opaque; pass ``None`` to
        start from the beginning (full initial sync).  A full replay
        (compaction epoch change, :meth:`delete_all`) invalidates old
        cursors: they restart from zero, which is idempotent for
        latest-wins consumers."""
        with self._lock:
            self._refresh_locked()
            idx = 0
            if cursor is not None:
                gen, pos = cursor
                if gen == self._log_gen and pos <= len(self._log):
                    idx = pos
            seen = set()
            tids = []
            for tid in reversed(self._log[idx:]):
                if tid not in seen:
                    seen.add(tid)
                    tids.append(tid)
            tids.reverse()
            delta = [
                copy.deepcopy(self._view[tid])
                for tid in tids
                if tid in self._view
            ]
            return (self._log_gen, len(self._log)), delta

    def __len__(self):
        with self._lock:
            return len(self._view)

    # -- appends -------------------------------------------------------
    def append(self, doc):
        self.append_many([doc])

    def append_many(self, docs):  # protocol: cursor-advance
        """Group-commit a batch of trial-state transitions: ONE
        ``O_APPEND`` write + ONE fsync covers every doc in ``docs``
        (the ≥10x fsyncs-per-transition win over per-doc at batch
        sizes the service's fused suggest already produces)."""
        if not docs:
            return
        default, _ = _codec()
        with self._lock:
            self._refresh_locked()
            active = self._manifest["active"]
            path = self.segment_path(active)
            nbytes, end = journal_io.append_records(
                path, docs, default=default, fsync_kind="segment",
                with_offset=True,
            )
            stats = _stats()
            if stats is not None:
                stats.record_segment_append(len(docs), nbytes)
            chaos = _active_chaos()
            if chaos is not None:
                chaos.maybe_torn_segment(path, docs[0].get("tid", 0))
            # post-write manifest re-check: a concurrent seal or
            # compaction may have cut the segment under us — if our
            # bytes fell outside the surviving range, re-home them
            manifest, sig = self._read_manifest()
            if manifest is not None and sig != self._manifest_sig:
                if not self._write_survives(manifest, active, end):
                    self._manifest = manifest
                    self._manifest_sig = sig
                    journal_io.append_records(
                        self.segment_path(manifest["active"]), docs,
                        default=default, fsync_kind="segment",
                        with_offset=True,
                    )
                    logger.info(
                        "segment store %s: re-homed %d record(s) cut by "
                        "a concurrent seal/compaction", self.dir,
                        len(docs),
                    )
            for doc in docs:
                self._apply(copy.deepcopy(doc))
            # our own appended bytes are already in the view: advance
            # the cursor so the next refresh does not replay them — but
            # ONLY when our write is contiguous with it.  Under
            # O_APPEND another process's records may have landed in
            # [cursor, end - nbytes) between our refresh above and our
            # write; jumping the cursor to `end` would skip those bytes
            # forever.  Leaving the cursor put lets the next refresh
            # replay the gap; re-replaying our own records is harmless
            # (latest-wins per tid).
            if self._offsets.get(active, 0) == end - nbytes:
                self._offsets[active] = end
            self._maybe_seal_locked()
            if self.auto_compact and self._compaction_due_locked():
                self._compact_locked()

    @staticmethod
    def _write_survives(manifest, segment, end_offset):
        """Under ``manifest``, do bytes ``[..end_offset)`` of
        ``segment`` still get replayed?"""
        if manifest.get("active") == segment:
            return True
        for entry in manifest.get("sealed", ()):
            if entry["name"] == segment:
                return int(entry["bytes"]) >= end_offset
        return False

    # -- sealing -------------------------------------------------------
    def _seal_lock_acquire(self, timeout=10.0):  # protocol: lock-break
        """Cross-process seal/compaction mutex: O_CREAT|O_EXCL lock
        file, stale-broken after 30s (a SIGKILL'd sealer must not wedge
        the store forever)."""
        lock = os.path.join(self.dir, ".seal.lock")
        deadline = time.monotonic() + timeout
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                return lock
            except FileExistsError:
                try:
                    if time.time() - os.path.getmtime(lock) > 30.0:
                        # break the stale lock by renaming it to a
                        # private name first: only ONE breaker wins the
                        # rename, so two processes that both judged the
                        # lock stale cannot end up holding the mutex
                        # concurrently (unlinking the shared path
                        # directly could remove a fresh lock another
                        # breaker just re-created)
                        stale = "%s.stale-%d-%d" % (
                            lock, os.getpid(), time.monotonic_ns()
                        )
                        os.rename(lock, stale)  # durability: exempt(lock break: the lock file carries no data; the rename IS the mutual exclusion)
                        os.unlink(stale)
                        continue
                except OSError:
                    continue
                if time.monotonic() > deadline:
                    return None
                time.sleep(0.01)

    def _maybe_seal_locked(self):
        active = self._manifest["active"]
        try:
            size = os.path.getsize(self.segment_path(active))
        except FileNotFoundError:
            return
        if size < self.segment_max_bytes:
            return
        self._seal_active_locked()

    def seal_active(self):
        """Force-seal the active segment (replication cut points and
        graceful handoff ship ONLY sealed segments).  No-op when the
        active segment holds no records."""
        with self._lock:
            self._refresh_locked()
            self._seal_active_locked()

    def _seal_active_locked(self):
        lock = self._seal_lock_acquire()
        if lock is None:
            return  # another process is sealing; it will land
        try:
            # re-read under the seal lock: the seal may already be done
            manifest, sig = self._read_manifest()
            if manifest is not None:
                self._manifest = manifest
                self._manifest_sig = sig
            active = self._manifest["active"]
            path = self.segment_path(active)
            try:
                size = os.path.getsize(path)
            except FileNotFoundError:
                return
            if size == 0:
                return
            with open(path, "rb") as f:
                raw = f.read(size)
            _, object_hook = _codec()
            records, consumed, _torn, _pending = parse_segment_chunk(
                raw, object_hook=object_hook
            )
            if not records:
                return
            # the sealed range ends at the last valid record: a torn or
            # in-flight tail line stays outside the seal and is re-homed
            # by its writer's post-append manifest check
            sealed_bytes = consumed
            entry = {
                "name": active,
                "bytes": int(sealed_bytes),
                "records": len(records),
                "crc32": "%08x" % (zlib.crc32(raw[:sealed_bytes])
                                   & 0xFFFFFFFF),
            }
            manifest = dict(self._manifest)
            manifest["sealed"] = list(manifest.get("sealed", ())) + [entry]
            manifest["active"] = segment_name(manifest["next_seq"])
            manifest["next_seq"] = int(manifest["next_seq"]) + 1
            self._write_manifest(manifest)
            stats = _stats()
            if stats is not None:
                stats.record_segment_seal()
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass

    # -- compaction ----------------------------------------------------
    def _compaction_due_locked(self):
        live = max(len(self._view), 1)
        dead = self._applied_records - len(self._view)
        return (
            dead > self.compact_dead_factor * live
            and len(self._manifest.get("sealed", ())) > 0
        )

    def compact(self):
        """Fold the latest doc per tid into a fresh base segment, swap
        the manifest (epoch bump), re-home straggler records, retire the
        old segments.  Crash-safe at every step: the old manifest and
        segments survive until the new manifest is published; after
        that, the old files are at worst orphans fsck FS412 reclaims."""
        with self._lock:
            self._compact_locked()

    def _compact_locked(self):
        lock = self._seal_lock_acquire()
        if lock is None:
            return
        try:
            default, object_hook = _codec()
            # re-sync under the seal lock so the fold sees every record
            self._refresh_locked()
            old_manifest = self._manifest
            old_names = [n for n, _ in self._segment_ranges()]
            old_active = old_manifest["active"]
            old_active_consumed = self._offsets.get(old_active, 0)
            base_name = segment_name(old_manifest["next_seq"])
            docs = [self._view[tid] for tid in sorted(self._view)]
            blob = b"".join(
                journal_io.frame_record(d, default=default) for d in docs
            )
            from .file_trials import _atomic_write

            # the base segment is PUBLISHED atomically at its final
            # name; a crash before the manifest swap leaves it an
            # unreferenced orphan (FS412), never a half-truth
            _atomic_write(
                self.segment_path(base_name), blob, fsync_kind="segment"
            )
            manifest = {
                "version": 1,
                "epoch": int(old_manifest.get("epoch", 0)) + 1,
                "next_seq": int(old_manifest["next_seq"]) + 2,
                "active": segment_name(old_manifest["next_seq"] + 1),
                "sealed": [{
                    "name": base_name,
                    "bytes": len(blob),
                    "records": len(docs),
                    "crc32": "%08x" % (zlib.crc32(blob) & 0xFFFFFFFF),
                }],
            }
            self._write_manifest(manifest)
            chaos = _active_chaos()
            if chaos is not None:
                # the mid-compaction kill window: manifest swapped, old
                # segments not yet unlinked (FS412 orphans)
                chaos.maybe_compaction_kill(self.dir, manifest["epoch"])
            # re-home stragglers: records a concurrent appender landed
            # in the old active after our fold (their own post-append
            # check also covers this; latest-wins replay dedupes)
            try:
                with open(self.segment_path(old_active), "rb") as f:
                    f.seek(old_active_consumed)
                    tail = f.read()
            except FileNotFoundError:
                tail = b""
            if tail:
                stragglers, _, _, _ = parse_segment_chunk(
                    tail, object_hook=object_hook
                )
                if stragglers:
                    journal_io.append_records(
                        self.segment_path(manifest["active"]),
                        stragglers, default=default,
                        fsync_kind="segment", with_offset=True,
                    )
                    for rec in stragglers:
                        self._apply(rec)
            # retire the folded lineage
            for name in old_names:
                if name == base_name:
                    continue
                try:
                    os.unlink(self.segment_path(name))
                except FileNotFoundError:
                    pass
            # the view IS the folded base: reset the cursor to match
            self._offsets = {base_name: len(blob)}
            self._applied_records = len(docs)
            stats = _stats()
            if stats is not None:
                stats.record_segment_compaction(
                    n_retired=len(old_names) - (
                        1 if base_name in old_names else 0
                    )
                )
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass

    # -- maintenance ---------------------------------------------------
    def delete_all(self):
        """Wipe the log and view (``FileTrials.delete_all``)."""
        with self._lock:
            for p in glob.glob(os.path.join(self.dir, SEGMENT_GLOB)):
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass
            self._view = {}
            self._state_tids = {s: set() for s in JOB_STATES}
            self._offsets = {}
            self._applied_records = 0
            self._log = []
            self._log_gen += 1  # invalidate consumer cursors
            fresh = self._fresh_manifest()
            fresh["epoch"] = int(self._manifest.get("epoch", 0)) + 1
            self._write_manifest(fresh)

    def sealed_entries(self):
        """The manifest's sealed-segment entries (copies), replay-
        ordered — the replication unit list."""
        with self._lock:
            self._refresh_locked()
            return [dict(e) for e in self._manifest.get("sealed", ())]

    def epoch(self):
        with self._lock:
            return int(self._manifest.get("epoch", 0))

    def status(self):
        with self._lock:
            return {
                "epoch": int(self._manifest.get("epoch", 0)),
                "n_sealed": len(self._manifest.get("sealed", ())),
                "active": self._manifest.get("active"),
                "live_docs": len(self._view),
                "applied_records": self._applied_records,
            }


def migrate_queue_dir(root) -> int:
    """One-way migration: fold every legacy ``trials/*.json`` doc into
    a fresh segmented store at ``root`` and remove the doc files.
    Returns the number of docs migrated.  Crash-safe: docs are only
    unlinked after the segment append (one group commit) fsync'd; a
    crash mid-unlink re-migrates the survivors idempotently (latest-
    wins replay by tid)."""
    from .file_trials import _read_doc

    root = os.path.abspath(root)
    store = SegmentStore(root)
    paths = sorted(glob.glob(os.path.join(root, "trials", "*.json")))
    docs = []
    for p in paths:
        doc = _read_doc(p, quarantine=False)
        if doc is not None:
            docs.append(doc)
    if docs:
        store.append_many(docs)
    for p in paths:
        try:
            os.unlink(p)
        except FileNotFoundError:
            pass
    return len(docs)
