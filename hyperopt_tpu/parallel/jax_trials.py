"""JaxTrials: batched asynchronous trial execution.

Reference parity (SURVEY.md §2 #18): ``hyperopt/spark.py`` —
``SparkTrials(Trials)`` (`parallelism`, `timeout`, `loss_threshold`,
concurrency cap ~L30-200) and ``_SparkFMinState`` (driver-side dispatcher,
per-trial tasks, job cancellation on timeout → ``JOB_STATE_CANCEL``,
``_begin/_finish_trial_run`` ~L200-600).

TPU-native redesign: instead of JVM executors there are two execution
planes —
- **host plane** (arbitrary Python objectives): a thread-pool dispatcher
  claims JOB_STATE_NEW docs, runs ``domain.evaluate`` concurrently, and
  enforces per-trial timeouts by cancel-marking (the Spark job-group
  cancel analog);
- **device plane** (jittable objectives): pass ``device_fn=`` — a whole
  queue batch is evaluated as ONE vmapped XLA program with the batch axis
  sharded across the mesh's ``dp`` axis
  (:func:`hyperopt_tpu.parallel.sharding.make_sharded_batch_eval`) —
  SparkTrials' "1 task per trial" becomes "1 program per batch".

``fmin`` drives both through the same asynchronous enqueue/poll loop it
uses for every async backend.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from timeit import default_timer as timer

import numpy as np

from ..base import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    STATUS_OK,
    Ctrl,
    Domain,
    Trials,
    spec_from_misc,
    validate_loss_threshold,
    validate_timeout,
)
from ..utils import coarse_utcnow

logger = logging.getLogger(__name__)

MAX_CONCURRENT_JOBS_ALLOWED = 128


class JaxTrials(Trials):
    """Trials store executing trials in parallel on the local host/devices.

    Drop-in ``Trials`` subclass (the plugin boundary): pass to
    ``fmin(trials=JaxTrials(parallelism=8))``.
    """

    asynchronous = True
    poll_interval_secs = 0.02  # in-process dispatcher: poll fast

    def __init__(
        self,
        parallelism=None,
        timeout=None,
        loss_threshold=None,
        trial_timeout=None,
        device_fn=None,
        mesh=None,
        exp_key=None,
        refresh=True,
        max_speculation=None,
    ):
        """``timeout`` is the whole-run budget (SparkTrials semantics: it
        bounds ``fmin``, not a single trial); ``trial_timeout`` is the
        per-trial cancellation limit (timeout → ``JOB_STATE_CANCEL``).
        They are independent knobs.

        ``max_speculation``: staleness depth of the pipelined suggest
        engine (see :func:`hyperopt_tpu.fmin.fmin`).  In this backend the
        engine prefetches the next suggestion(s) while the dispatcher's
        workers (or the device batch program) evaluate, replacing the
        suggest barrier the enqueue/poll loop otherwise pays."""
        super().__init__(exp_key=exp_key, refresh=refresh)
        validate_timeout(timeout)
        validate_timeout(trial_timeout)
        validate_loss_threshold(loss_threshold)
        if parallelism is None:
            import jax

            parallelism = max(1, len(jax.devices()))
        if parallelism > MAX_CONCURRENT_JOBS_ALLOWED:
            logger.warning(
                "parallelism %d capped at %d", parallelism, MAX_CONCURRENT_JOBS_ALLOWED
            )
            parallelism = MAX_CONCURRENT_JOBS_ALLOWED
        self.parallelism = parallelism
        self.timeout = timeout
        self.trial_timeout = trial_timeout
        self.loss_threshold = loss_threshold
        self.device_fn = device_fn
        self.mesh = mesh
        self.max_speculation = max_speculation
        self._fmin_state = None

    def fmin(
        self,
        fn,
        space,
        algo=None,
        max_evals=None,
        timeout=None,
        loss_threshold=None,
        max_queue_len=None,
        rstate=None,
        verbose=False,
        pass_expr_memo_ctrl=None,
        catch_eval_exceptions=False,
        return_argmin=True,
        show_progressbar=True,
        early_stop_fn=None,
        trials_save_file="",
        points_to_evaluate=None,
        max_speculation=None,
        retry_policy=None,
        fault_stats=None,
        search_stats=None,
    ):
        from ..fmin import fmin as _fmin

        assert (
            not pass_expr_memo_ctrl
        ), "JaxTrials executes objectives outside the driver; plain configs only"
        timeout = timeout if timeout is not None else self.timeout
        loss_threshold = (
            loss_threshold if loss_threshold is not None else self.loss_threshold
        )
        if retry_policy is not None and fault_stats is None:
            # one shared FaultStats across dispatcher threads and the
            # driver, so retry/quarantine accounting lands in one place
            from ..observability import FaultStats

            fault_stats = FaultStats()
        state = _JaxFMinState(
            fn,
            space,
            self,
            parallelism=self.parallelism,
            trial_timeout=self.trial_timeout,
            device_fn=self.device_fn,
            mesh=self.mesh,
            catch_eval_exceptions=catch_eval_exceptions,
            retry_policy=retry_policy,
            fault_stats=fault_stats,
        )
        self._fmin_state = state
        state.start()
        try:
            return _fmin(
                fn,
                space,
                algo=algo,
                max_evals=max_evals,
                timeout=timeout,
                loss_threshold=loss_threshold,
                trials=self,
                rstate=rstate,
                verbose=verbose,
                # the queue must stay at least `parallelism` deep or the
                # dispatcher starves (top-level fmin defaults this to 1)
                max_queue_len=max(max_queue_len or 1, self.parallelism),
                allow_trials_fmin=False,
                pass_expr_memo_ctrl=pass_expr_memo_ctrl,
                catch_eval_exceptions=catch_eval_exceptions,
                return_argmin=return_argmin,
                show_progressbar=show_progressbar,
                early_stop_fn=early_stop_fn,
                trials_save_file=trials_save_file,
                points_to_evaluate=points_to_evaluate,
                max_speculation=(
                    max_speculation
                    if max_speculation is not None
                    else self.max_speculation
                ),
                retry_policy=retry_policy,
                fault_stats=fault_stats,
                search_stats=search_stats,
            )
        finally:
            state.stop()
            self._fmin_state = None


class _JaxFMinState:
    """Driver-side dispatcher: claims NEW trials, runs them concurrently."""

    POLL_SECS = 0.05

    def __init__(
        self,
        fn,
        space,
        trials,
        parallelism,
        trial_timeout=None,
        device_fn=None,
        mesh=None,
        catch_eval_exceptions=False,
        retry_policy=None,
        fault_stats=None,
    ):
        self.trials = trials
        self.domain = Domain(fn, space)
        self.parallelism = parallelism
        self.trial_timeout = trial_timeout
        self.catch_eval_exceptions = catch_eval_exceptions
        # hyperopt_tpu.resilience.RetryPolicy for the host-plane worker
        # threads: backoff retries + per-attempt watchdog + quarantine
        # (the device batch plane is jit-pure and keeps its own path)
        self.retry_policy = retry_policy
        self.fault_stats = fault_stats
        self._device_eval = None
        if device_fn is not None:
            from .sharding import default_mesh, make_sharded_batch_eval

            mesh = mesh or default_mesh()
            self._device_eval = make_sharded_batch_eval(mesh, device_fn)
            self._mesh = mesh
        self._stop = threading.Event()
        self._thread = None
        self._pool = None
        # Guards every multi-field trial-doc mutation from worker threads
        # AND the dispatcher's scan of the shared trial-doc list (the
        # guarded-by declaration below is enforced statically by
        # hyperopt_tpu.analysis.race_lint).  Invariant the driver's
        # refresh() relies on: a trial whose state reads DONE always
        # already has its result written — so result is assigned before
        # state inside the locked region, and the driver (reading under
        # the GIL) can never observe DONE-without-result.
        self._mutate_lock = threading.Lock()

    # guarded-by: trials._dynamic_trials: _mutate_lock

    # -- lifecycle -----------------------------------------------------
    def start(self):
        self._pool = ThreadPoolExecutor(max_workers=self.parallelism)
        self._thread = threading.Thread(target=self._dispatch_loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)

    # -- dispatch ------------------------------------------------------
    def _claim_new(self):
        claimed = []
        with self._mutate_lock:
            for trial in self.trials._dynamic_trials:
                if trial["state"] == JOB_STATE_NEW:
                    now = coarse_utcnow()
                    trial["book_time"] = now
                    trial["refresh_time"] = now
                    trial["owner"] = "jax_trials"
                    trial["state"] = JOB_STATE_RUNNING
                    claimed.append(trial)
        return claimed

    def _dispatch_loop(self):
        while not self._stop.is_set():
            claimed = self._claim_new()
            if claimed:
                if self._device_eval is not None:
                    self._run_batch_on_device(claimed)
                else:
                    for trial in claimed:
                        self._pool.submit(self._run_one, trial)
            time.sleep(self.POLL_SECS)

    # -- host plane ----------------------------------------------------
    def _evaluate(self, spec, ctrl, trial):
        """One objective call, under the retry policy when one is set
        (backoff + deterministic jitter + per-attempt watchdog;
        exhaustion raises TrialQuarantined, which the caller's error
        path lands as JOB_STATE_ERROR — quarantined, run continues)."""
        if self.retry_policy is None:
            return self.domain.evaluate(spec, ctrl)
        from ..resilience.retry import execute_with_retry

        result, attempts = execute_with_retry(
            lambda: self.domain.evaluate(spec, ctrl),
            self.retry_policy,
            key=trial["tid"],
            stats=self.fault_stats,
        )
        with self._mutate_lock:
            trial["misc"]["attempts"] = attempts
        return result

    def _run_one(self, trial):
        spec = spec_from_misc(trial["misc"])
        ctrl = Ctrl(self.trials, current_trial=trial)
        start = timer()
        try:
            if self.trial_timeout is not None:
                result_box = {}

                def target():
                    try:
                        result_box["result"] = self._evaluate(
                            spec, ctrl, trial
                        )
                    except BaseException as e:  # propagated below
                        result_box["error"] = e

                t = threading.Thread(target=target, daemon=True)
                t.start()
                t.join(self.trial_timeout)
                if t.is_alive():
                    with self._mutate_lock:
                        trial["refresh_time"] = coarse_utcnow()
                        trial["state"] = JOB_STATE_CANCEL
                    logger.warning(
                        "trial %s cancelled after %.1fs timeout",
                        trial["tid"],
                        self.trial_timeout,
                    )
                    return
                if "error" in result_box:
                    raise result_box["error"]
                result = result_box["result"]
            else:
                result = self._evaluate(spec, ctrl, trial)
        except Exception as e:
            logger.error("trial %s exception: %s", trial["tid"], e)
            with self._mutate_lock:
                trial["misc"]["error"] = (str(type(e)), str(e))
                trial["refresh_time"] = coarse_utcnow()
                trial["state"] = JOB_STATE_ERROR
            return
        with self._mutate_lock:
            trial["result"] = result
            trial["refresh_time"] = coarse_utcnow()
            trial["state"] = JOB_STATE_DONE
        logger.debug("trial %s done in %.3fs", trial["tid"], timer() - start)

    # -- device plane --------------------------------------------------
    def _run_batch_on_device(self, trials_batch):
        import jax.numpy as jnp

        specs = [spec_from_misc(t["misc"]) for t in trials_batch]
        labels = sorted({k for s in specs for k in s})
        if any(set(s) != set(labels) for s in specs):
            # conditional spaces have ragged configs; device plane needs
            # dense configs -> fall back to host threads
            for trial in trials_batch:
                self._pool.submit(self._run_one, trial)
            return
        # pad the batch to the mesh's dp extent for even sharding
        dp = int(self._mesh.shape.get("dp", 1))
        b = len(specs)
        padded = b if b % dp == 0 else b + (dp - b % dp)
        batch = {
            k: np.asarray([s[k] for s in specs] + [specs[-1][k]] * (padded - b))
            for k in labels
        }
        try:
            losses = np.asarray(self._device_eval(batch))[:b]
        except Exception as e:
            logger.error("device batch failed: %s", e)
            with self._mutate_lock:
                for trial in trials_batch:
                    trial["misc"]["error"] = (str(type(e)), str(e))
                    trial["refresh_time"] = coarse_utcnow()
                    trial["state"] = JOB_STATE_ERROR
            return
        now = coarse_utcnow()
        with self._mutate_lock:
            for trial, loss in zip(trials_batch, losses):
                trial["result"] = {"loss": float(loss), "status": STATUS_OK}
                trial["refresh_time"] = now
                trial["state"] = JOB_STATE_DONE
