"""Search-space DSL facade.

Reference parity (SURVEY.md §2 #4): ``hyperopt/hp.py`` — thin re-exports of
the ``hp_*`` constructors in ``pyll_utils``.
"""

from .pyll_utils import (
    hp_choice as choice,
    hp_loguniform as loguniform,
    hp_lognormal as lognormal,
    hp_normal as normal,
    hp_pchoice as pchoice,
    hp_qloguniform as qloguniform,
    hp_qlognormal as qlognormal,
    hp_qnormal as qnormal,
    hp_quniform as quniform,
    hp_randint as randint,
    hp_uniform as uniform,
    hp_uniformint as uniformint,
)

__all__ = [
    "choice",
    "loguniform",
    "lognormal",
    "normal",
    "pchoice",
    "qloguniform",
    "qlognormal",
    "qnormal",
    "quniform",
    "randint",
    "uniform",
    "uniformint",
]
