"""Pipelined speculative suggest engine.

The serial driver loop adds suggest time and objective time: ``FMinIter``
blocks on the objective for trial *t* before the device program for trial
*t+1* launches.  But TPE's own design point is *asynchronous* evaluation —
the algorithm tolerates suggesting from a history that is missing in-flight
results (Bergstra et al., NeurIPS 2011; Bergstra, Yamins & Cox, ICML 2013) —
so nothing forces those two times to add.

This module exploits that: while the user objective for trial *t* runs (in
a worker thread), the engine **speculatively launches** the full fused
device suggest program (γ-split → Parzen fit → draw → score → argmax) for
trials *t+1 … t+k* against the current history, via the algorithm's
``async_variant`` (non-blocking dispatch, :func:`tpe.suggest_async`).  When
trial *t* completes, a cheap host-side check on the loss quantile decides
whether the completed result would have changed the γ-split the speculation
was fit on; only then is the speculation re-issued (same ids, same seed,
fresh history).  ``max_speculation`` bounds the staleness depth *k*;
``k=0`` disables the engine entirely and the driver takes its original
serial path bit-for-bit.

Speculation-validity policies (per suggest algorithm, discovered through a
``speculation_policy`` attribute on the unwrapped function):

- ``"independent"`` (``rand.suggest``): reads nothing from history —
  speculations are always valid.
- ``"tpe_quantile"`` (``tpe.suggest``): **hypothesis-exact branch
  prediction.**  A pending trial's parameter vector *x* is fully known
  while its objective runs; only its loss is not — and the loss enters
  the TPE fit solely through γ-split membership.  So the speculative
  suggest is fit against the hypothetical history in which every
  in-flight trial has completed into the *above* set (its known *x*
  joins g(x) with a worst-case loss; ``n_below`` is computed for the
  grown count; see ``DeviceHistory.hypothetical_append``).  When the
  real result does land above and the below-count is unchanged — the
  overwhelmingly common case, since the below set holds only the best
  ``min(ceil(γ·√N), LF)`` losses — the consumed suggestion equals the
  post-completion serial suggestion **bit-for-bit**.  Otherwise (the
  result ranks inside the below set, the below-count grew, or the trial
  errored out of existence) the speculation is re-issued against the
  now-complete history — also exact.  With ``max_speculation=1`` and a
  deterministic objective, the whole k=1 trajectory therefore
  reproduces the serial trajectory exactly; speculations deeper than
  the in-flight window (k≥2) additionally miss the not-yet-resolved
  intermediate suggestions and are consumed with the classic bounded
  staleness TPE tolerates by design.
- anything else: **strict** — the engine does not speculate at all.
  Every completed trial appends a loss, which would invalidate the
  speculation, so speculative work would be recomputed — and, for an
  algorithm with observable side effects, visibly double-invoke it —
  every single trial.  ``next_batch`` instead computes synchronously
  with the serial loop's exact seed protocol, which makes the engine
  safe to enable for arbitrary suggest algorithms: unknown algorithms
  get the serial trajectory, bit-for-bit.

Determinism: the engine draws exactly one seed from the driver's
``rstate`` per suggest call, in trial order — the same protocol as the
serial loop — and invalidation re-uses the speculation's original seed, so
a fixed ``rstate`` fixes the whole trajectory for any ``k``.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import deque
from functools import partial

import numpy as np

from .base import JOB_STATE_NEW, JOB_STATE_RUNNING
from .observability import SpeculationStats

logger = logging.getLogger(__name__)

# tpe.suggest defaults, used when the algo partial doesn't override them
# (kept in sync by tests/test_pipeline.py::test_policy_defaults_match_tpe)
_TPE_DEFAULTS = {"gamma": 0.25, "linear_forgetting": 25, "n_startup_jobs": 20}


def _unwrap(algo):
    """Peel functools.partial layers → (function, merged keywords)."""
    kw = {}
    fn = algo
    while isinstance(fn, partial):
        merged = dict(fn.keywords or {})
        merged.update(kw)
        kw = merged
        fn = fn.func
    return fn, kw


def _async_variant(algo):
    """The algo's non-blocking dispatch variant with the partial's
    keywords re-applied, or None when the algo doesn't provide one."""
    fn, kw = _unwrap(algo)
    afn = getattr(fn, "async_variant", None)
    if afn is None:
        return None
    return partial(afn, **kw) if kw else afn


def _policy_for(algo):
    """(policy_name, params) for the speculation-validity check."""
    fn, kw = _unwrap(algo)
    policy = getattr(fn, "speculation_policy", "strict")
    if policy == "tpe_quantile":
        if kw.get("trial_filter") is not None:
            # the algorithm computes its γ-split over the FILTERED
            # history, while the quantile check below reasons about the
            # full loss array — a filter would silently mis-predict
            # validity, so don't speculate at all
            return "strict", {}
        params = dict(_TPE_DEFAULTS)
        for key in params:
            if key not in kw:
                continue
            if key == "linear_forgetting":
                # None is MEANINGFUL to tpe.suggest (no n_below cap),
                # unlike the other keys where None would just crash the
                # algorithm — mirror its semantics exactly
                params[key] = kw[key]
            elif kw[key] is not None:
                params[key] = kw[key]
        return policy, params
    return policy, {}


def _n_below(n, gamma, lf):
    # mirrors tpe._suggest_device: ceil(gamma * sqrt(n)) capped at
    # linear_forgetting unless that is None (0 caps at 0)
    nb = int(np.ceil(gamma * np.sqrt(n)))
    if lf is not None:
        nb = min(nb, int(lf))
    return nb


class _Speculation:
    __slots__ = ("ids", "seed", "resolve", "snap")

    def __init__(self, ids, seed, resolve, snap):
        self.ids = ids
        self.seed = seed
        self.resolve = resolve
        self.snap = snap


class SpeculativeSuggestEngine:
    """Issues suggest calls ahead of objective completion, bounded by a
    staleness depth ``max_speculation``.

    The driver (``FMinIter``) uses two entry points:

    - :meth:`speculate` — called while an objective is running (or while
      an async backend is polling): reserves the next trial ids, draws the
      next seed, and launches the suggest program without blocking.
    - :meth:`next_batch` — called at enqueue time in place of the direct
      ``algo(...)`` call: validates pending speculations against the
      now-current history, re-issues any the γ-split shift invalidated,
      and returns ``(new_trials, new_ids)`` — resolving a speculative
      readback when one is available, computing synchronously otherwise.

    All device work in flight when an invalidation or :meth:`discard`
    happens is simply dropped (the resolver is never called); per-device
    program ordering makes that safe against subsequent history appends.
    """

    def __init__(self, algo, domain, trials, rstate, max_speculation=1,
                 stats=None, device_recovery=None):
        if max_speculation < 0:
            raise ValueError(f"max_speculation must be >= 0, got {max_speculation}")
        self.algo = algo
        self.domain = domain
        self.trials = trials
        self.rstate = rstate
        self.max_speculation = int(max_speculation)
        self.stats = stats if stats is not None else SpeculationStats()
        # optional hyperopt_tpu.resilience.device.DeviceRecovery: the
        # engine's SYNCHRONOUS suggest calls (a miss, or the recompute
        # after a failed speculative readback) run through it so an
        # XLA/TPU runtime error re-initializes and retries instead of
        # aborting the run; speculative launches stay unwrapped — their
        # failures are already degraded to the serial protocol by the
        # callers, and the recompute lands here anyway
        self.device_recovery = device_recovery
        self.policy, self.policy_params = _policy_for(algo)
        self._algo_async = _async_variant(algo)
        # The serial driver calls the engine from one thread, but the
        # async plane interleaves speculate() (main loop) with backend
        # dispatcher threads and future backends may prefetch from
        # worker callbacks — so the engine carries an explicit
        # two-level lock discipline, enforced statically by
        # hyperopt_tpu.analysis.race_lint (see docs/static_analysis.md):
        #
        # - ``_dispatch_lock`` (reentrant, coarse) serializes the
        #   compound schedule operations — speculate's check+draw+
        #   launch+append, next_batch's validate+pop+resolve, discard —
        #   so concurrent callers cannot overshoot max_speculation or
        #   interleave rstate draws (which would break the k=1
        #   bit-for-bit serial-trajectory guarantee).
        # - ``_pending_lock`` (fine) guards the queue state itself, so
        #   cheap inspections never wait behind a blocking readback.
        #
        # lock-order: _dispatch_lock < _pending_lock
        self._dispatch_lock = threading.RLock()
        self._pending_lock = threading.Lock()
        self._pending = deque()  # guarded-by: _pending_lock
        # (ids, seed) pairs whose speculative LAUNCH failed (device
        # error at dispatch): the serial protocol already consumed the
        # id allocation and the rstate draw, so they must be re-used —
        # not redrawn — by the next launch or synchronous suggest, or a
        # recovered run's trajectory diverges from the fault-free run.
        # Survives discard(): these are unlaunched protocol state, not
        # in-flight device work.
        self._spare = deque()  # guarded-by: _dispatch_lock

    # -- snapshot / validation ----------------------------------------
    def _snapshot(self):
        """Capture what the pending suggestion's validity depends on."""
        if self.policy == "independent":
            return ("independent",)
        hist = self.trials.history
        n = len(hist.losses)
        cv = getattr(hist, "content_version", None)
        if self.policy == "tpe_quantile":
            p = self.policy_params
            if len(self.trials.trials) < p["n_startup_jobs"] or n == 0:
                # the algo took its random-search startup path: valid as
                # long as it still would (the gate re-checks at validate)
                return ("startup",)
            nb = _n_below(n, p["gamma"], p["linear_forgetting"])
            if 1 <= nb <= n:
                losses = np.asarray(hist.losses, dtype=np.float64)
                thr = float(np.partition(losses, nb - 1)[nb - 1])
            else:
                thr = float("inf")
            # version counters are only comparable within ONE hist
            # object (tpe_device.sync documents the same invariant), so
            # counter-based snapshots pin the history's identity
            return ("quantile", n, nb, thr, cv, weakref.ref(hist))
        # strict policies never speculate: speculate() returns before any
        # launch, so no validity protocol exists (or is needed) for them
        raise AssertionError("strict speculation has no snapshot")

    def _still_valid(self, snap):
        kind = snap[0]
        if kind == "independent":
            return True
        hist = self.trials.history
        n_now = len(hist.losses)
        if kind == "startup":
            p = self.policy_params
            return len(self.trials.trials) < p["n_startup_jobs"] or n_now == 0
        if kind == "hyp":
            return self._hyp_still_valid(snap, hist, n_now)
        _, n0, nb0, thr, cv, hist_ref = snap
        if hist_ref() is not hist:
            # a swapped-in history restarts its version counters; the
            # snapshot's counters (and threshold) mean nothing against it
            return False
        # any non-append rewrite (delete, in-place loss edit) since the
        # snapshot invalidates unconditionally — the quantile shortcut
        # below only reasons about appended losses
        if cv is not None and getattr(hist, "last_nonappend_version", 0) > cv:
            return False
        if n_now == n0:
            return True
        if n_now < n0:
            return False
        p = self.policy_params
        if _n_below(n_now, p["gamma"], p["linear_forgetting"]) != nb0:
            return False
        new = np.asarray(hist.losses[n0:], dtype=np.float64)
        # strict <: the γ-split ranks by a STABLE argsort, so a tied loss
        # appended later ranks after the incumbent and the below set is
        # unchanged (matches tpe_device._loss_ranks semantics)
        return not bool(np.any(new < thr))

    def _hyp_still_valid(self, snap, hist, n_now):
        """Did every result the hypothesis bet on come true?

        The speculation was fit on ``n0`` real losses plus the
        hypothesized pending trials, with ``n_below`` = ``nb_fit`` for
        the grown count.  It still stands iff nothing rewrote history,
        no appended loss ranks inside the first ``nb_fit`` (stable f32
        ranking, matching the device's ``_loss_ranks``), the below-count
        the next fit would use equals ``nb_fit``, and no hypothesized
        trial died without a loss (its x sits in g(x) but the serial fit
        will never contain it).  Hypothesized trials merely still
        running keep the speculation valid — consuming it then is the
        async plane's fantasy mode; the serial driver always consumes
        after the completion, where these checks certify bit-for-bit
        equality with the serial suggestion."""
        _, n0, nb_fit, hyp_tids, cv, hist_ref = snap
        if hist_ref() is not hist:
            return False  # swapped-in history: counters not comparable
        if cv is not None and getattr(hist, "last_nonappend_version", 0) > cv:
            return False
        if n_now < n0:
            return False
        done_tids = {int(t) for t in hist.loss_tids[n0:]}
        hyp_set = set(hyp_tids)
        still_out = 0
        for t in self.trials._dynamic_trials:
            tid = int(t["tid"])
            if tid in hyp_set and tid not in done_tids:
                if t["state"] in (JOB_STATE_NEW, JOB_STATE_RUNNING):
                    still_out += 1
                else:
                    return False
        p = self.policy_params
        if _n_below(n_now + still_out, p["gamma"],
                    p["linear_forgetting"]) != nb_fit:
            return False
        if n_now > n0:
            losses = np.asarray(hist.losses[:n_now], dtype=np.float32)
            order = np.argsort(losses, kind="stable")  # NaN ranks last
            ranks = np.empty(n_now, np.int64)
            ranks[order] = np.arange(n_now)
            if np.any(ranks[n0:] < nb_fit):
                return False
        return True

    def _validate(self, exposed=False):
        """Re-issue every pending speculation the current history has
        invalidated (same ids, same seed, fresh history).  ``exposed``:
        the caller is on the driver's critical path (consume time), so
        re-issue launch cost must not be booked as hidden time."""
        with self._pending_lock:
            if not self._pending:
                return
            if all(self._still_valid(sp.snap) for sp in self._pending):
                return
            # the speculations were issued against successive rstate
            # draws in trial order; one stale γ-split invalidates them
            # all (each later speculation was fit on the same stale
            # history)
            stale = list(self._pending)
            self._pending.clear()
        self.stats.record_invalidation(len(stale))
        for j, sp in enumerate(stale):
            t0 = time.perf_counter()
            try:
                resolve, snap = self._launch_spec(sp.ids, sp.seed)
            except Exception as launch_err:
                # re-issue dispatch failed (device error): park this and
                # every later stale speculation's (ids, seed) in order —
                # the next launch or synchronous suggest re-uses them, so
                # the trajectory stays seed-transparent through the fault
                logger.exception(
                    "re-issue dispatch failed; falling back to "
                    "synchronous recompute"
                )
                if self.device_recovery is not None:
                    self.device_recovery.absorb(launch_err)
                for sp2 in stale[j:]:
                    # safe: _validate's only callers (speculate,
                    # next_batch) hold _dispatch_lock around the call
                    self._spare.append((sp2.ids, sp2.seed))  # lint: disable=RL301
                break
            with self._pending_lock:
                self._pending.append(
                    _Speculation(sp.ids, sp.seed, resolve, snap)
                )
            self.stats.record_dispatch(
                time.perf_counter() - t0, hypothesis=snap[0] == "hyp",
                exposed=exposed,
            )

    # -- dispatch ------------------------------------------------------
    def _call_algo_sync(self, ids, seed):
        """The serial protocol's exact algo call, under device recovery
        when the driver provided one."""
        if self.device_recovery is not None:
            return self.device_recovery.run(
                lambda: self.algo(ids, self.domain, self.trials, seed)
            )
        return self.algo(ids, self.domain, self.trials, seed)

    def _launch(self, ids, seed):
        if self._algo_async is not None:
            return self._algo_async(ids, self.domain, self.trials, seed)
        docs = self.algo(ids, self.domain, self.trials, seed)
        return lambda: docs

    def _launch_spec(self, ids, seed):
        """(resolver, validity snapshot) for one speculative suggest —
        with the lands-above hypothesis folded into the fit whenever the
        algorithm supports async dispatch and results are in flight."""
        if self.policy != "tpe_quantile":
            return self._launch(ids, seed), self._snapshot()
        p = self.policy_params
        hist = self.trials.history
        n0 = len(hist.losses)
        if len(self.trials.trials) < p["n_startup_jobs"] or n0 == 0:
            return self._launch(ids, seed), ("startup",)
        pending = [
            t for t in self.trials._dynamic_trials
            if t["state"] in (JOB_STATE_NEW, JOB_STATE_RUNNING)
        ]
        nb_fit = _n_below(
            n0 + len(pending), p["gamma"], p["linear_forgetting"]
        )
        # nb_fit <= n0: with every pending result hypothesized above, the
        # below set must fit inside the real losses (always true past
        # startup; degenerate tiny-history corners fall back to stale)
        if pending and self._algo_async is not None and nb_fit <= n0:
            cv = getattr(hist, "content_version", None)
            resolve = self._algo_async(
                ids, self.domain, self.trials, seed,
                pending=[t["misc"]["vals"] for t in pending],
            )
            snap = (
                "hyp", n0, nb_fit,
                tuple(int(t["tid"]) for t in pending), cv,
                weakref.ref(hist),
            )
            return resolve, snap
        return self._launch(ids, seed), self._snapshot()

    def speculate(self, batch_size=1, limit=None):
        """Launch up to ``max_speculation`` pending suggestions (each for
        ``batch_size`` fresh trial ids) without blocking.  Call while an
        objective is evaluating; the device computes in the background.

        ``limit`` caps pending speculations at the number of suggestions
        the driver will still consume this run, so the final trials of a
        bounded run don't launch device work (and burn trial ids) for
        suggestions past ``max_evals`` that nothing will ever read."""
        cap = self.max_speculation
        if limit is not None:
            cap = min(cap, max(int(limit), 0))
        if cap <= 0:
            return
        if self.policy == "strict":
            # every completed trial would invalidate a strict speculation
            # (see module docstring): don't burn the work, stay serial
            return
        with self._dispatch_lock:
            # the driver may have completed trials since the last refresh
            # (several NEW trials evaluated back-to-back, e.g.
            # points_to_evaluate warm starts): validation and the pending
            # scan below must see those losses, or a completed-but-
            # unsynced trial is neither in the history nor hypothesized
            # and a re-issued speculation silently loses its observation
            self.trials.refresh()
            self._validate()
            while True:
                with self._pending_lock:
                    if len(self._pending) >= cap:
                        break
                t0 = time.perf_counter()
                if self._spare:
                    # a previous launch failed after the draw: reuse its
                    # ids and seed (the serial protocol's exact next call)
                    ids, seed = self._spare.popleft()
                else:
                    ids = self.trials.new_trial_ids(batch_size)
                    self.trials.refresh()
                    seed = int(self.rstate.integers(2 ** 31 - 1))
                try:
                    resolve, snap = self._launch_spec(ids, seed)
                except Exception:
                    # dispatch failed (device error, compile OOM): park
                    # the consumed (ids, seed) for the next attempt so
                    # the trajectory stays seed-transparent, then let the
                    # caller degrade to the serial protocol
                    self._spare.appendleft((ids, seed))
                    raise
                with self._pending_lock:
                    self._pending.append(
                        _Speculation(ids, seed, resolve, snap)
                    )
                self.stats.record_dispatch(
                    time.perf_counter() - t0, hypothesis=snap[0] == "hyp"
                )

    # -- consumption ---------------------------------------------------
    def next_batch(self, n):
        """Trial docs + ids for the next ``n`` enqueue slots.

        Pending (validated) speculations are consumed first; any remainder
        is computed synchronously with a fresh seed — exactly one rstate
        draw per suggest call either way.  Returns ``(new_trials,
        new_ids)``; ``new_trials`` is None when the algorithm signalled a
        stop and nothing was produced."""
        with self._dispatch_lock:
            self._validate(exposed=True)
            docs, ids = [], []
            while True:
                with self._pending_lock:
                    if not self._pending or (
                        len(ids) + len(self._pending[0].ids) > n
                    ):
                        break
                    sp = self._pending.popleft()
                t0 = time.perf_counter()
                try:
                    out = sp.resolve()
                    self.stats.record_resolve(time.perf_counter() - t0)
                except Exception as readback_err:
                    # JAX defers device-side execution errors to the
                    # readback; a speculation-only failure must not abort
                    # a run that would have completed serially — drop
                    # every in-flight speculation and recompute this one
                    # synchronously with ITS ids and seed (the serial
                    # protocol's exact call)
                    logger.exception(
                        "speculative readback failed; recomputing "
                        "synchronously"
                    )
                    if self.device_recovery is not None:
                        self.device_recovery.absorb(readback_err)
                    self.discard()
                    t1 = time.perf_counter()
                    out = self._call_algo_sync(sp.ids, sp.seed)
                    self.stats.record_sync(time.perf_counter() - t1)
                if out is None:
                    return (docs if docs else None), ids
                docs.extend(out)
                ids.extend(sp.ids)
            rem = n - len(ids)
            while rem > 0:
                if self._spare and len(self._spare[0][0]) <= rem:
                    # a launch-failed speculation already consumed these
                    # ids and this seed — the serial protocol's exact
                    # next call is to re-use them synchronously
                    fresh, seed = self._spare.popleft()
                else:
                    fresh = self.trials.new_trial_ids(rem)
                    self.trials.refresh()
                    seed = int(self.rstate.integers(2 ** 31 - 1))
                t0 = time.perf_counter()
                out = self._call_algo_sync(fresh, seed)
                self.stats.record_sync(time.perf_counter() - t0)
                if out is None:
                    return (docs if docs else None), ids + fresh
                docs.extend(out)
                ids.extend(fresh)
                rem = n - len(ids)
            return docs, ids

    def discard(self):
        """Drop every pending speculation (in-flight device work is
        abandoned, never read).  Used when the run stops or an objective
        exception propagates mid-speculation."""
        with self._dispatch_lock:
            with self._pending_lock:
                n = len(self._pending)
                self._pending.clear()
        if n:
            self.stats.record_discard(n)
