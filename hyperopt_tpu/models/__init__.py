"""Benchmark objectives and meta-model artifacts.

``domains`` is the benchmark-objective zoo (the reference ships it as
``hyperopt/tests/test_domains.py``; here it is a library module because the
benchmarks double as conformance + perf configs, see BASELINE.md).
``atpe_models`` holds the ATPE meta-model artifacts/heuristics.
"""

from . import domains

__all__ = ["domains"]
