"""Benchmark objective zoo.

Reference parity (SURVEY.md §4): ``hyperopt/tests/test_domains.py`` —
``quadratic1``, ``q1_lognormal``, ``q1_choice``, ``n1``, ``gauss_wave``,
``gauss_wave2``, ``distractor``, ``branin``, ``many_dists`` — each a
(space, loss) pair; test suites parametrize over them, and BASELINE.md's
conformance configs (Branin-2D, Hartmann-6D) live here too.

Each domain is a :class:`BenchDomain` with a ``space``, an objective
``fn(config) -> loss``, and a ``quality_threshold``: the loss an optimizer
should reach within ``quality_evals`` trials (the reference's
"optimization-quality thresholds per benchmark domain" test pattern —
robust to RNG/backend change, unlike bitwise asserts).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable

import numpy as np

from .. import hp


@dataclass
class BenchDomain:
    name: str
    space: object
    fn: Callable
    quality_threshold: float  # best loss an optimizer should reach ...
    quality_evals: int        # ... within this many trials
    fmin: float = float("nan")  # known global minimum (if any)


def _quadratic1():
    space = {"x": hp.uniform("x", -5, 5)}
    return BenchDomain(
        "quadratic1", space, lambda c: (c["x"] - 3) ** 2,
        quality_threshold=0.2, quality_evals=50, fmin=0.0,
    )


def _q1_lognormal():
    space = {"x": hp.qlognormal("x", 0, 2, 1)}
    return BenchDomain(
        "q1_lognormal", space,
        lambda c: max(c["x"], 0) ** 2 * 1e-2 + abs(c["x"] - 3) * 0.1,
        quality_threshold=0.5, quality_evals=50,
    )


def _q1_choice():
    space = hp.choice(
        "mode",
        [
            {"use": "left", "x": hp.uniform("xl", -10, 0)},
            {"use": "right", "x": hp.uniform("xr", 0, 10)},
        ],
    )
    def fn(c):
        return (c["x"] - 3) ** 2
    return BenchDomain("q1_choice", space, fn, quality_threshold=0.5, quality_evals=80, fmin=0.0)


def _n1():
    space = {"x": hp.normal("x", 0, 1)}
    return BenchDomain(
        "n1", space, lambda c: c["x"], quality_threshold=-1.5, quality_evals=60
    )


def _gauss_wave():
    space = {"x": hp.uniform("x", -20, 20)}
    def fn(c):
        x = c["x"]
        return -math.exp(-((x / 10.0) ** 2)) * math.cos(x)
    return BenchDomain("gauss_wave", space, fn, quality_threshold=-0.9, quality_evals=80, fmin=-1.0)


def _gauss_wave2():
    space = {
        "curve": hp.choice("curve", [{"kind": "flat"}, {"kind": "wave", "amp": hp.uniform("amp", 0.5, 2.0)}]),
        "x": hp.uniform("x", -20, 20),
    }
    def fn(c):
        x = c["x"]
        base = -math.exp(-((x / 10.0) ** 2))
        if c["curve"]["kind"] == "wave":
            return base * math.cos(x) * c["curve"]["amp"]
        return base * 0.5
    return BenchDomain("gauss_wave2", space, fn, quality_threshold=-1.0, quality_evals=120)


def _distractor():
    # global optimum in a narrow basin at x=-5; broad distractor basin at x=5
    space = {"x": hp.uniform("x", -15, 15)}
    def fn(c):
        x = c["x"]
        return -(1.2 * math.exp(-((x + 5.0) ** 2) / 0.5) + math.exp(-((x - 5.0) ** 2) / 18.0))
    return BenchDomain("distractor", space, fn, quality_threshold=-0.9, quality_evals=150, fmin=-1.2)


def _branin():
    # Branin-Hoo: global minimum 0.397887 at three points
    space = {"x": hp.uniform("x", -5.0, 10.0), "y": hp.uniform("y", 0.0, 15.0)}
    def fn(c):
        x, y = c["x"], c["y"]
        a, b, cc = 1.0, 5.1 / (4 * math.pi ** 2), 5.0 / math.pi
        r, s, t = 6.0, 10.0, 1.0 / (8 * math.pi)
        return a * (y - b * x ** 2 + cc * x - r) ** 2 + s * (1 - t) * math.cos(x) + s
    return BenchDomain("branin", space, fn, quality_threshold=1.0, quality_evals=100, fmin=0.397887)


_H6_ALPHA = np.array([1.0, 1.2, 3.0, 3.2])
_H6_A = np.array(
    [
        [10, 3, 17, 3.5, 1.7, 8],
        [0.05, 10, 17, 0.1, 8, 14],
        [3, 3.5, 1.7, 10, 17, 8],
        [17, 8, 0.05, 10, 0.1, 14],
    ]
)
_H6_P = 1e-4 * np.array(
    [
        [1312, 1696, 5569, 124, 8283, 5886],
        [2329, 4135, 8307, 3736, 1004, 9991],
        [2348, 1451, 3522, 2883, 3047, 6650],
        [4047, 8828, 8732, 5743, 1091, 381],
    ]
)


def _hartmann6():
    # 6-D Hartmann: global minimum -3.32237
    space = {f"x{i}": hp.uniform(f"x{i}", 0.0, 1.0) for i in range(6)}
    def fn(c):
        x = np.array([c[f"x{i}"] for i in range(6)])
        inner = np.sum(_H6_A * (x - _H6_P) ** 2, axis=1)
        return float(-np.sum(_H6_ALPHA * np.exp(-inner)))
    return BenchDomain("hartmann6", space, fn, quality_threshold=-2.5, quality_evals=150, fmin=-3.32237)


def _many_dists():
    space = {
        "a": hp.choice("a", [0, 1, 2]),
        "b": hp.randint("b", 10),
        "c": hp.uniform("c", 4, 7),
        "d": hp.loguniform("d", -2, 0),
        "e": hp.quniform("e", 0, 10, 3),
        "f": hp.qloguniform("f", 0, 3, 2),
        "g": hp.normal("g", 4, 7),
        "h": hp.lognormal("h", -2, 2),
        "i": hp.qnormal("i", 0, 10, 2),
        "j": hp.qlognormal("j", 0, 2, 1),
        "k": hp.pchoice("k", [(0.1, 0), (0.9, 1)]),
        "z": hp.uniform("z", -5, 5),
    }
    def fn(c):
        return float(c["z"] ** 2 + 0.01 * (c["c"] + c["d"] + c["a"]))
    return BenchDomain("many_dists", space, fn, quality_threshold=0.5, quality_evals=80)


def _nested_arch():
    """Deep conditional space (ML-architecture shaped): a top-level
    branch choice where one branch carries an inner choice — exercises
    multi-level activity masks the way the reference's conditional
    test spaces do (hyperopt/tests/test_domains.py many_dists/choice)."""
    space = hp.choice(
        "arch",
        [
            {
                "kind": 0,
                "lr": hp.loguniform("mlp_lr", -6.0, 0.0),
                "width": hp.quniform("mlp_width", 16, 128, 16),
            },
            {
                "kind": 1,
                "lr": hp.loguniform("cnn_lr", -6.0, 0.0),
                "block": hp.choice(
                    "cnn_block",
                    [
                        {"b": 0, "filters": hp.quniform("f_a", 8, 64, 8)},
                        {"b": 1, "depth": hp.quniform("f_b", 1, 4, 1)},
                    ],
                ),
            },
        ],
    )

    def fn(c):
        # optimum: cnn branch, block b=0, lr≈e^-3, filters≈40
        lr_term = (math.log(c["lr"]) + 3.0) ** 2
        if c["kind"] == 0:
            return 1.0 + lr_term + abs(c["width"] - 64) / 64.0
        if c["block"]["b"] == 0:
            return lr_term + abs(c["block"]["filters"] - 40) / 40.0
        return 0.5 + lr_term + abs(c["block"]["depth"] - 2) / 2.0

    return BenchDomain(
        "nested_arch", space, fn, quality_threshold=0.5, quality_evals=120, fmin=0.0
    )


def _rosen10():
    """10-D Rosenbrock on [-2, 2]^10 — the zoo's high-dimensional
    continuous domain (history_per_param stays small even at many
    trials, the regime the ATPE featurizer must see in training)."""
    space = {f"r{i}": hp.uniform(f"r{i}", -2.0, 2.0) for i in range(10)}

    def fn(c):
        x = np.array([c[f"r{i}"] for i in range(10)])
        return float(
            np.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2 + (1.0 - x[:-1]) ** 2)
        )

    return BenchDomain(
        "rosen10", space, fn, quality_threshold=900.0, quality_evals=150, fmin=0.0
    )


def _make_all():
    ds = [
        _quadratic1(),
        _q1_lognormal(),
        _q1_choice(),
        _n1(),
        _gauss_wave(),
        _gauss_wave2(),
        _distractor(),
        _branin(),
        _hartmann6(),
        _many_dists(),
        _nested_arch(),
        _rosen10(),
    ]
    return {d.name: d for d in ds}


DOMAINS = _make_all()


def get(name: str) -> BenchDomain:
    return DOMAINS[name]
