"""Offline ATPE meta-model training (the reference's atpe_models pipeline).

Reference parity (SURVEY.md §2 #15): the reference ships pretrained
LightGBM artifacts (``hyperopt/atpe_models/scaling_model.json``,
``model-<target>.txt``) produced by an offline sweep over benchmark
optimization problems.  That corpus is unobtainable offline and LightGBM
is absent, so this trainer regenerates the same artifact *shape* from
this repo's own domain zoo with sklearn gradient boosting:

1. For each (domain, seed): run a base TPE optimization and snapshot the
   trials at checkpoints — each snapshot is one "optimization state".
2. For each state: continue the run under many sampled TPE meta-configs
   (γ, n_EI_candidates, prior_weight, secondary-cutoff locks,
   result-filtering mode/multiplier) for a fixed budget and record the
   final best loss.
3. Label each state with the meta-config statistics of its top-quartile
   continuations (majority vote for the filtering mode), featurize the
   state with ``ATPEOptimizer.compute_features``, and fit one model per
   ``META_TARGETS`` entry (classifier for the mode, regressors else;
   n_EI_candidates in log2).
4. Write ``scaling_model.json`` (feature normalization + transforms +
   provenance) and ``model-<target>.pkl`` artifacts.

Run:  python -m hyperopt_tpu.models.train_atpe [--quick] [--out DIR]
(CPU is fine — spaces are tiny; jit caches make the sweep minutes, not
hours.  --quick shrinks everything for CI smoke.)
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import pickle
import sys
import time
from functools import partial

import numpy as np

# Training domains: the zoo minus HELD_OUT.  The held-out pair is never
# seen by the trainer — one low-dim continuous domain and one conditional
# domain — so tests/test_atpe.py can check the artifacts GENERALIZE
# instead of scoring them on their own training data (VERDICT r4 #3).
HELD_OUT = ("branin", "q1_choice")
DEFAULT_DOMAINS = (
    "quadratic1",
    "q1_lognormal",
    "n1",
    "gauss_wave",
    "gauss_wave2",
    "distractor",
    "hartmann6",
    "many_dists",
    "nested_arch",
    "rosen10",
)

GRID = {
    "gamma": (0.15, 0.25, 0.40),
    "n_EI_candidates": (24, 256),
    "prior_weight": (0.5, 1.0),
    "secondary_cutoff": (0.0, 0.25),
    "result_filtering": (
        ("none", 1.0),
        ("age", 0.5),
        ("loss_rank", 0.6),
        ("random", 0.7),
    ),
}


def sample_configs(n, rng):
    """n distinct meta-configs sampled uniformly from the grid product."""
    seen, out = set(), []
    while len(out) < n:
        cfg = {
            "gamma": rng.choice(GRID["gamma"]),
            "n_EI_candidates": int(rng.choice(GRID["n_EI_candidates"])),
            "prior_weight": rng.choice(GRID["prior_weight"]),
            "secondary_cutoff": rng.choice(GRID["secondary_cutoff"]),
        }
        mode, mult = GRID["result_filtering"][rng.integers(len(GRID["result_filtering"]))]
        cfg["result_filtering_mode"] = mode
        cfg["result_filtering_multiplier"] = mult
        key = tuple(sorted((k, str(v)) for k, v in cfg.items()))
        if key in seen:
            if len(seen) >= 3 * 2 * 2 * 2 * 4:  # grid exhausted
                break
            continue
        seen.add(key)
        out.append(cfg)
    return out


def _run_base(domain, seed, n_trials):
    from hyperopt_tpu import Trials, fmin, tpe

    trials = Trials()
    fmin(
        domain.fn,
        domain.space,
        algo=tpe.suggest,
        max_evals=n_trials,
        trials=trials,
        rstate=np.random.default_rng(seed),
        show_progressbar=False,
        verbose=False,
    )
    return trials


def _continue_with(domain, snapshot_docs, cfg, extra_evals, seed):
    """Continue a snapshotted run under one meta-config; return final best."""
    from hyperopt_tpu import Trials, fmin, tpe
    from hyperopt_tpu.base import Domain, trials_from_docs
    from ..algos import atpe as atpe_mod

    trials = trials_from_docs(copy.deepcopy(snapshot_docs))
    dom = Domain(domain.fn, domain.space)

    # secondary-cutoff locks chosen once at the checkpoint (the per-call
    # re-choice in atpe.suggest averages to the same behavior)
    param_locks = None
    if cfg["secondary_cutoff"] > 0:
        opt = atpe_mod.ATPEOptimizer()
        _, per_param_corr = opt.compute_features(dom, trials)
        rng = np.random.default_rng(seed + 10_000)
        locked = opt.choose_locks(
            per_param_corr,
            cfg["secondary_cutoff"],
            rng,
            exclude=atpe_mod.ATPEOptimizer.condition_driver_labels(dom),
        )
        param_locks = atpe_mod.locks_from_labels(dom, trials, locked) or None

    trial_filter = atpe_mod.build_trial_filter(
        cfg["result_filtering_mode"], cfg["result_filtering_multiplier"]
    )
    algo = partial(
        tpe.suggest,
        gamma=cfg["gamma"],
        n_EI_candidates=cfg["n_EI_candidates"],
        prior_weight=cfg["prior_weight"],
        param_locks=param_locks,
        trial_filter=trial_filter,
    )
    n0 = len(trials.trials)
    fmin(
        domain.fn,
        domain.space,
        algo=algo,
        max_evals=n0 + extra_evals,
        trials=trials,
        rstate=np.random.default_rng(seed + 20_000),
        show_progressbar=False,
        verbose=False,
    )
    losses = [l for l in trials.losses() if l is not None]
    return float(np.min(losses)) if losses else float("inf")


def label_results(results):
    """State labels from (final_best, cfg) continuation results.

    Top-quartile majority voting (the round-4 scheme) was measurably
    noisy: with ~20 configs per state the filtering-mode majority was
    close to uniform chance, and the shipped models learned to predict
    ``random`` filtering — i.e. throw away a third of the history —
    which LOST to the heuristic on held-out domains.  Instead:

    - continuous targets: rank-weighted mean over ALL configs
      (``w ∝ exp(−rank/(n/4))`` — smooth, emphasizes winners, uses every
      observation instead of the top 5);
    - filtering mode: the mode whose configs' MEDIAN final best is
      lowest (an entire-group comparison, robust to one lucky draw);
    - multiplier: rank-weighted mean within the winning mode (1.0 for
      ``none``, where it is meaningless).

    The raw results ride along under ``_results`` so future re-labelings
    can rerun from pickled shards without re-sweeping.
    """
    if not results:
        raise ValueError("label_results: empty continuation results")
    results = sorted(results, key=lambda r: r[0])
    n = len(results)
    w = np.exp(-np.arange(n) / max(1.0, n / 4.0))
    w = w / w.sum()

    def wmean(key, transform=lambda v: v):
        return float(sum(
            wi * transform(cfg[key]) for wi, (_, cfg) in zip(w, results)
        ))

    by_mode = {}
    for best, cfg in results:
        by_mode.setdefault(cfg["result_filtering_mode"], []).append(best)
    mode = min(by_mode, key=lambda m: float(np.median(by_mode[m])))
    if mode == "none":
        mult = 1.0
    else:
        mw = np.array(
            [wi for wi, (_, c) in zip(w, results)
             if c["result_filtering_mode"] == mode]
        )
        mv = [c["result_filtering_multiplier"] for _, c in results
              if c["result_filtering_mode"] == mode]
        mult = float(np.average(mv, weights=mw)) if mw.sum() > 0 else 1.0
    return {
        "gamma": wmean("gamma"),
        "n_EI_candidates": wmean("n_EI_candidates", np.log2),
        "prior_weight": wmean("prior_weight"),
        "secondary_cutoff": wmean("secondary_cutoff"),
        "result_filtering_mode": mode,
        "result_filtering_multiplier": mult,
        "_results": [(b, dict(c)) for b, c in results],
    }


def relabel_rows(rows):
    """Recompute labels from the raw ``_results`` stored in each row
    (no-op for legacy rows without them)."""
    out = []
    for feats, labels in rows:
        raw = labels.get("_results")
        out.append((feats, label_results(raw)) if raw else (feats, labels))
    return out


def build_corpus(domains, seeds, checkpoints, n_configs, cont_evals, log=print):
    from hyperopt_tpu.base import Domain
    from . import domains as zoo
    from ..algos import atpe as atpe_mod

    rng = np.random.default_rng(0)
    configs = sample_configs(n_configs, rng)
    rows = []  # (features dict, labels dict)
    t0 = time.time()
    for dname in domains:
        domain = zoo.get(dname)
        for seed in seeds:
            base = _run_base(domain, seed, max(checkpoints))
            docs = base.trials
            for ckpt in checkpoints:
                snapshot = [d for d in docs if d["tid"] < ckpt]
                if len(snapshot) < 10:
                    continue
                dom = Domain(domain.fn, domain.space)
                from hyperopt_tpu.base import trials_from_docs

                snap_trials = trials_from_docs(copy.deepcopy(snapshot))
                opt = atpe_mod.ATPEOptimizer()
                feats, _ = opt.compute_features(dom, snap_trials)
                feats["_domain"] = dname  # provenance only (not a feature)

                results = []
                for ci, cfg in enumerate(configs):
                    best = _continue_with(
                        domain, snapshot, cfg, cont_evals, seed * 1000 + ci
                    )
                    results.append((best, cfg))
                labels = label_results(results)
                rows.append((feats, labels))
                log(
                    f"  state {dname}/s{seed}/n{ckpt}: "
                    f"{len(results)} configs, best={results[0][0]:.4g}, "
                    f"labels γ={labels['gamma']:.2f} "
                    f"mode={labels['result_filtering_mode']} "
                    f"[{time.time()-t0:.0f}s]"
                )
    return rows


def save_rows(rows, path):
    """Pickle one corpus shard (list of (features, labels) rows) — lets
    the hours-long sweep run as independent per-domain processes and
    survive interruptions; merge with ``--fit-from``.  Atomic replace:
    an interruption mid-save keeps the previous shard intact instead of
    tearing hours of sweep output."""
    from ..checkpoint import atomic_pickle_dump

    atomic_pickle_dump(rows, path)


def load_rows(paths):
    rows = []
    for p in paths:
        with open(p, "rb") as f:
            rows.extend(pickle.load(f))
    return rows


# A meta-model must PAY RENT to override the heuristic: it ships only if
# grouped-by-domain cross-validation shows genuine cross-domain skill.
# With a small corpus most targets have none (their labels are dominated
# by continuation noise + a global mean, and a global-mean policy loses
# to the tuned heuristic) — those targets stay on the heuristic rules.
# As the corpus grows, targets clear the bar one by one.  R² is measured
# against the grouped-CV mean predictor; the classifier bar is majority
# accuracy + margin.
CV_R2_MIN = 0.05
CV_ACC_MARGIN = 0.03


def fit_models(rows, log=print):
    from sklearn.ensemble import (
        GradientBoostingClassifier,
        GradientBoostingRegressor,
    )
    from sklearn.model_selection import GroupKFold

    from ..algos.atpe import FEATURE_NAMES, META_TARGETS

    X = np.array([[f[k] for k in FEATURE_NAMES] for f, _ in rows])
    mu, sd = X.mean(axis=0), X.std(axis=0)
    Xn = (X - mu) / np.where(sd > 0, sd, 1.0)
    missing = sum(1 for f, _ in rows if "_domain" not in f)
    if missing:
        # without domain provenance, GroupKFold degenerates to per-row
        # KFold and the skill gate measures in-distribution recall — the
        # exact failure it exists to prevent.  Legacy shards must be
        # re-swept, not silently accepted.
        raise ValueError(
            f"fit_models: {missing}/{len(rows)} rows lack '_domain' "
            "provenance; rebuild those shards (grouped CV gating needs it)"
        )
    groups = np.array([f["_domain"] for f, _ in rows])
    n_groups = len(set(groups))

    def make(target):
        if target == "result_filtering_mode":
            return GradientBoostingClassifier(
                n_estimators=60, max_depth=2, random_state=0
            )
        return GradientBoostingRegressor(
            n_estimators=60, max_depth=2, random_state=0
        )

    models = {}
    cv_scores = {}
    active = []
    for target in META_TARGETS:
        y = [lab[target] for _, lab in rows]
        is_clf = target == "result_filtering_mode"
        if is_clf:
            y = np.asarray(y)
            if len(set(y.tolist())) < 2:
                cv_scores[target] = None  # constant class: nothing to learn
                continue
        else:
            y = np.asarray(y, dtype=float)

        # grouped CV: every fold predicts DOMAINS it never saw — the same
        # generalization the held-out gate demands
        if n_groups >= 3:
            cv = GroupKFold(n_splits=min(5, n_groups))
            err = base_err = 0.0
            hits = base_hits = 0
            for tr, te in cv.split(Xn, y, groups):
                if is_clf and len(np.unique(y[tr])) < 2:
                    # a fold whose train split is single-class (labels
                    # correlate with domain): that class IS the fold's
                    # prediction — same as the majority baseline
                    pred = np.full(len(te), y[tr][0])
                else:
                    m = make(target)
                    m.fit(Xn[tr], y[tr])
                    pred = m.predict(Xn[te])
                if is_clf:
                    vals, counts = np.unique(y[tr], return_counts=True)
                    majority = vals[np.argmax(counts)]
                    hits += int(np.sum(pred == y[te]))
                    base_hits += int(np.sum(y[te] == majority))
                else:
                    err += float(np.sum((pred - y[te]) ** 2))
                    base_err += float(np.sum((y[te] - y[tr].mean()) ** 2))
            if is_clf:
                score = (hits - base_hits) / len(y)
                keep = score > CV_ACC_MARGIN
            else:
                score = 1.0 - err / max(base_err, 1e-12)
                keep = score > CV_R2_MIN
        else:
            score, keep = None, True  # tiny/smoke corpora: no gating basis
        cv_scores[target] = None if score is None else round(float(score), 4)
        log(f"  fit {target}: cv_skill={cv_scores[target]} -> "
            f"{'ACTIVE' if keep else 'heuristic (model shipped, inactive)'}")
        # the model is always fitted and shipped (reference artifact
        # shape: one file per target); whether it OVERRIDES the heuristic
        # at suggest time is the evidence-gated active_targets list below
        m = make(target)
        m.fit(Xn, y)
        models[target] = m
        if keep:
            active.append(target)

    scaling = {
        "mean": {k: float(m_) for k, m_ in zip(FEATURE_NAMES, mu)},
        "std": {k: float(s) for k, s in zip(FEATURE_NAMES, sd)},
        "transforms": {"n_EI_candidates": "log2"},
        "corpus_rows": len(rows),
        "cv_skill": cv_scores,
        "active_targets": active,
    }
    return models, scaling


def _held_out_regret(models, scaling, seeds=(0, 1, 2), max_evals=40, log=print):
    # seeds MUST cover the set tests/test_atpe.py's held-out gate runs —
    # a narrower validation here would let an artifact ship that the
    # deterministic CI gate then rejects
    """Validation on the HELD_OUT domains (never in the corpus): run
    artifact-driven ATPE vs the heuristic and report the mean normalized
    regret difference (negative = artifacts better).  Returned in the
    scaling provenance so a regression is visible in the committed
    artifact itself."""
    from functools import partial

    from hyperopt_tpu import Trials, fmin
    from . import domains as zoo
    from ..algos import atpe as atpe_mod

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        write_artifacts(models, dict(scaling), td)
        diffs = []
        for dname in HELD_OUT:
            d = zoo.get(dname)
            for seed in seeds:
                finals = {}
                for kind, mdir in (("artifact", td), ("heuristic", "")):
                    trials = Trials()
                    fmin(
                        d.fn, d.space,
                        algo=partial(atpe_mod.suggest, model_dir=mdir),
                        max_evals=max_evals, trials=trials,
                        rstate=np.random.default_rng(seed),
                        show_progressbar=False, verbose=False,
                    )
                    finals[kind] = min(
                        l for l in trials.losses() if l is not None
                    )
                scale = abs(finals["heuristic"]) + 0.1
                diff = (finals["artifact"] - finals["heuristic"]) / scale
                diffs.append(diff)
                log(f"  held-out {dname}/s{seed}: artifact={finals['artifact']:.4g} "
                    f"heuristic={finals['heuristic']:.4g} diff={diff:+.3f}")
        return float(np.mean(diffs))


def write_artifacts(models, scaling, out_dir):
    # atomic replaces: a sweep interrupted mid-write must never leave a
    # torn artifact that the ATPE suggest path would then unpickle
    from ..parallel.file_trials import _atomic_write

    os.makedirs(out_dir, exist_ok=True)
    _atomic_write(
        os.path.join(out_dir, "scaling_model.json"),
        json.dumps(scaling, indent=1, sort_keys=True).encode(),
    )
    for target, model in models.items():
        _atomic_write(
            os.path.join(out_dir, f"model-{target}.pkl"),
            pickle.dumps(model),
        )


def _fit_validate_write(rows, out):
    """Fit → held-out validation → write, with provenance — the ONE
    artifact-writing sequence (both the direct path and --fit-from go
    through it, so shipped artifacts always carry provenance and a
    held-out score)."""
    if not rows:
        print("train_atpe: empty corpus, nothing written", file=sys.stderr)
        return 1
    rows = relabel_rows(rows)  # idempotent; upgrades shards on scheme changes
    models, scaling = fit_models(rows)
    held = _held_out_regret(models, scaling)
    scaling["provenance"] = {
        "train_domains": sorted(
            {f.get("_domain", "?") for f, _ in rows}
        ),
        "held_out_domains": list(HELD_OUT),
        "held_out_mean_regret_diff": held,
    }
    write_artifacts(models, scaling, out)
    print(
        f"train_atpe: wrote {len(models)} models + scaling to {out} "
        f"(corpus_rows={scaling['corpus_rows']}, "
        f"held_out_mean_regret_diff={held:+.3f})"
    )
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default=None, help="artifact directory")
    ap.add_argument("--quick", action="store_true", help="tiny CI-smoke corpus")
    ap.add_argument("--domains", nargs="*", default=None)
    ap.add_argument("--seeds", type=int, default=2)
    ap.add_argument(
        "--seed-offset", type=int, default=0,
        help="first seed (shards of one corpus use disjoint seed ranges)",
    )
    ap.add_argument("--configs", type=int, default=32)
    ap.add_argument("--cont-evals", type=int, default=15)
    ap.add_argument(
        "--checkpoints", type=int, nargs="*", default=None,
        help="snapshot sizes (default 20 45)",
    )
    ap.add_argument(
        "--rows-out", default=None,
        help="build the corpus shard, pickle the rows here, and exit "
        "(no model fitting)",
    )
    ap.add_argument(
        "--fit-from", nargs="*", default=None,
        help="skip corpus building; load row pickles, fit, validate on "
        "the held-out domains, and write artifacts",
    )
    ap.add_argument(
        "--tpu", action="store_true",
        help="allow the TPU backend (default forces CPU: the sweep is "
        "thousands of tiny-history suggests, where per-call dispatch "
        "latency dominates any device win)",
    )
    args = ap.parse_args(argv)

    if not args.tpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from ..algos.atpe import DEFAULT_MODEL_DIR

    out = args.out or DEFAULT_MODEL_DIR
    if args.quick:
        domains = args.domains or ["quadratic1", "gauss_wave2"]
        seeds, checkpoints = [0], (20,)
        n_configs, cont = 6, 6
    else:
        domains = args.domains or list(DEFAULT_DOMAINS)
        seeds = list(range(args.seed_offset, args.seed_offset + args.seeds))
        checkpoints = tuple(args.checkpoints or (20, 45))
        n_configs, cont = args.configs, args.cont_evals

    if args.fit_from:
        rows = load_rows(args.fit_from)
        print(f"train_atpe: fitting from {len(args.fit_from)} shards, "
              f"{len(rows)} rows")
        return _fit_validate_write(rows, out)

    print(
        f"train_atpe: {len(domains)} domains x seeds {seeds[0]}..{seeds[-1]} x "
        f"{len(checkpoints)} checkpoints x {n_configs} configs "
        f"x {cont} continuation evals -> {args.rows_out or out}"
    )
    rows = build_corpus(domains, seeds, checkpoints, n_configs, cont)
    if not rows:
        print("train_atpe: empty corpus, nothing written", file=sys.stderr)
        return 1
    if args.rows_out:
        save_rows(rows, args.rows_out)
        print(f"train_atpe: saved {len(rows)} rows to {args.rows_out}")
        return 0
    return _fit_validate_write(rows, out)


if __name__ == "__main__":
    sys.exit(main())
