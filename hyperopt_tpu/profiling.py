"""Roofline-aware device performance observability.

``BENCH_TPU.json`` quotes 1.44% MFU against the MXU bf16 peak — a number
that *sounds* like a 70x kernel-speed bug, but the EI scorer is a
logsumexp-dominated kernel whose XLA form materializes an O(C x K)
component matrix: at production shapes it can be **bandwidth-bound**, in
which case the MXU peak is the wrong ceiling and the right question is
"what fraction of HBM bandwidth does it achieve?".  Nobody could answer
that, because no layer measured bytes moved.  This module is that layer:

- a per-program **cost model**: FLOPs *and* bytes-moved for every fused
  suggest program signature, from XLA's own
  ``jit(...).lower(...).compile().cost_analysis()`` when available
  (:func:`xla_cost`) and from an analytical per-family model otherwise
  (:func:`analytical_cost` — the always-on default: it is arithmetic on
  shapes, never a second compile on the serving path);
- **roofline attribution** (:func:`roofline`): arithmetic intensity vs
  the ridge point decides which ceiling *binds* each dispatch — HBM
  bandwidth or peak FLOP/s — and ``roofline_pct`` is the fraction of
  that *binding* ceiling achieved, so "1.44% MFU" becomes either "3% of
  a roofline it is far from" or "80% of the bandwidth bound it is at";
- a :class:`DeviceProfiler` observer hooked on
  ``tpe_device._suggest_observers``: every dispatch records device
  time, achieved GB/s, achieved TFLOP/s, binding ceiling, roofline_pct,
  and live-buffer bytes into an
  :class:`~hyperopt_tpu.observability.DeviceStats` (exported as
  Prometheus gauges on the service ``/metrics``, attached as attrs on
  the tracing layer's ``device.dispatch`` spans);
- an opt-in bounded :class:`ProfileCapture` around ``jax.profiler``
  (``--profile-dir``, N dispatches) for TensorBoard/Perfetto deep
  dives.

Timing caveat (same as bench.py): device intervals are host-observed
(launch -> blocking readback).  On the synchronous suggest and service
paths the readback is immediate so the interval is tight; a speculative
dispatch whose resolver is called late reports the wait separately
(``wait_s``) and its busy time as launch + readback only.
"""

from __future__ import annotations

import logging
import os
import threading

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------
# Hardware ceilings
# ---------------------------------------------------------------------

# v5e: 197 TFLOP/s bf16 MXU peak (bench.py reports MFU against this,
# i.e. conservatively low for the f32 paths) and 819 GB/s HBM bandwidth.
TPU_PEAK_TFLOPS = 197.0
TPU_PEAK_HBM_GBPS = 819.0

# Nominal single-socket CPU ceilings so CPU-mode artifacts (the CI
# smoke's DEVICE_PROFILE.json) still carry self-consistent, NON-NULL
# roofline attribution.  Order-of-magnitude placeholders, flagged by
# ``source: "nominal_cpu"`` — never compare absolute CPU roofline_pct
# against a TPU capture.
CPU_PEAK_TFLOPS = 0.2
CPU_PEAK_DRAM_GBPS = 25.0


def platform_peaks(platform: str) -> dict:
    """The {peak_tflops, peak_hbm_GBps, ridge_ai, source} ceiling set
    for ``platform`` ("tpu"/"cpu"/...).  Env overrides
    ``HYPEROPT_TPU_PEAK_TFLOPS`` / ``HYPEROPT_TPU_PEAK_HBM_GBPS`` pin
    other chip generations without a code change.

    ``ridge_ai`` is the roofline ridge point in FLOPs/byte: programs
    below it cannot reach the FLOP peak no matter how good the kernel —
    HBM bandwidth binds them.
    """
    if platform == "tpu":
        peak_tflops, peak_bw = TPU_PEAK_TFLOPS, TPU_PEAK_HBM_GBPS
        source = "tpu_v5e_datasheet"
    else:
        peak_tflops, peak_bw = CPU_PEAK_TFLOPS, CPU_PEAK_DRAM_GBPS
        source = f"nominal_{platform}"
    env_f = os.environ.get("HYPEROPT_TPU_PEAK_TFLOPS")
    env_b = os.environ.get("HYPEROPT_TPU_PEAK_HBM_GBPS")
    if env_f:
        peak_tflops, source = float(env_f), "env_override"
    if env_b:
        peak_bw, source = float(env_b), "env_override"
    return {
        "peak_tflops": peak_tflops,
        "peak_hbm_GBps": peak_bw,
        "ridge_ai": (peak_tflops * 1e12) / (peak_bw * 1e9),
        "source": source,
    }


def roofline(flops: float, bytes_moved: float, device_s: float,
             peaks: dict) -> dict:
    """Attribute one program execution to the roofline ceiling that
    binds it.

    Arithmetic intensity ``AI = flops / bytes`` below the ridge point
    means the program's attainable FLOP/s is ``AI * peak_BW`` — HBM
    bandwidth is the binding ceiling and ``roofline_pct`` is achieved
    GB/s over peak GB/s (identically: achieved FLOP/s over attainable
    FLOP/s).  At or above the ridge the FLOP peak binds and
    ``roofline_pct`` is achieved TFLOP/s over peak TFLOP/s.  Both
    per-ceiling percentages are always reported so the table never
    hides the non-binding axis.
    """
    flops = max(float(flops), 0.0)
    bytes_moved = max(float(bytes_moved), 0.0)
    if device_s <= 0.0 or (flops == 0.0 and bytes_moved == 0.0):
        return {
            "achieved_tflops": None, "achieved_GBps": None,
            "ai_flops_per_byte": None, "ridge_ai": peaks["ridge_ai"],
            "binding_ceiling": None, "roofline_pct": None,
            "roofline_pct_mxu": None, "roofline_pct_bw": None,
        }
    achieved_tflops = flops / device_s / 1e12
    achieved_gbps = bytes_moved / device_s / 1e9
    pct_mxu = 100.0 * achieved_tflops / peaks["peak_tflops"]
    pct_bw = 100.0 * achieved_gbps / peaks["peak_hbm_GBps"]
    ai = flops / bytes_moved if bytes_moved else float("inf")
    binding = "hbm_bw" if ai < peaks["ridge_ai"] else "flops"
    return {
        "achieved_tflops": achieved_tflops,
        "achieved_GBps": achieved_gbps,
        "ai_flops_per_byte": None if ai == float("inf") else ai,
        "ridge_ai": peaks["ridge_ai"],
        "binding_ceiling": binding,
        "roofline_pct": pct_bw if binding == "hbm_bw" else pct_mxu,
        "roofline_pct_mxu": pct_mxu,
        "roofline_pct_bw": pct_bw,
    }


# ---------------------------------------------------------------------
# Cost model: FLOPs and bytes per fused suggest program
# ---------------------------------------------------------------------

_F32 = 4  # every device buffer in the suggest plane is f32/i32


def _cont_request_cost(args, statics) -> dict:
    """Analytical (flops, bytes) for one continuous-family request —
    the per-family extension of ``bench._scorer_flops`` that also
    counts HBM traffic.  Terms below ~1% of the totals at production
    shapes (prior uploads, argmax, counts) are deliberately dropped."""
    from .ops.score import pair_score_cost

    obs = args[1]
    losses = args[4]
    L, cap = int(obs.shape[0]), int(obs.shape[1])
    capt = int(losses.shape[0])
    k = int(statics["k"])
    n_cand = int(statics["n_cand"])
    cap_b = int(statics["cap_b"])
    C = k * n_cand
    K = (cap_b + 1) + (cap + 1)
    quantized = bool(statics.get("quantized"))
    n_buckets = int(statics.get("n_buckets", 0) or 0)

    # split/fit/draw: ranks argsort over [CAPT] (shared by the family),
    # per-label pack argsorts over [cap], Parzen fits ~O(cap), and the
    # truncated-GMM draw ~O(C) — all linear-ish terms
    flops = 16.0 * capt + L * (32.0 * cap + 12.0 * C)
    # input residency: obs+pos [L,cap] x2, losses+keep+ranks [CAPT]
    bytes_moved = 2.0 * L * cap * _F32 + 3.0 * capt * _F32
    from .ops.score import effective_scorer
    eff = effective_scorer(statics.get("scorer", "xla"), K)
    if eff != "fused" or quantized:
        # candidates: written by the draw, re-read by the scorer.  The
        # fused mega-kernel streams them through VMEM instead (its own
        # u-stream/candidate traffic is charged by pair_score_cost) —
        # charging the round trip here too would double-count it and
        # silently skew the roofline attribution for the new kernel.
        bytes_moved += 2.0 * L * C * _F32
    mxu_flops = 0.0
    if quantized and n_buckets > 0:
        # bucket-grid scoring: exact quantized lpdf on a [B] grid per
        # side (erf-based CDF, ~30 flops/cell), then an O(C) gather
        flops += L * (2.0 * 30.0 * n_buckets * K + 4.0 * C)
        bytes_moved += L * (2.0 * n_buckets * K * _F32 + C * _F32)
    elif quantized or statics.get("scorer") == "exact":
        # per-candidate exact lpdf: [C, K] erf broadcast per side
        flops += L * 2.0 * 30.0 * C * K
        bytes_moved += L * 2.0 * C * K * _F32
    else:
        sc = pair_score_cost(C, K, statics.get("scorer", "xla"))
        flops += L * sc["flops"]
        bytes_moved += L * sc["bytes"]
        mxu_flops = L * sc["mxu_flops"]
    # winners out
    bytes_moved += L * k * _F32
    return {"flops": flops, "bytes": bytes_moved, "mxu_flops": mxu_flops}


def _idx_request_cost(args, statics) -> dict:
    """Analytical (flops, bytes) for one index-family request."""
    obs = args[1]
    losses = args[4]
    prior_p = args[8]
    L, cap = int(obs.shape[0]), int(obs.shape[1])
    capt = int(losses.shape[0])
    U = int(prior_p.shape[1])
    C = int(statics["k"]) * int(statics["n_cand"])
    # posterior scatter-add over [cap] per side + [U] normalize, then a
    # C-candidate draw and two O(C) categorical lpdf gathers
    flops = 16.0 * capt + L * (2.0 * (4.0 * cap + 6.0 * U) + 10.0 * C)
    bytes_moved = (
        2.0 * L * cap * _F32 + 3.0 * capt * _F32
        + 2.0 * L * U * _F32 + 3.0 * L * C * _F32
        + L * int(statics["k"]) * _F32
    )
    return {"flops": flops, "bytes": bytes_moved, "mxu_flops": 0.0}


def analytical_cost(requests) -> dict:
    """{flops, bytes, mxu_flops, source} for one fused multi-family
    request list — pure shape arithmetic (microseconds; safe on every
    dispatch).  ``mxu_flops`` is the matmul-only subset MFU is defined
    against (``bench._scorer_flops`` semantics)."""
    total = {"flops": 0.0, "bytes": 0.0, "mxu_flops": 0.0}
    for kind, args, statics in requests:
        one = (
            _cont_request_cost(args, statics) if kind == "cont"
            else _idx_request_cost(args, statics)
        )
        for key in total:
            total[key] += one[key]
    total["source"] = "analytical"
    return total


def xla_cost(requests) -> dict:
    """{flops, bytes, source} for the fused program of ``requests``
    from XLA's own ``cost_analysis()`` — compiles a fresh copy of the
    program (seconds), so this belongs in reports and tests, never on
    the dispatch path.  Returns ``None`` when the backend does not
    expose a cost analysis."""
    import jax

    from .algos import tpe_device

    _, run = tpe_device._build_multi_run(requests)
    compiled = jax.jit(run).lower(
        [args for _, args, _ in requests]
    ).compile()
    try:
        analyses = compiled.cost_analysis()
    except Exception:  # backend without cost analysis
        return None
    if analyses is None:
        return None
    if isinstance(analyses, dict):
        analyses = [analyses]
    flops = sum(float(a.get("flops", 0.0)) for a in analyses)
    bytes_moved = sum(
        float(a.get("bytes accessed", 0.0)) for a in analyses
    )
    if flops <= 0.0 and bytes_moved <= 0.0:
        return None
    return {"flops": flops, "bytes": bytes_moved, "source": "xla"}


def signature_key(requests) -> str:
    """A human-readable stable key for one fused program signature —
    the row key of the DEVICE_PROFILE.json roofline table and of the
    profiler's cost cache.  Carries the same (trial-bucket, families)
    identity as ``tpe_device.compile_key`` plus every shape/static the
    cost model branches on (``cap_b``, scorer choice, quantization
    grid, mesh) — two programs whose costs can differ must never share
    a key, or the first-seen cost would misattribute the other's
    roofline."""
    parts = []
    capt = 0
    for kind, args, statics in requests:
        obs = args[1]
        losses = args[4]
        capt = max(capt, int(losses.shape[0]))
        bits = [
            f"L{int(obs.shape[0])}", f"cap{int(obs.shape[1])}",
            f"capb{int(statics['cap_b'])}",
            f"k{int(statics['k'])}", f"c{int(statics['n_cand'])}",
        ]
        if kind == "cont":
            bits.append(str(statics.get("scorer", "?")))
            if statics.get("quantized"):
                bits.append(f"q{int(statics.get('n_buckets', 0) or 0)}")
            if statics.get("log_scale"):
                bits.append("log")
            if statics.get("mesh") is not None:
                bits.append(f"mesh{_mesh_label(statics['mesh'])}")
        else:
            bits.append(f"u{int(statics.get('upper', 0) or 0)}")
        parts.append(f"{kind}[{','.join(bits)}]")
    return f"capt{capt}:" + "+".join(parts)


def _mesh_label(mesh) -> str:
    """'DPxSP' for a jax Mesh (sig-key + telemetry label)."""
    try:
        return "x".join(
            str(int(mesh.shape[name])) for name in mesh.axis_names
        )
    except Exception:  # pragma: no cover - defensive
        return "mesh"


def dispatch_mesh(requests):
    """The jax Mesh a fused request list would shard over (None for
    single-chip) — every cont family of one suggest shares the one
    mesh, so the first hit is THE mesh."""
    for _, _, statics in requests:
        mesh = statics.get("mesh")
        if mesh is not None:
            return mesh
    return None


def dispatch_devices(requests):
    """Stable per-chip labels ('<platform>:<id>') of the devices the
    fused program for ``requests`` runs on: the mesh's device set, or
    the default device for a single-chip dispatch.  The per-device
    telemetry split keys on these labels."""
    import numpy as np

    mesh = dispatch_mesh(requests)
    if mesh is not None:
        return [
            f"{d.platform}:{d.id}" for d in np.asarray(mesh.devices).flat
        ]
    import jax

    d = jax.devices()[0]
    return [f"{d.platform}:{d.id}"]


# ---------------------------------------------------------------------
# The dispatch observer
# ---------------------------------------------------------------------

_tls = threading.local()


def last_dispatch_record(consume: bool = True):
    """The most recent dispatch record produced ON THIS THREAD by an
    installed :class:`DeviceProfiler` (None when none).  The service
    scheduler reads it right after the fused readback — the resolver
    ran on the same thread — to attach roofline attrs to the
    ``device.dispatch`` spans.  ``consume`` clears it so a later batch
    can never be attributed with a stale record."""
    rec = getattr(_tls, "last_record", None)
    if consume:
        _tls.last_record = None
    return rec


class DeviceProfiler:
    """The per-dispatch roofline observer.

    ``install()`` registers on ``tpe_device._suggest_observers``; for
    every fused dispatch it computes the program's cost (cached per
    signature — the steady state is one dict lookup) and returns a
    completion callback the resolver fires with host-observed timings.
    Each completed dispatch becomes one record in ``stats``
    (:class:`~hyperopt_tpu.observability.DeviceStats`) and this
    thread's :func:`last_dispatch_record`.

    Overhead contract: *not installed* means ``_suggest_observers``
    stays empty and the dispatch path pays one truthiness check
    (device_report.py's overhead section measures the installed cost
    too — acceptance: suggest p50 within 5%).
    """

    def __init__(self, stats=None, peaks=None, keep_samples=False):
        from .observability import DeviceStats

        self.stats = stats if stats is not None else DeviceStats()
        self._peaks = peaks
        self.keep_samples = bool(keep_samples)
        self._lock = threading.Lock()
        self._cost_cache = {}  # guarded-by: _lock  (sig_key -> cost dict)
        self._samples = {}  # guarded-by: _lock  (sig_key -> requests)
        self._installed = None
        # disarmed after the first failure: CPU's memory_stats() is
        # None and some backends raise — probe once, not per dispatch
        self._backend_mem = True

    @property
    def peaks(self) -> dict:
        # resolved lazily so constructing a profiler never initializes
        # the jax backend
        if self._peaks is None:
            import jax

            self._peaks = platform_peaks(jax.default_backend())
        return self._peaks

    def install(self):
        if self._installed is not None:
            return self
        from .algos import tpe_device

        tpe_device._suggest_observers.append(self._observe)
        self._installed = self._observe
        return self

    def uninstall(self):
        if self._installed is None:
            return
        from .algos import tpe_device

        try:
            tpe_device._suggest_observers.remove(self._installed)
        except ValueError:
            pass
        self._installed = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def sample_requests(self, sig_key: str):
        """The retained request list for ``sig_key`` (requires
        ``keep_samples=True``) — device_report.py re-lowers it for the
        per-signature ``cost_analysis()`` cross-check."""
        with self._lock:
            return self._samples.get(sig_key)

    def signature_keys(self):
        with self._lock:
            return sorted(self._samples)

    # -- the observer --------------------------------------------------
    def _observe(self, requests):
        """Fires host-side once per fused dispatch, BEFORE the launch.
        Returns the completion callback the resolver invokes with the
        timing event — must never raise (profiling cannot fail a
        suggest)."""
        try:
            sig_key = signature_key(requests)
            with self._lock:
                cached = self._cost_cache.get(sig_key)
            if cached is None:
                cost = analytical_cost(requests)
                devices = dispatch_devices(requests)
                cached = (cost, devices)
                with self._lock:
                    self._cost_cache[sig_key] = cached
                    if self.keep_samples:
                        self._samples[sig_key] = requests
            cost, devices = cached
            # live-buffer residency of this program: every device array
            # it reads (nbytes is shape metadata — no transfer)
            arg_bytes = 0
            for _, args, _ in requests:
                for a in args:
                    arg_bytes += int(getattr(a, "nbytes", 0))
            peaks = self.peaks
            if len(devices) > 1:
                # mesh dispatch: the program spans len(devices) chips,
                # so the aggregate ceilings scale with the mesh (the
                # ridge point is unchanged — both axes scale together)
                peaks = dict(peaks)
                peaks["peak_tflops"] *= len(devices)
                peaks["peak_hbm_GBps"] *= len(devices)
                peaks["source"] = f"{peaks['source']}_x{len(devices)}"
            stats = self.stats
        except Exception:
            logger.warning("device profiler observe failed", exc_info=True)
            return None

        def _on_complete(event):
            try:
                if event.get("error"):
                    return  # failed readback: no timings to attribute
                device_s = float(event["device_s"])
                roof = roofline(cost["flops"], cost["bytes"], device_s,
                                peaks)
                rec = {
                    "sig": sig_key,
                    "n_requests": int(event.get("n_requests", 1)),
                    "device_s": device_s,
                    "launch_s": float(event.get("launch_s", 0.0)),
                    "wait_s": float(event.get("wait_s", 0.0)),
                    "readback_s": float(event.get("readback_s", 0.0)),
                    "flops": cost["flops"],
                    "mxu_flops": cost["mxu_flops"],
                    "hbm_bytes": cost["bytes"],
                    "live_bytes": arg_bytes + int(event.get("out_bytes", 0)),
                    "cost_source": cost["source"],
                    "compiled": bool(event.get("compiled", False)),
                    "devices": list(devices),
                }
                if self._backend_mem:
                    try:
                        import jax

                        # per-device allocator peaks: on a mesh every
                        # participating chip reports its own — a skewed
                        # shard shows up as ONE hot chip, not a blend
                        any_mem = False
                        all_devs = {
                            f"{d.platform}:{d.id}": d
                            for d in jax.devices()
                        }
                        for label in devices:
                            dev = all_devs.get(label)
                            if dev is None:
                                continue
                            mem = dev.memory_stats()
                            if mem:
                                any_mem = True
                                stats.set_backend_peak_bytes(
                                    mem.get("peak_bytes_in_use"),
                                    device=label,
                                )
                        if not any_mem:
                            self._backend_mem = False
                    except Exception:
                        self._backend_mem = False
                rec.update(roof)
                stats.record_dispatch(rec)
                _tls.last_record = rec
            except Exception:
                logger.warning(
                    "device profiler record failed", exc_info=True
                )

        return _on_complete


# ---------------------------------------------------------------------
# Bounded jax.profiler capture
# ---------------------------------------------------------------------


class ProfileCapture:
    """Opt-in ``jax.profiler`` capture of the first N fused dispatches.

    The service CLI's ``--profile-dir`` hook: starts a profiler trace
    at the first dispatch after :meth:`install` and stops it once
    ``max_dispatches`` have *resolved*, so the capture holds complete
    device programs and is bounded however long the server lives.
    View with TensorBoard/Perfetto.  Never raises into the dispatch
    path; a backend without profiler support logs once and disarms.
    """

    # lock-order: _lock
    def __init__(self, log_dir, max_dispatches: int = 16):
        self.log_dir = str(log_dir)
        self.max_dispatches = int(max_dispatches)
        self._lock = threading.Lock()
        self._started = False  # guarded-by: _lock
        self._stopped = False  # guarded-by: _lock
        self._n_seen = 0  # guarded-by: _lock
        self._n_resolved = 0  # guarded-by: _lock
        self._installed = None

    def install(self):
        if self._installed is not None or self.max_dispatches <= 0:
            return self
        from .algos import tpe_device

        tpe_device._suggest_observers.append(self._observe)
        self._installed = self._observe
        return self

    def uninstall(self):
        if self._installed is not None:
            from .algos import tpe_device

            try:
                tpe_device._suggest_observers.remove(self._installed)
            except ValueError:
                pass
            self._installed = None
        self._stop()

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def summary(self) -> dict:
        with self._lock:
            return {
                "log_dir": self.log_dir,
                "max_dispatches": self.max_dispatches,
                "started": self._started,
                "stopped": self._stopped,
                "n_captured": min(self._n_resolved, self.max_dispatches),
            }

    def _start(self):
        import jax

        try:
            jax.profiler.start_trace(self.log_dir)
            return True
        except Exception:
            logger.warning(
                "jax.profiler capture unavailable; disarming",
                exc_info=True,
            )
            return False

    def _stop(self):
        with self._lock:
            if not self._started or self._stopped:
                self._stopped = True
                return
            self._stopped = True
        import jax

        try:
            jax.profiler.stop_trace()
            logger.info(
                "device profile captured to %s (%d dispatches)",
                self.log_dir, self.max_dispatches,
            )
        except Exception:
            logger.warning("jax.profiler stop failed", exc_info=True)

    def _observe(self, requests):
        with self._lock:
            if self._stopped:
                return None
            if self._n_seen >= self.max_dispatches:
                past_budget = True
            else:
                past_budget = False
                self._n_seen += 1
            need_start = not past_budget and not self._started
            if need_start:
                self._started = True
        if past_budget:
            # backstop: a budgeted dispatch whose resolver never ran (a
            # discarded speculation) must not leave the trace open for
            # the server's lifetime — the first dispatch past budget
            # closes it
            self._stop()
            return None
        if need_start and not self._start():
            with self._lock:
                self._stopped = True
            return None

        def _on_complete(event):
            # error events count too: a failed readback consumed budget
            with self._lock:
                self._n_resolved += 1
                done = self._n_resolved >= self.max_dispatches
            if done:
                self._stop()

        return _on_complete
