"""Tracing & profiling hooks.

The reference has no built-in tracing (SURVEY.md §5) — only module loggers
and ``verbose`` flags.  This module goes further, per the survey's rebuild
note: per-phase driver timings plus ``jax.profiler`` integration so the
device-side suggest kernels can be traced on real TPUs (view with
TensorBoard or Perfetto).
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict
from functools import wraps

logger = logging.getLogger(__name__)


class PhaseTimings:
    """Accumulated wall-clock per driver phase (suggest / evaluate / ...)."""

    def __init__(self):
        self._total = defaultdict(float)
        self._count = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._total[name] += dt
            self._count[name] += 1

    def record(self, name, seconds):
        self._total[name] += seconds
        self._count[name] += 1

    def summary(self):
        return {
            name: {
                "total_s": round(self._total[name], 6),
                "count": self._count[name],
                "mean_ms": round(1e3 * self._total[name] / max(self._count[name], 1), 3),
            }
            for name in sorted(self._total)
        }

    def log_summary(self, level=logging.INFO):
        for name, stats in self.summary().items():
            logger.log(
                level,
                "phase %-12s total %8.3fs  n=%-5d mean %8.3fms",
                name,
                stats["total_s"],
                stats["count"],
                stats["mean_ms"],
            )


def timed_suggest(algo, timings: PhaseTimings):
    """Wrap a suggest function so each call lands in ``timings``."""

    @wraps(algo)
    def wrapper(new_ids, domain, trials, seed, *args, **kwargs):
        with timings.phase("suggest"):
            return algo(new_ids, domain, trials, seed, *args, **kwargs)

    return wrapper


def traced_suggest(algo, log_dir):
    """Wrap a suggest function in a ``jax.profiler.trace`` so its device
    kernels appear in TensorBoard/Perfetto traces under ``log_dir``."""
    import jax

    @wraps(algo)
    def wrapper(new_ids, domain, trials, seed, *args, **kwargs):
        with jax.profiler.trace(str(log_dir)):
            return algo(new_ids, domain, trials, seed, *args, **kwargs)

    return wrapper


@contextlib.contextmanager
def annotate(name):
    """Named region visible in device profiles (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
