"""Tracing & profiling hooks.

The reference has no built-in tracing (SURVEY.md §5) — only module loggers
and ``verbose`` flags.  This module goes further, per the survey's rebuild
note: per-phase driver timings plus ``jax.profiler`` integration so the
device-side suggest kernels can be traced on real TPUs (view with
TensorBoard or Perfetto).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import defaultdict
from functools import wraps

logger = logging.getLogger(__name__)


class PhaseTimings:
    """Accumulated wall-clock per driver phase (suggest / evaluate / ...).

    Thread-safe: the driver loop owns one, but the optimization service
    records into a shared instance from concurrent handler threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total = defaultdict(float)
        self._count = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def record(self, name, seconds):
        with self._lock:
            self._total[name] += seconds
            self._count[name] += 1

    def summary(self):
        with self._lock:
            totals = dict(self._total)
            counts = dict(self._count)
        return {
            name: {
                "total_s": round(totals[name], 6),
                "count": counts[name],
                "mean_ms": round(1e3 * totals[name] / max(counts[name], 1), 3),
            }
            for name in sorted(totals)
        }

    def log_summary(self, level=logging.INFO):
        for name, stats in self.summary().items():
            logger.log(
                level,
                "phase %-12s total %8.3fs  n=%-5d mean %8.3fms",
                name,
                stats["total_s"],
                stats["count"],
                stats["mean_ms"],
            )


class SpeculationStats:
    """Overlap accounting for the pipelined suggest engine.

    Splits per-suggest wall-clock into **hidden** time (speculative
    dispatch work done while the user objective runs — off the critical
    path) and **exposed** time (work the driver had to wait for: resolving
    a speculative readback, or a fully synchronous suggest after a miss /
    invalidation).  ``hidden_s / (hidden_s + exposed_s)`` is the fraction
    of suggest cost the pipeline removed from the wall clock.
    """

    def __init__(self):
        self.dispatch_s = 0.0  # hidden: speculative launch (host marshal + jit dispatch)
        self.reissue_exposed_s = 0.0  # exposed: re-issue launched at consume time
        self.resolve_s = 0.0  # exposed: blocking readback of a used speculation
        self.sync_s = 0.0  # exposed: synchronous suggest (miss or no speculation)
        self.n_dispatched = 0
        self.n_hypothesis = 0
        self.n_used = 0
        self.n_invalidated = 0
        self.n_sync = 0
        self.n_discarded = 0

    def record_dispatch(self, seconds, hypothesis=False, exposed=False):
        # ``exposed``: the launch ran on the driver's critical path (an
        # invalidation re-issue at consume time), not behind an objective
        if exposed:
            self.reissue_exposed_s += seconds
        else:
            self.dispatch_s += seconds
        self.n_dispatched += 1
        if hypothesis:
            # fit against the hypothetical lands-above history (exact
            # when the prediction holds; see hyperopt_tpu.pipeline)
            self.n_hypothesis += 1

    def record_resolve(self, seconds):
        self.resolve_s += seconds
        self.n_used += 1

    def record_sync(self, seconds):
        self.sync_s += seconds
        self.n_sync += 1

    def record_invalidation(self, n=1):
        self.n_invalidated += n

    def record_discard(self, n=1):
        self.n_discarded += n

    @property
    def hidden_s(self):
        return self.dispatch_s

    @property
    def exposed_s(self):
        return self.resolve_s + self.sync_s + self.reissue_exposed_s

    def summary(self):
        total = self.hidden_s + self.exposed_s
        return {
            "hidden_s": round(self.hidden_s, 6),
            "exposed_s": round(self.exposed_s, 6),
            "hidden_frac": round(self.hidden_s / total, 4) if total else None,
            "resolve_s": round(self.resolve_s, 6),
            "sync_s": round(self.sync_s, 6),
            "reissue_exposed_s": round(self.reissue_exposed_s, 6),
            "n_dispatched": self.n_dispatched,
            "n_hypothesis": self.n_hypothesis,
            "n_used": self.n_used,
            "n_invalidated": self.n_invalidated,
            "n_sync": self.n_sync,
            "n_discarded": self.n_discarded,
        }

    def log_summary(self, level=logging.INFO):
        s = self.summary()
        logger.log(
            level,
            "speculation: hidden %.3fs exposed %.3fs (frac %s) "
            "dispatched=%d (hypothesis=%d) used=%d invalidated=%d "
            "sync=%d discarded=%d",
            s["hidden_s"],
            s["exposed_s"],
            s["hidden_frac"],
            s["n_dispatched"],
            s["n_hypothesis"],
            s["n_used"],
            s["n_invalidated"],
            s["n_sync"],
            s["n_discarded"],
        )


class FaultStats:
    """Fault-tolerance accounting for :mod:`hyperopt_tpu.resilience`.

    Every recovery event in the fault-tolerance layer — lease expiries and
    reclamations, retries and their backoff sleeps, quarantines, device
    re-initializations, CPU fallbacks, dropped stale results, and every
    chaos-injected fault (``chaos_*`` keys) — lands here, so a run can
    assert that injected faults and recoveries balance (the chaos
    campaign's accounting invariant).

    Counters are an open set keyed by event name; the well-known keys are

    - ``lease_expired`` / ``lease_reclaimed`` / ``lease_quarantined`` —
      reaper activity (expiries observed, trials re-queued, trials moved
      to ``JOB_STATE_ERROR`` after ``max_attempts``)
    - ``stale_lock_cleared`` — torn/orphaned lock files removed
    - ``trial_failure`` / ``trial_retried`` / ``trial_quarantined`` —
      retry-policy activity (plus ``backoff_s`` accumulated sleep)
    - ``objective_timeout`` — per-trial watchdog expiries
    - ``stale_result_dropped`` — a worker's result discarded because its
      lease had been reclaimed while it ran
    - ``heartbeat`` — lease renewals
    - ``device_error`` / ``device_reinit`` / ``cpu_fallback`` — device
      recovery activity
    - ``chaos_<site>`` — faults injected by the chaos harness

    Thread-safe: the reaper, worker threads, and the driver all record
    concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = defaultdict(int)
        self._backoff_s = 0.0

    def record(self, event: str, n: int = 1):
        with self._lock:
            self._counts[event] += n

    def record_backoff(self, seconds: float):
        with self._lock:
            self._backoff_s += float(seconds)

    def get(self, event: str) -> int:
        with self._lock:
            return self._counts.get(event, 0)

    @property
    def backoff_s(self) -> float:
        with self._lock:
            return self._backoff_s

    def counts(self) -> dict:
        """Snapshot of all counters (sorted, chaos keys included)."""
        with self._lock:
            return dict(sorted(self._counts.items()))

    def injected(self) -> dict:
        """Just the chaos-injected fault counters, keyed by site."""
        with self._lock:
            return {
                k[len("chaos_"):]: v
                for k, v in sorted(self._counts.items())
                if k.startswith("chaos_")
            }

    def merge(self, other: "FaultStats"):
        """Fold another FaultStats into this one (campaign aggregation)."""
        o = other.counts()
        ob = other.backoff_s
        with self._lock:
            for k, v in o.items():
                self._counts[k] += v
            self._backoff_s += ob

    def summary(self) -> dict:
        out = self.counts()
        out["backoff_s"] = round(self.backoff_s, 6)
        return out

    def log_summary(self, level=logging.INFO):
        s = self.summary()
        if len(s) == 1:  # only backoff_s, nothing happened
            return
        logger.log(
            level,
            "faults: %s",
            " ".join(f"{k}={v}" for k, v in s.items()),
        )


# Fixed histogram bucket upper bounds (seconds) for suggest latency —
# log-spaced from sub-millisecond device-cache hits out past the worst
# compile-storm tail BENCH_SERVE.json recorded (26s p99); +Inf implied.
SUGGEST_DURATION_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


# Fixed histogram bucket upper bounds (seconds) for store fsync latency
# — local SSDs fsync in fractions of a millisecond, NFS/GCS-fuse mounts
# in tens to hundreds; the tail past 1 s is the "storage plane is the
# bottleneck" evidence the segmented-store roadmap item needs.
FSYNC_DURATION_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)


def quantile_from_counts(edges, counts, q):
    """The q-quantile of a fixed-bucket histogram given per-bucket (NOT
    cumulative) counts — shared by :class:`LatencyHistogram` and the SLO
    engine's window deltas (a window histogram is the elementwise
    difference of two cumulative snapshots).  ``counts`` has one more
    entry than ``edges`` (the +Inf bucket); observations there report
    the last finite edge (a floor).  None when empty."""
    total = sum(counts)
    if not total:
        return None
    rank = q * total
    seen = 0.0
    lo = 0.0
    for i, edge in enumerate(edges):
        n = counts[i]
        if seen + n >= rank:
            if n == 0:
                return edge
            frac = (rank - seen) / n
            return lo + frac * (edge - lo)
        seen += n
        lo = edge
    return edges[-1] if edges else None


class LatencyHistogram:
    """A fixed-bucket latency histogram (the Prometheus histogram
    shape: cumulative ``_bucket{le=...}`` counts + ``_sum``/``_count``).

    Unlike a bounded percentile ring buffer, bucket counts never evict:
    the exported p99 is the p99 of EVERY observation, not "p99 of the
    last N" — under load a ring silently narrows its window exactly when
    the tail matters most.  Quantiles are interpolated within the
    containing bucket (exact at bucket edges, monotone in between).

    NOT thread-safe on its own; the owner (:class:`ServiceStats`)
    serializes access under its lock.
    """

    def __init__(self, buckets=SUGGEST_DURATION_BUCKETS):
        self.buckets = tuple(float(b) for b in buckets)
        assert list(self.buckets) == sorted(self.buckets)
        # counts[i] = observations <= buckets[i]; counts[-1] = +Inf bucket
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0
        self.sum_s = 0.0

    def observe(self, seconds: float):
        s = float(seconds)
        self.total += 1
        self.sum_s += s
        for i, edge in enumerate(self.buckets):
            if s <= edge:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def quantile(self, q: float):
        """The q-quantile in seconds (None when empty), linearly
        interpolated inside the containing bucket.  The +Inf bucket has
        no upper edge; observations there report the last finite edge
        (a floor — the true value is at least that)."""
        return quantile_from_counts(self.buckets, self.counts, q)

    def state(self) -> dict:
        """A diffable snapshot: per-bucket (non-cumulative) counts plus
        total/sum — what the SLO engine stores per tick so a window's
        histogram is the elementwise difference of two snapshots."""
        return {
            "edges": self.buckets,
            "counts": list(self.counts),
            "total": self.total,
            "sum_s": self.sum_s,
        }

    def to_dict(self) -> dict:
        """Cumulative bucket counts keyed by upper edge (the Prometheus
        exposition shape), plus sum/count."""
        cum, acc = [], 0
        for i, edge in enumerate(self.buckets):
            acc += self.counts[i]
            cum.append((edge, acc))
        cum.append((float("inf"), acc + self.counts[-1]))
        return {"buckets": cum, "count": self.total, "sum_s": self.sum_s}


class ServiceStats:
    """Request / latency / batch-occupancy accounting for the
    optimization service (:mod:`hyperopt_tpu.service`).

    Tracks, per endpoint, how many requests were served and how many
    were rejected with backpressure; per study, how many suggests were
    served; and for the continuous-batching scheduler, how many fused
    device dispatches ran and how many suggest requests each one
    carried (``mean_batch_occupancy`` — the "requests per device
    program" number the service exists to push above 1).

    Suggest latency lives in a fixed-bucket :class:`LatencyHistogram`
    (the exported source of truth — no eviction, so p99 means p99 of
    everything) with per-phase attributed-seconds counters fed by the
    scheduler, plus a bounded ring sample kept only for the human
    ``/v1/status`` JSON (its quantiles are "of the last N" and say so).
    Idempotent replays are tagged and excluded from latency — a journal
    hit must not fake a fast suggest or mask a slow one.

    Thread-safe: HTTP handler threads and the scheduler thread record
    concurrently.
    """

    def __init__(self, max_latency_samples=65536):
        from collections import deque

        self._lock = threading.Lock()
        self._requests = defaultdict(int)       # endpoint -> served
        self._rejected = defaultdict(int)       # endpoint -> 429s
        self._errors = defaultdict(int)         # endpoint -> 5xx/504s
        self._replayed = defaultdict(int)       # endpoint -> journal hits
        self._study_suggests = defaultdict(int)  # study -> suggests served
        # the exported latency source of truth: fixed buckets, no window
        self._suggest_hist = LatencyHistogram()
        # the warm/cold split: every suggest lands in the union histogram
        # above AND in exactly one of these — "cold" means the fused
        # dispatch that served it carried an XLA compile (first-touch),
        # "warm" is steady state.  BENCH_SERVE's 26 s p99 next to a 39 ms
        # p50 is the blended view; these attribute it.
        self._suggest_hist_warm = LatencyHistogram()
        self._suggest_hist_cold = LatencyHistogram()
        # ring buffer: a bounded human-readable sample of RECENT traffic
        # for /v1/status only (window size is reported alongside)
        self._suggest_latencies = deque(maxlen=int(max_latency_samples))
        # per-phase attributed seconds (queue_wait/coalesce/prepare/
        # dispatch/readback/finish/inline), fed by the scheduler
        self._phase_s = defaultdict(float)
        self._phase_n = defaultdict(int)
        # XLA (re)compile events keyed by (trial-bucket, families);
        # request-path events counted separately (background warmup/
        # containment compiles are excluded from cold attribution)
        self._compile_events = defaultdict(int)
        self._n_request_compile_events = 0
        self._n_dispatches = 0        # fused device programs launched
        self._n_batched = 0           # suggests served through a dispatch
        self._n_inline = 0            # host-side suggests (startup/rand)
        self._dispatch_s = 0.0
        self._queue_depth = 0         # last-observed scheduler queue depth
        # cumulative depth accounting: every observation adds to the
        # sum, so a window delta (sum/samples) yields the MEAN depth
        # over that window — the controller's objective term.  Sampled
        # at request arrival AND at batch dispatch (a quiet tenant's
        # drained queue is an observation too, not a blind spot).
        self._queue_depth_sum = 0     # sum of observed depths
        self._queue_depth_samples = 0  # number of observations
        self._n_studies = 0
        # compile-plane accounting (hyperopt_tpu.compile_ledger):
        # cold suggests overall, cold suggests AFTER the service first
        # reported ready (the SL607 numerator — post-warmup the request
        # path must pay ~zero compiles), and host-side cold-containment
        # fallbacks served while a compile proceeded off-thread
        self._n_cold_suggests = 0
        self._n_cold_after_ready = 0
        self._n_cold_fallbacks = 0
        self._ready = False           # latched by mark_ready()

    def record_request(self, endpoint: str, seconds=None, study=None,
                       replay=False, cold=False):
        """``replay=True`` marks a response served from the idempotency
        journal: counted as a request, NEVER as a latency observation
        (journal hits are instant and would dilute the histogram's
        tail exactly when retries spike).  ``cold=True`` marks a suggest
        whose fused dispatch carried an XLA compile: it lands in the
        union histogram AND the cold split (warm otherwise)."""
        with self._lock:
            self._requests[endpoint] += 1
            if endpoint == "suggest" and not replay:
                if study is not None:
                    self._study_suggests[str(study)] += 1
                if cold:
                    self._n_cold_suggests += 1
                    if self._ready:
                        self._n_cold_after_ready += 1
                if seconds is not None:
                    self._suggest_hist.observe(float(seconds))
                    split = (
                        self._suggest_hist_cold if cold
                        else self._suggest_hist_warm
                    )
                    split.observe(float(seconds))
                    self._suggest_latencies.append(float(seconds))

    def mark_ready(self):
        """Latch "the service has reported ready": cold suggests from
        here on count against SL607 (a compile in the request path
        after warmup is the failure the warmup exists to prevent).

        Armed by the first GREEN ``/readyz`` evaluation — deliberately:
        an embedded service that is never readiness-probed keeps SL607
        in ``no_data``, because without a readiness barrier its traffic
        legitimately interleaves with first-touch compiles (a short
        in-process campaign runs ~10% cold organically, and paging on
        that would punish correct behavior).  Serving deployments
        always probe ``/readyz`` (``wait_ready``, k8s), which is
        exactly the population the rule guards."""
        with self._lock:
            self._ready = True

    def record_cold_fallback(self):
        """One suggest served host-side (cold containment) while its
        unwarmed fused program compiled off-thread."""
        with self._lock:
            self._n_cold_fallbacks += 1

    @property
    def n_cold_fallbacks(self) -> int:
        with self._lock:
            return self._n_cold_fallbacks

    def record_rejection(self, endpoint: str):
        with self._lock:
            self._rejected[endpoint] += 1

    def record_error(self, endpoint: str):
        """A request that failed server-side (5xx/504) — the numerator
        of the SL603 error-rate objective, next to backpressure 429s."""
        with self._lock:
            self._errors[endpoint] += 1

    def record_replay(self, endpoint: str):
        """A retried request answered from the idempotency journal —
        exactly-once doing its job (no seed consumed, no state change)."""
        with self._lock:
            self._replayed[endpoint] += 1

    def record_dispatch(self, n_requests: int, seconds: float):
        """One fused device program carrying ``n_requests`` suggests."""
        with self._lock:
            self._n_dispatches += 1
            self._n_batched += int(n_requests)
            self._dispatch_s += float(seconds)

    def record_phase(self, phase: str, seconds: float, n: int = 1):
        """Attribute ``seconds`` of suggest wall-time to a named phase
        (the histogram's per-phase sums — always on, tracing or not)."""
        with self._lock:
            self._phase_s[str(phase)] += float(seconds)
            self._phase_n[str(phase)] += int(n)

    def record_compile(self, bucket, families, background=False):
        """One XLA (re)trace of the fused suggest program, keyed by its
        (trial-count bucket, family composition).  ``background=True``
        marks an off-request-path compile (AOT warmup replay, cold-
        containment background thread): counted in the per-key event
        map but excluded from :attr:`n_compile_events`, so a request
        that merely OVERLAPPED it is never attributed cold."""
        with self._lock:
            self._compile_events[(int(bucket), str(families))] += 1
            if not background:
                self._n_request_compile_events += 1

    @property
    def n_compile_events(self) -> int:
        """Request-path compile events only (the cold-attribution
        delta); the full per-key map is :meth:`compile_events`."""
        with self._lock:
            return self._n_request_compile_events

    def record_inline(self, n: int = 1):
        """Suggests served host-side (random startup) — no device
        program, so they count toward requests but not occupancy."""
        with self._lock:
            self._n_inline += int(n)

    def set_queue_depth(self, n: int):
        with self._lock:
            self._queue_depth = int(n)
            self._queue_depth_sum += int(n)
            self._queue_depth_samples += 1

    def set_n_studies(self, n: int):
        with self._lock:
            self._n_studies = int(n)

    @property
    def mean_batch_occupancy(self):
        with self._lock:
            if not self._n_dispatches:
                return None
            return self._n_batched / self._n_dispatches

    def latency_quantiles(self):
        """{"p50_ms": ..., "p99_ms": ...} over the FULL histogram — the
        exported source of truth (bucket-interpolated, no eviction)."""
        with self._lock:
            p50 = self._suggest_hist.quantile(0.50)
            p99 = self._suggest_hist.quantile(0.99)
        return {
            "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
            "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
        }

    @staticmethod
    def _split_quantiles(hist):
        p50, p99 = hist.quantile(0.50), hist.quantile(0.99)
        return {
            "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
            "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
            "count": hist.total,
        }

    def split_latency_quantiles(self):
        """{"warm": {...}, "cold": {...}} — the first-touch (compile-
        carrying) vs steady-state attribution of the suggest latency."""
        with self._lock:
            return {
                "warm": self._split_quantiles(self._suggest_hist_warm),
                "cold": self._split_quantiles(self._suggest_hist_cold),
            }

    def warm_hist_state(self) -> dict:
        """Diffable snapshot of the STEADY-STATE (compile-excluded)
        suggest histogram — the SLO engine's latency-rule input (the
        PR 7 convention: compile-carrying dispatches are real cost but
        meaningless steady-state latency)."""
        with self._lock:
            return self._suggest_hist_warm.state()

    def slo_counters(self) -> dict:
        """The scalar counters the SLO engine snapshots per tick.
        ``requests_mutating`` counts only the suggest/report/create
        routes — the SL603 denominator must not be diluted by a
        dashboard polling /v1/alerts or /metrics between incidents."""
        with self._lock:
            mutating = ("suggest", "report", "create_study")
            return {
                "requests_suggest": self._requests.get("suggest", 0),
                "requests_mutating": sum(
                    self._requests.get(e, 0) for e in mutating
                ),
                "requests_total": sum(self._requests.values()),
                "rejected_total": sum(self._rejected.values()),
                # numerator and denominator must cover the SAME routes:
                # a flaky read-only endpoint's 500s would otherwise
                # overstate the mutating error rate
                "errors_mutating": sum(
                    self._errors.get(e, 0) for e in mutating
                ),
                "errors_total": sum(self._errors.values()),
                # compile-plane counters (SL607 + cold containment)
                "suggests_cold": self._n_cold_suggests,
                "suggests_cold_after_ready": self._n_cold_after_ready,
                "cold_fallbacks": self._n_cold_fallbacks,
                # cumulative queue-depth accounting: a window delta of
                # sum/samples is the mean depth over that window (the
                # control plane's backlog objective term)
                "queue_depth_sum": self._queue_depth_sum,
                "queue_depth_samples": self._queue_depth_samples,
            }

    def window_quantiles(self):
        """Ring-buffer quantiles over the last-N sample — the HUMAN
        numbers for /v1/status, with the window size spelled out so
        "p99" can never be silently read as all-time."""
        import numpy as np

        with self._lock:
            lat = list(self._suggest_latencies)
            cap = self._suggest_latencies.maxlen
        if not lat:
            return {"p50_ms": None, "p99_ms": None,
                    "window": 0, "max_window": cap}
        arr = np.asarray(lat)
        return {
            "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
            "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
            "window": len(lat),
            "max_window": cap,
        }

    def phase_summary(self) -> dict:
        with self._lock:
            return {
                phase: {
                    "total_s": round(self._phase_s[phase], 6),
                    "count": self._phase_n[phase],
                }
                for phase in sorted(self._phase_s)
            }

    def compile_events(self) -> dict:
        """{"<bucket>/<families>": count} snapshot."""
        with self._lock:
            return {
                f"{bucket}/{families}": n
                for (bucket, families), n in sorted(
                    self._compile_events.items()
                )
            }

    def histogram_dict(self) -> dict:
        with self._lock:
            return self._suggest_hist.to_dict()

    def summary(self) -> dict:
        q = self.latency_quantiles()
        split = self.split_latency_quantiles()
        window = self.window_quantiles()
        phases = self.phase_summary()
        compiles = self.compile_events()
        with self._lock:
            occ = (
                self._n_batched / self._n_dispatches
                if self._n_dispatches
                else None
            )
            return {
                "requests": dict(sorted(self._requests.items())),
                "rejected": dict(sorted(self._rejected.items())),
                "errors": dict(sorted(self._errors.items())),
                "idempotent_replays": dict(sorted(self._replayed.items())),
                "study_suggests": dict(sorted(self._study_suggests.items())),
                "n_dispatches": self._n_dispatches,
                "n_batched_suggests": self._n_batched,
                "n_inline_suggests": self._n_inline,
                "mean_batch_occupancy": (
                    round(occ, 4) if occ is not None else None
                ),
                "dispatch_s": round(self._dispatch_s, 6),
                "queue_depth": self._queue_depth,
                "queue_depth_mean": (
                    round(
                        self._queue_depth_sum
                        / self._queue_depth_samples, 4,
                    )
                    if self._queue_depth_samples else None
                ),
                "n_studies": self._n_studies,
                "n_cold_suggests": self._n_cold_suggests,
                "n_cold_after_ready": self._n_cold_after_ready,
                "n_cold_fallbacks": self._n_cold_fallbacks,
                # histogram-derived (all observations ever)
                "suggest_latency": q,
                # first-touch (compile-carrying) vs steady-state split
                "suggest_latency_warm": split["warm"],
                "suggest_latency_cold": split["cold"],
                # ring-derived (recent window; human eyes only)
                "suggest_latency_window": window,
                "phase_seconds": phases,
                "compile_events": compiles,
            }

    def log_summary(self, level=logging.INFO):
        s = self.summary()
        logger.log(
            level,
            "service: requests=%s rejected=%s dispatches=%d occupancy=%s "
            "p50=%sms p99=%sms",
            s["requests"],
            s["rejected"],
            s["n_dispatches"],
            s["mean_batch_occupancy"],
            s["suggest_latency"]["p50_ms"],
            s["suggest_latency"]["p99_ms"],
        )


class DeviceStats:
    """Per-dispatch device-plane accounting for the roofline profiler
    (:mod:`hyperopt_tpu.profiling`).

    Every fused suggest dispatch an installed
    :class:`~hyperopt_tpu.profiling.DeviceProfiler` observes lands here
    as one record: host-observed device seconds, modeled FLOPs and HBM
    bytes, achieved TFLOP/s and GB/s, and the roofline attribution —
    WHICH ceiling binds the program (HBM bandwidth vs peak FLOP/s) and
    what fraction of that binding ceiling it achieved.  Aggregates:

    - **duty cycle** — device-busy seconds over wall seconds since this
      stats object started (host-observed dispatch->resolve intervals;
      exact on the sync/service paths, an upper bound under
      speculative overlap);
    - **binding-ceiling histogram** — dispatch counts per ceiling, the
      one-line answer to "is this workload bandwidth- or compute-
      bound";
    - **memory watermarks** — the high-water of live program bytes
      (inputs + output of a dispatch) and, when the backend reports
      one, its peak allocated bytes;
    - a bounded **per-signature table** (the DEVICE_PROFILE.json
      roofline table): per fused-program signature, dispatch count,
      mean device time, cost, and mean/last roofline attribution.

    Thread-safe: resolver callbacks record from scheduler/driver
    threads while ``/metrics`` renders concurrently.
    """

    MAX_SIGNATURES = 128
    MAX_RECENT = 128

    def __init__(self):
        from collections import deque

        self._lock = threading.Lock()
        # bounded ring of the most recent dispatch records — the flight
        # recorder's device-plane evidence at breach time
        self._recent = deque(maxlen=self.MAX_RECENT)  # guarded-by: _lock
        self._t_started = time.monotonic()
        self._n_dispatches = 0  # guarded-by: _lock
        self._n_requests = 0  # guarded-by: _lock
        self._busy_s = 0.0  # guarded-by: _lock
        self._launch_s = 0.0  # guarded-by: _lock
        self._readback_s = 0.0  # guarded-by: _lock
        self._flops_total = 0.0  # guarded-by: _lock
        self._bytes_total = 0.0  # guarded-by: _lock
        self._n_compiled = 0  # guarded-by: _lock
        self._ceiling_counts = defaultdict(int)  # guarded-by: _lock
        # roofline-percent aggregation over STEADY-STATE dispatches only
        # (a record tagged ``compiled`` timed an XLA compile inside its
        # interval — real cost, meaningless throughput)
        self._pct_sum = defaultdict(float)  # guarded-by: _lock
        self._pct_n = defaultdict(int)  # guarded-by: _lock
        self._live_bytes_hw = 0  # guarded-by: _lock
        self._backend_peak_bytes = None  # guarded-by: _lock
        self._sigs = {}  # guarded-by: _lock
        self._sig_drops = 0  # guarded-by: _lock
        self._last = None  # guarded-by: _lock
        # per-device split (mesh execution mode): busy seconds and
        # dispatch counts per chip label ('<platform>:<id>') — a
        # dispatch's device interval is attributed to EVERY chip its
        # program spanned, so a chip left out of the mesh (or only
        # reached by single-chip traffic) shows as the cold/hot one
        # instead of blending into one average.  Allocator peaks are
        # genuinely per-chip (each device reports its own memory_stats).
        self._busy_by_device = defaultdict(float)  # guarded-by: _lock
        self._n_by_device = defaultdict(int)  # guarded-by: _lock
        self._live_hw_by_device = defaultdict(int)  # guarded-by: _lock
        self._backend_peak_by_device = {}  # guarded-by: _lock

    def record_dispatch(self, rec: dict):
        """One completed fused dispatch (record shape documented in
        :meth:`hyperopt_tpu.profiling.DeviceProfiler._observe`)."""
        device_s = float(rec.get("device_s") or 0.0)
        ceiling = rec.get("binding_ceiling")
        pct = rec.get("roofline_pct")
        live = int(rec.get("live_bytes") or 0)
        compiled = bool(rec.get("compiled"))
        with self._lock:
            self._n_dispatches += 1
            self._n_requests += int(rec.get("n_requests") or 1)
            self._n_compiled += int(compiled)
            self._busy_s += device_s
            self._launch_s += float(rec.get("launch_s") or 0.0)
            self._readback_s += float(rec.get("readback_s") or 0.0)
            self._flops_total += float(rec.get("flops") or 0.0)
            self._bytes_total += float(rec.get("hbm_bytes") or 0.0)
            if ceiling is not None:
                # the ceiling classification is pure arithmetic
                # intensity — timing-independent, so compiled
                # dispatches count here too
                self._ceiling_counts[str(ceiling)] += 1
                if pct is not None and not compiled:
                    self._pct_sum[str(ceiling)] += float(pct)
                    self._pct_n[str(ceiling)] += 1
            if live > self._live_bytes_hw:
                self._live_bytes_hw = live
            for dev in rec.get("devices") or ():
                dev = str(dev)
                self._busy_by_device[dev] += device_s
                self._n_by_device[dev] += 1
                # upper bound per chip: replicated history buffers are
                # resident full-size on every mesh device; only the
                # sharded scoring intermediates split
                if live > self._live_hw_by_device[dev]:
                    self._live_hw_by_device[dev] = live
            self._last = dict(rec)
            self._recent.append(dict(rec))
            sig = str(rec.get("sig", "?"))
            agg = self._sigs.get(sig)
            if agg is None:
                if len(self._sigs) >= self.MAX_SIGNATURES:
                    self._sig_drops += 1
                    return
                agg = self._sigs[sig] = {
                    "n": 0, "n_requests": 0, "n_compiled": 0,
                    "steady_s": 0.0, "n_steady": 0, "any_s": 0.0,
                    "pct_sum": 0.0, "ceilings": defaultdict(int),
                    "last": None, "last_any": None,
                }
            agg["n"] += 1
            agg["n_requests"] += int(rec.get("n_requests") or 1)
            agg["n_compiled"] += int(compiled)
            agg["any_s"] += device_s
            agg["last_any"] = dict(rec)
            if not compiled:
                agg["steady_s"] += device_s
                agg["n_steady"] += 1
                if pct is not None:
                    agg["pct_sum"] += float(pct)
                agg["last"] = dict(rec)
            if ceiling is not None:
                agg["ceilings"][str(ceiling)] += 1

    def set_backend_peak_bytes(self, nbytes, device=None):
        """Record the backend allocator's peak (``Device.memory_stats()
        ['peak_bytes_in_use']`` where available — TPU yes, CPU no).
        With ``device`` (a '<platform>:<id>' label) the peak is ALSO
        tracked per chip — the mesh-mode skew signal."""
        if nbytes is None:
            return
        with self._lock:
            if (
                self._backend_peak_bytes is None
                or nbytes > self._backend_peak_bytes
            ):
                self._backend_peak_bytes = int(nbytes)
            if device is not None:
                prev = self._backend_peak_by_device.get(str(device))
                if prev is None or nbytes > prev:
                    self._backend_peak_by_device[str(device)] = int(nbytes)

    @property
    def n_dispatches(self) -> int:
        with self._lock:
            return self._n_dispatches

    def last_record(self):
        with self._lock:
            return dict(self._last) if self._last is not None else None

    def recent_records(self) -> list:
        """The last ``MAX_RECENT`` dispatch records, oldest first (a
        snapshot) — pulled by the flight recorder at dump time."""
        with self._lock:
            return [dict(r) for r in self._recent]

    def slo_counters(self) -> dict:
        """The scalar counters the SLO engine snapshots per tick."""
        with self._lock:
            return {
                "busy_s": self._busy_s,
                "dispatches": self._n_dispatches,
            }

    def duty_cycle(self):
        """Device-busy fraction of wall time since this object started
        (None before the first dispatch); clamped at 1.0 — overlapping
        host-observed intervals cannot mean >100% busy."""
        with self._lock:
            busy = self._busy_s
            n = self._n_dispatches
        if not n:
            return None
        elapsed = time.monotonic() - self._t_started
        return min(busy / elapsed, 1.0) if elapsed > 0 else None

    def duty_cycle_by_device(self) -> dict:
        """{device_label: busy fraction of wall time} over the chips
        any observed dispatch spanned (same clamp semantics as the
        blended :meth:`duty_cycle`)."""
        with self._lock:
            busy = dict(self._busy_by_device)
        elapsed = time.monotonic() - self._t_started
        if elapsed <= 0:
            return {}
        return {
            dev: min(b / elapsed, 1.0) for dev, b in sorted(busy.items())
        }

    def per_device(self) -> dict:
        """The per-chip telemetry rows: busy seconds, dispatch count,
        duty cycle, live-buffer high-water (upper bound — replicated
        buffers are full-size per chip), and the chip's own allocator
        peak when the backend reports one."""
        duty = self.duty_cycle_by_device()
        with self._lock:
            labels = set(self._busy_by_device) | set(
                self._backend_peak_by_device
            )
            return {
                dev: {
                    "busy_s": round(self._busy_by_device.get(dev, 0.0), 6),
                    "n_dispatches": self._n_by_device.get(dev, 0),
                    "duty_cycle": (
                        round(duty[dev], 6) if dev in duty else None
                    ),
                    "live_buffer_highwater_bytes": (
                        self._live_hw_by_device.get(dev, 0)
                    ),
                    "backend_peak_bytes": (
                        self._backend_peak_by_device.get(dev)
                    ),
                }
                for dev in sorted(labels)
            }

    def ceiling_counts(self) -> dict:
        with self._lock:
            return dict(sorted(self._ceiling_counts.items()))

    def mean_roofline_pct(self) -> dict:
        """{ceiling: mean roofline_pct over the STEADY-STATE dispatches
        it bound} (compile-carrying dispatches excluded)."""
        with self._lock:
            return {
                c: self._pct_sum[c] / n
                for c, n in sorted(self._pct_n.items())
                if n
            }

    def signature_table(self) -> list:
        """The per-signature roofline table, most-dispatched first.
        Rows prefer steady-state records; a signature whose only
        dispatches carried a compile falls back to those (flagged by
        ``steady: false``) — either way every row reports a non-null
        binding ceiling and roofline_pct (the DEVICE_PROFILE
        acceptance gate)."""
        with self._lock:
            rows = []
            for sig, agg in self._sigs.items():
                steady = agg["n_steady"] > 0
                last = (agg["last"] if steady else agg["last_any"]) or {}
                mean_s = (
                    agg["steady_s"] / agg["n_steady"] if steady
                    else agg["any_s"] / max(agg["n"], 1)
                )
                rows.append({
                    "sig": sig,
                    "n_dispatches": agg["n"],
                    "n_compile_dispatches": agg["n_compiled"],
                    "n_requests": agg["n_requests"],
                    "steady": steady,
                    "device_ms_mean": round(mean_s * 1e3, 4),
                    "flops_per_dispatch": last.get("flops"),
                    "mxu_flops_per_dispatch": last.get("mxu_flops"),
                    "hbm_bytes_per_dispatch": last.get("hbm_bytes"),
                    "ai_flops_per_byte": last.get("ai_flops_per_byte"),
                    "achieved_tflops": last.get("achieved_tflops"),
                    "achieved_GBps": last.get("achieved_GBps"),
                    "binding_ceiling": last.get("binding_ceiling"),
                    "roofline_pct": last.get("roofline_pct"),
                    "roofline_pct_mean": round(
                        agg["pct_sum"] / agg["n_steady"], 4
                    ) if steady else last.get("roofline_pct"),
                    "ceilings": dict(sorted(agg["ceilings"].items())),
                    "cost_source": last.get("cost_source"),
                })
        rows.sort(key=lambda r: -r["n_dispatches"])
        return rows

    def summary(self) -> dict:
        duty = self.duty_cycle()
        pct = self.mean_roofline_pct()
        table = self.signature_table()
        per_device = self.per_device()
        with self._lock:
            return {
                "n_dispatches": self._n_dispatches,
                "n_requests": self._n_requests,
                "n_compile_dispatches": self._n_compiled,
                "busy_s": round(self._busy_s, 6),
                "launch_s": round(self._launch_s, 6),
                "readback_s": round(self._readback_s, 6),
                "duty_cycle": round(duty, 6) if duty is not None else None,
                "flops_total": self._flops_total,
                "hbm_bytes_total": self._bytes_total,
                "binding_ceiling_counts": dict(
                    sorted(self._ceiling_counts.items())
                ),
                "roofline_pct_mean": {
                    k: round(v, 4) for k, v in pct.items()
                },
                "memory": {
                    "live_buffer_highwater_bytes": self._live_bytes_hw,
                    "backend_peak_bytes": self._backend_peak_bytes,
                },
                "per_device": per_device,
                "signatures": table,
                "signature_drops": self._sig_drops,
            }

    def log_summary(self, level=logging.INFO):
        s = self.summary()
        if not s["n_dispatches"]:
            return
        logger.log(
            level,
            "device: dispatches=%d duty=%s GB=%.3f ceilings=%s "
            "roofline_pct=%s",
            s["n_dispatches"],
            s["duty_cycle"],
            s["hbm_bytes_total"] / 1e9,
            s["binding_ceiling_counts"],
            s["roofline_pct_mean"],
        )


class StoreStats:
    """Storage-plane accounting for the FileTrials queue, the response
    journal, and the lease protocol — the one telemetry plane that had
    none (ISSUE 9), and the before/after evidence the segmented-store
    roadmap item will be judged against.

    Every durability-relevant filesystem operation lands here:

    - **fsyncs** — count + fixed-bucket latency histogram + bytes, by
      ``kind`` (``doc``/``segment``/``journal``/``attachment``/
      ``counter``/``lease``/``bundle``) — the SL606 objective's input;
    - **segments** — appends (write calls vs records: the group-commit
      ratio), seals, compactions, O(delta) replays + their record
      counts, torn records, and replica pulls of the segmented trial
      store (the committed before/after proof for the per-doc →
      segment migration);
    - **doc writes** — trial-doc inserts/rewrites and their encoded
      bytes (reconciles against trial counts: one insert + one result
      write per completed trial on the service path);
    - **directory scans** — every O(N) ``all_docs``/native state scan,
      with entries scanned (the cost ``refresh_local`` exists to dodge);
    - **refreshes** — local (in-memory recompute) vs full (disk
      re-read); the local hit rate is the single-writer fast path
      working as designed;
    - **journal** — appends/bytes/compactions/torn lines of the
      exactly-once response journal;
    - **leases** — grants/renewals/reaps/clears;
    - **quarantines** — torn docs moved aside by ``_read_doc``.

    A bounded ring of recent notable ops (every fsync, with kind,
    latency, and bytes) feeds the flight recorder at dump time.

    Thread-safe: handler/scheduler/reaper/worker threads record while
    ``/metrics`` renders concurrently.
    """

    MAX_RECENT_OPS = 256

    # lock-order: _lock
    def __init__(self):
        from collections import deque

        self._lock = threading.Lock()
        self._fsync_hist = LatencyHistogram(FSYNC_DURATION_BUCKETS)  # guarded-by: _lock
        self._fsync_kinds = defaultdict(int)  # guarded-by: _lock
        self._fsync_bytes = 0  # guarded-by: _lock
        self._doc_writes = 0  # guarded-by: _lock
        self._doc_write_bytes = 0  # guarded-by: _lock
        self._attachment_writes = 0  # guarded-by: _lock
        self._attachment_bytes = 0  # guarded-by: _lock
        self._scans = 0  # guarded-by: _lock
        self._scan_entries = 0  # guarded-by: _lock
        self._refresh_local = 0  # guarded-by: _lock
        self._refresh_full = 0  # guarded-by: _lock
        self._journal_appends = 0  # guarded-by: _lock
        self._journal_bytes = 0  # guarded-by: _lock
        self._journal_compactions = 0  # guarded-by: _lock
        self._journal_torn = 0  # guarded-by: _lock
        self._lease_events = defaultdict(int)  # guarded-by: _lock
        self._quarantined = 0  # guarded-by: _lock
        # segmented trial store (parallel.segment_store)
        self._segment_appends = 0  # guarded-by: _lock  (write calls)
        self._segment_records = 0  # guarded-by: _lock  (docs appended)
        self._segment_bytes = 0  # guarded-by: _lock
        self._segment_seals = 0  # guarded-by: _lock
        self._segment_compactions = 0  # guarded-by: _lock
        self._segments_retired = 0  # guarded-by: _lock
        self._segment_replays = 0  # guarded-by: _lock  (refresh calls)
        self._segment_replays_full = 0  # guarded-by: _lock
        self._segment_replay_records = 0  # guarded-by: _lock  (delta docs)
        self._segment_torn = 0  # guarded-by: _lock
        self._segments_pulled = 0  # guarded-by: _lock  (replication)
        self._segment_pull_bytes = 0  # guarded-by: _lock
        self._recent_ops = deque(maxlen=self.MAX_RECENT_OPS)  # guarded-by: _lock

    # -- recording -----------------------------------------------------
    def record_fsync(self, seconds: float, kind: str = "doc",
                     nbytes: int = 0):
        with self._lock:
            self._fsync_hist.observe(float(seconds))
            self._fsync_kinds[str(kind)] += 1
            self._fsync_bytes += int(nbytes)
            self._recent_ops.append({
                "op": "fsync", "kind": str(kind),
                "seconds": round(float(seconds), 6),
                "bytes": int(nbytes), "t": time.time(),
            })

    def record_doc_write(self, nbytes: int):
        with self._lock:
            self._doc_writes += 1
            self._doc_write_bytes += int(nbytes)

    def record_attachment_write(self, nbytes: int):
        with self._lock:
            self._attachment_writes += 1
            self._attachment_bytes += int(nbytes)

    def record_scan(self, n_entries: int):
        with self._lock:
            self._scans += 1
            self._scan_entries += int(n_entries)

    def record_refresh(self, local: bool):
        with self._lock:
            if local:
                self._refresh_local += 1
            else:
                self._refresh_full += 1

    def record_journal_append(self, nbytes: int):
        with self._lock:
            self._journal_appends += 1
            self._journal_bytes += int(nbytes)

    def record_journal_compaction(self, nbytes: int = 0):
        with self._lock:
            self._journal_compactions += 1

    def record_journal_torn(self, n: int = 1):
        with self._lock:
            self._journal_torn += int(n)

    def record_segment_append(self, n_records: int, nbytes: int):
        """One segment write call (group commit): ``n_records``
        trial-state transitions landed in ONE O_APPEND write."""
        with self._lock:
            self._segment_appends += 1
            self._segment_records += int(n_records)
            self._segment_bytes += int(nbytes)

    def record_segment_seal(self, n: int = 1):
        with self._lock:
            self._segment_seals += int(n)

    def record_segment_compaction(self, n_retired: int = 0):
        with self._lock:
            self._segment_compactions += 1
            self._segments_retired += int(n_retired)

    def record_segment_replay(self, n_records: int, full: bool = False):
        """One O(delta) tail refresh replaying ``n_records`` docs
        (``full``: a from-scratch replay — initial load or a
        post-compaction epoch change)."""
        with self._lock:
            self._segment_replays += 1
            if full:
                self._segment_replays_full += 1
            self._segment_replay_records += int(n_records)

    def record_segment_torn(self, n: int = 1):
        with self._lock:
            self._segment_torn += int(n)

    def record_segment_pull(self, n_segments: int, nbytes: int):
        """Sealed segments shipped to a replica by SegmentMirror."""
        with self._lock:
            self._segments_pulled += int(n_segments)
            self._segment_pull_bytes += int(nbytes)

    def record_lease(self, event: str, n: int = 1):
        """``event``: grant | renew | reap | clear | quarantine."""
        with self._lock:
            self._lease_events[str(event)] += int(n)

    def record_quarantine(self, n: int = 1):
        with self._lock:
            self._quarantined += int(n)

    # -- reading -------------------------------------------------------
    def fsync_hist_state(self) -> dict:
        with self._lock:
            return self._fsync_hist.state()

    def fsync_histogram_dict(self) -> dict:
        with self._lock:
            return self._fsync_hist.to_dict()

    def slo_counters(self) -> dict:
        """The scalar counters the SLO engine snapshots per tick —
        ``store_bad`` is the SL605 zero-tolerance numerator (torn
        journal lines + quarantined docs)."""
        with self._lock:
            return {
                "store_bad": (
                    self._journal_torn + self._quarantined
                    + self._segment_torn
                ),
                "fsyncs_total": sum(self._fsync_kinds.values()),
            }

    def recent_ops(self) -> list:
        """The last ``MAX_RECENT_OPS`` store operations, oldest first
        (a snapshot) — pulled by the flight recorder at dump time."""
        with self._lock:
            return [dict(o) for o in self._recent_ops]

    def summary(self) -> dict:
        with self._lock:
            p50 = self._fsync_hist.quantile(0.50)
            p99 = self._fsync_hist.quantile(0.99)
            n_refresh = self._refresh_local + self._refresh_full
            return {
                "fsyncs": dict(sorted(self._fsync_kinds.items())),
                "fsyncs_total": sum(self._fsync_kinds.values()),
                "fsync_bytes_total": self._fsync_bytes,
                "fsync_p50_ms": (
                    round(p50 * 1e3, 4) if p50 is not None else None
                ),
                "fsync_p99_ms": (
                    round(p99 * 1e3, 4) if p99 is not None else None
                ),
                "fsync_sum_s": round(self._fsync_hist.sum_s, 6),
                "doc_writes": self._doc_writes,
                "doc_write_bytes": self._doc_write_bytes,
                "attachment_writes": self._attachment_writes,
                "attachment_bytes": self._attachment_bytes,
                "scans": self._scans,
                "scan_entries": self._scan_entries,
                "refresh_local": self._refresh_local,
                "refresh_full": self._refresh_full,
                "refresh_local_hit_rate": (
                    round(self._refresh_local / n_refresh, 4)
                    if n_refresh else None
                ),
                "journal_appends": self._journal_appends,
                "journal_bytes": self._journal_bytes,
                "journal_compactions": self._journal_compactions,
                "journal_torn_lines": self._journal_torn,
                "segment_appends": self._segment_appends,
                "segment_records": self._segment_records,
                "segment_bytes": self._segment_bytes,
                "segment_seals": self._segment_seals,
                "segment_compactions": self._segment_compactions,
                "segments_retired": self._segments_retired,
                "segment_replays": self._segment_replays,
                "segment_replays_full": self._segment_replays_full,
                "segment_replay_records": self._segment_replay_records,
                "segment_torn_lines": self._segment_torn,
                "segments_pulled": self._segments_pulled,
                "segment_pull_bytes": self._segment_pull_bytes,
                "lease_events": dict(sorted(self._lease_events.items())),
                "quarantined_docs": self._quarantined,
            }

    def log_summary(self, level=logging.INFO):
        s = self.summary()
        if not s["fsyncs_total"] and not s["scans"]:
            return
        logger.log(
            level,
            "store: fsyncs=%d (p99 %sms) doc_writes=%d scans=%d "
            "(entries=%d) refresh_local_rate=%s journal_appends=%d",
            s["fsyncs_total"], s["fsync_p99_ms"], s["doc_writes"],
            s["scans"], s["scan_entries"], s["refresh_local_hit_rate"],
            s["journal_appends"],
        )


def build_info() -> dict:
    """{"version", "jax", "backend"} — the identity labels of the
    ``hyperopt_build_info`` gauge, so a scrape (or a flight-recorder
    bundle) says WHAT it measured.  Never imports jax eagerly: an
    uninitialized backend reports "uninitialized" rather than paying
    (or worse, hanging on) device init inside a metrics render."""
    import sys as _sys

    try:
        from . import __version__ as version
    except ImportError:  # pragma: no cover - defensive
        version = "unknown"
    jax_mod = _sys.modules.get("jax")
    jax_version = getattr(jax_mod, "__version__", None) or "not-imported"
    backend = "uninitialized"
    if jax_mod is not None:
        try:
            from jax._src import xla_bridge

            if xla_bridge._backends:
                backend = jax_mod.devices()[0].platform
        except Exception:  # pragma: no cover - defensive
            backend = "unknown"
    return {
        "version": str(version),
        "jax": str(jax_version),
        "backend": str(backend),
    }


# ---------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------


def _prom_escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _prom_value(v) -> str:
    if v is None:
        return "NaN"
    return repr(float(v))


def render_prometheus(
    timings: "PhaseTimings" = None,
    speculation: "SpeculationStats" = None,
    faults: "FaultStats" = None,
    service: "ServiceStats" = None,
    device: "DeviceStats" = None,
    study_health: dict = None,
    store: "StoreStats" = None,
    slo: list = None,
    control: dict = None,
    build: dict = None,
    extra: dict = None,
    namespace: str = "hyperopt",
):
    """Render the observability counters in the Prometheus text
    exposition format (version 0.0.4) — the payload of the optimization
    server's ``/metrics`` endpoint, and usable standalone for any run
    that holds these stats objects.

    Every argument is optional; only the sections passed render.
    ``extra`` is a flat ``{metric_suffix: scalar}`` dict rendered as
    gauges (for ad-hoc gauges like process uptime).

    ``study_health``: ``{"rows": [...], "truncated_total": int}`` — the
    per-study search-health gauge block.  Each row is one
    :meth:`hyperopt_tpu.diagnostics.SearchStats.metrics_row` dict; the
    CALLER bounds the row count (top-N studies by recency — see
    ``OptimizationService.metrics_text``), and ``truncated_total``
    counts the studies dropped by that bound so a million-study fleet
    can never blow up the exposition unnoticed.

    ``store``: a :class:`StoreStats` — the storage-plane gauge block.
    ``slo``: a list of SLO rule rows (``hyperopt_tpu.slo.SloEngine
    .metrics_rows``) — status/burn-rate/breaches per SL6xx rule.
    ``control``: the control-plane block
    (``hyperopt_tpu.control.ControlStats.control_metrics``) —
    self-tuning decision counters, the last objective, the frozen
    flag, and the SH5xx admission-reclaim counter.
    ``build``: the :func:`build_info` labels dict — one
    ``hyperopt_build_info{version,jax,backend} 1`` identity gauge.
    """
    lines = []

    def head(name, help_text, kind):
        lines.append(f"# HELP {namespace}_{name} {help_text}")
        lines.append(f"# TYPE {namespace}_{name} {kind}")

    def sample(name, labels, value):
        if labels:
            lbl = ",".join(
                f'{k}="{_prom_escape(v)}"' for k, v in sorted(labels.items())
            )
            lines.append(f"{namespace}_{name}{{{lbl}}} {_prom_value(value)}")
        else:
            lines.append(f"{namespace}_{name} {_prom_value(value)}")

    if timings is not None:
        summ = timings.summary()
        head("phase_seconds_total", "Accumulated wall-clock per driver phase.", "counter")
        for phase, st in summ.items():
            sample("phase_seconds_total", {"phase": phase}, st["total_s"])
        head("phase_count_total", "Invocations per driver phase.", "counter")
        for phase, st in summ.items():
            sample("phase_count_total", {"phase": phase}, st["count"])

    if speculation is not None:
        s = speculation.summary()
        head("speculation_seconds_total",
             "Pipelined-suggest time split into hidden vs exposed.", "counter")
        sample("speculation_seconds_total", {"kind": "hidden"}, s["hidden_s"])
        sample("speculation_seconds_total", {"kind": "exposed"}, s["exposed_s"])
        head("speculation_events_total",
             "Pipelined-suggest engine event counts.", "counter")
        for key in (
            "n_dispatched", "n_hypothesis", "n_used", "n_invalidated",
            "n_sync", "n_discarded",
        ):
            sample("speculation_events_total", {"event": key[2:]}, s[key])

    if faults is not None:
        counts = faults.counts()
        head("fault_events_total",
             "Fault-tolerance recovery and chaos-injection events.", "counter")
        for event, n in counts.items():
            sample("fault_events_total", {"event": event}, n)
        head("fault_backoff_seconds_total",
             "Accumulated retry-backoff sleep.", "counter")
        sample("fault_backoff_seconds_total", None, faults.backoff_s)

    def histogram(name, help_text, hist_dict):
        head(name, help_text, "histogram")
        for edge, cum in hist_dict["buckets"]:
            le = "+Inf" if edge == float("inf") else repr(float(edge))
            lines.append(f'{namespace}_{name}_bucket{{le="{le}"}} {cum}')
        lines.append(
            f"{namespace}_{name}_sum {_prom_value(hist_dict['sum_s'])}"
        )
        lines.append(f"{namespace}_{name}_count {hist_dict['count']}")

    if service is not None:
        s = service.summary()
        head("service_requests_total", "Requests served per endpoint.", "counter")
        for endpoint, n in s["requests"].items():
            sample("service_requests_total", {"endpoint": endpoint}, n)
        head("service_rejected_total",
             "Requests rejected with backpressure per endpoint.", "counter")
        for endpoint, n in s["rejected"].items():
            sample("service_rejected_total", {"endpoint": endpoint}, n)
        head("service_errors_total",
             "Requests that failed server-side (5xx/504) per endpoint.",
             "counter")
        for endpoint, n in s.get("errors", {}).items():
            sample("service_errors_total", {"endpoint": endpoint}, n)
        head("service_idempotent_replays_total",
             "Retried requests answered from the response journal.",
             "counter")
        for endpoint, n in s.get("idempotent_replays", {}).items():
            sample(
                "service_idempotent_replays_total",
                {"endpoint": endpoint}, n,
            )
        head("service_study_suggests_total",
             "Suggest requests served per study.", "counter")
        for study, n in s["study_suggests"].items():
            sample("service_study_suggests_total", {"study": study}, n)
        head("service_dispatches_total",
             "Fused device suggest programs launched.", "counter")
        sample("service_dispatches_total", None, s["n_dispatches"])
        head("service_batched_suggests_total",
             "Suggest requests served through a fused dispatch.", "counter")
        sample("service_batched_suggests_total", None, s["n_batched_suggests"])
        head("service_inline_suggests_total",
             "Suggest requests served host-side (startup/random).", "counter")
        sample("service_inline_suggests_total", None, s["n_inline_suggests"])
        hist = service.histogram_dict()
        head("service_suggest_duration_seconds",
             "Suggest latency histogram (fixed buckets, no eviction — "
             "the exported quantile source of truth).", "histogram")
        for edge, cum in hist["buckets"]:
            le = "+Inf" if edge == float("inf") else repr(float(edge))
            lines.append(
                f'{namespace}_service_suggest_duration_seconds_bucket'
                f'{{le="{le}"}} {cum}'
            )
        lines.append(
            f"{namespace}_service_suggest_duration_seconds_sum "
            f"{_prom_value(hist['sum_s'])}"
        )
        lines.append(
            f"{namespace}_service_suggest_duration_seconds_count "
            f"{hist['count']}"
        )
        head("service_suggest_phase_seconds_total",
             "Suggest wall-time attributed to a named phase "
             "(queue_wait/coalesce/draw/prepare/dispatch/readback/"
             "finish/inline).", "counter")
        for phase, st in s.get("phase_seconds", {}).items():
            sample("service_suggest_phase_seconds_total",
                   {"phase": phase}, st["total_s"])
        head("compile_events_total",
             "XLA (re)compiles of the fused suggest program, keyed by "
             "(trial-count bucket, family composition).", "counter")
        for key, n in s.get("compile_events", {}).items():
            bucket, _, families = key.partition("/")
            sample("compile_events_total",
                   {"bucket": bucket, "families": families}, n)
        head("service_batch_occupancy",
             "Mean suggest requests per fused device dispatch.", "gauge")
        sample("service_batch_occupancy", None, s["mean_batch_occupancy"])
        head("service_queue_depth", "Scheduler queue depth (last observed).", "gauge")
        sample("service_queue_depth", None, s["queue_depth"])
        head("service_studies", "Registered studies.", "gauge")
        sample("service_studies", None, s["n_studies"])
        head("service_suggest_latency_ms",
             "Suggest latency quantiles derived from the duration "
             "histogram (kept for dashboard compatibility).", "gauge")
        for q_key, q_name in (("p50_ms", "0.5"), ("p99_ms", "0.99")):
            sample(
                "service_suggest_latency_ms",
                {"quantile": q_name},
                s["suggest_latency"][q_key],
            )
        head("service_suggest_split_latency_ms",
             "Suggest latency quantiles split by first-touch attribution "
             "(cold = the fused dispatch carried an XLA compile; warm = "
             "steady state).", "gauge")
        for split in ("warm", "cold"):
            for q_key, q_name in (("p50_ms", "0.5"), ("p99_ms", "0.99")):
                sample(
                    "service_suggest_split_latency_ms",
                    {"split": split, "quantile": q_name},
                    s[f"suggest_latency_{split}"][q_key],
                )
        head("service_suggest_split_total",
             "Suggests served per first-touch attribution class.",
             "counter")
        for split in ("warm", "cold"):
            sample("service_suggest_split_total", {"split": split},
                   s[f"suggest_latency_{split}"]["count"])

    if device is not None:
        s = device.summary()
        head("device_dispatches_total",
             "Fused device programs observed by the roofline profiler.",
             "counter")
        sample("device_dispatches_total", None, s["n_dispatches"])
        head("device_busy_seconds_total",
             "Host-observed device-busy seconds (dispatch to resolve).",
             "counter")
        sample("device_busy_seconds_total", None, s["busy_s"])
        head("device_duty_cycle",
             "Device-busy fraction of wall time since stats start: the "
             "unlabeled series blends all chips; {device=...} series "
             "split per chip (mesh execution mode) — a chip only "
             "reached by single-chip traffic, or skipped by the mesh, "
             "shows as the outlier instead of blending in.", "gauge")
        sample("device_duty_cycle", None, s["duty_cycle"])
        for dev, row in s["per_device"].items():
            if row["duty_cycle"] is not None:
                sample("device_duty_cycle", {"device": dev},
                       row["duty_cycle"])
        head("device_hbm_bytes_total",
             "Modeled HBM bytes moved by observed dispatches.", "counter")
        sample("device_hbm_bytes_total", None, s["hbm_bytes_total"])
        head("device_flops_total",
             "Modeled FLOPs executed by observed dispatches.", "counter")
        sample("device_flops_total", None, s["flops_total"])
        head("device_binding_dispatches_total",
             "Dispatches per binding roofline ceiling "
             "(hbm_bw = bandwidth-bound, flops = compute-bound).",
             "counter")
        for ceiling, n in s["binding_ceiling_counts"].items():
            sample("device_binding_dispatches_total",
                   {"ceiling": ceiling}, n)
        head("device_roofline_pct",
             "Mean achieved fraction (percent) of the BINDING ceiling, "
             "per ceiling, over the dispatches it bound.", "gauge")
        for ceiling, pct in s["roofline_pct_mean"].items():
            sample("device_roofline_pct", {"ceiling": ceiling}, pct)
        head("device_memory_highwater_bytes",
             "Memory high-water: live program buffers (inputs+output of "
             "one dispatch) and backend allocator peak when reported; "
             "{device=...} series split per chip (allocator peaks are "
             "genuinely per-chip; live-buffer rows are an upper bound — "
             "replicated history buffers are full-size on every mesh "
             "device).", "gauge")
        mem = s["memory"]
        sample("device_memory_highwater_bytes",
               {"kind": "live_buffers"},
               mem["live_buffer_highwater_bytes"])
        if mem["backend_peak_bytes"] is not None:
            sample("device_memory_highwater_bytes",
                   {"kind": "backend_peak"}, mem["backend_peak_bytes"])
        for dev, row in s["per_device"].items():
            if row["live_buffer_highwater_bytes"]:
                sample("device_memory_highwater_bytes",
                       {"kind": "live_buffers", "device": dev},
                       row["live_buffer_highwater_bytes"])
            if row["backend_peak_bytes"] is not None:
                sample("device_memory_highwater_bytes",
                       {"kind": "backend_peak", "device": dev},
                       row["backend_peak_bytes"])

    if study_health is not None:
        rows = study_health.get("rows", ())
        gauges = (
            ("study_best_loss", "best_loss",
             "Best (lowest) finite reported loss per study."),
            ("study_regret", "regret",
             "Simple regret (best loss minus the known optimum) per "
             "study; NaN when no optimum was declared."),
            ("study_gamma", "gamma",
             "TPE gamma quantile of the study's latest fused suggest."),
            ("study_n_below", "n_below",
             "Below-set size of the study's latest fused suggest."),
            ("study_ei_max", "ei_max",
             "Max EI log-ratio over candidates, latest fused suggest "
             "(max over dimensions)."),
            ("study_ei_flatness", "ei_flatness",
             "EI landscape flatness (max minus log-mean-exp score; ~0 "
             "means no candidate ranks above any other), mean over "
             "dimensions."),
        )
        for metric, key, help_text in gauges:
            head(metric, help_text, "gauge")
            for row in rows:
                sample(metric, {"study": row["study"]}, row.get(key))
        head("study_health",
             "Per-study SH5xx search-health verdict (1 on the current "
             "state).", "gauge")
        for row in rows:
            sample(
                "study_health",
                {"study": row["study"], "state": row["state"]}, 1,
            )
        head("studies_truncated_total",
             "Studies omitted from the per-study gauge families by the "
             "cardinality bound (top-N by recency).", "counter")
        sample("studies_truncated_total", None,
               study_health.get("truncated_total", 0))

    if store is not None:
        s = store.summary()
        head("store_fsyncs_total",
             "Storage-plane fsyncs by kind (doc/segment/journal/"
             "attachment/counter/lease/bundle).", "counter")
        for kind, n in s["fsyncs"].items():
            sample("store_fsyncs_total", {"kind": kind}, n)
        histogram("store_fsync_duration_seconds",
                  "fsync latency histogram across the storage plane "
                  "(the SL606 objective's input).",
                  store.fsync_histogram_dict())
        head("store_fsync_bytes_total",
             "Bytes written through fsync'd storage-plane writes.",
             "counter")
        sample("store_fsync_bytes_total", None, s["fsync_bytes_total"])
        head("store_doc_writes_total",
             "Trial-doc writes (inserts + state rewrites).", "counter")
        sample("store_doc_writes_total", None, s["doc_writes"])
        head("store_doc_write_bytes_total",
             "Encoded bytes of trial-doc writes.", "counter")
        sample("store_doc_write_bytes_total", None, s["doc_write_bytes"])
        head("store_attachment_writes_total",
             "Attachment blob writes (config, seed cursor, ...).",
             "counter")
        sample("store_attachment_writes_total", None,
               s["attachment_writes"])
        head("store_scans_total",
             "O(N) trial-directory scans (all_docs / native state "
             "scans) — the cost refresh_local exists to dodge.",
             "counter")
        sample("store_scans_total", None, s["scans"])
        head("store_scan_entries_total",
             "Directory entries touched by those scans.", "counter")
        sample("store_scan_entries_total", None, s["scan_entries"])
        head("store_refresh_total",
             "Trials view refreshes: local (in-memory recompute) vs "
             "full (disk re-read).", "counter")
        sample("store_refresh_total", {"kind": "local"},
               s["refresh_local"])
        sample("store_refresh_total", {"kind": "full"}, s["refresh_full"])
        head("store_journal_appends_total",
             "Response-journal record appends (each one fsync'd).",
             "counter")
        sample("store_journal_appends_total", None, s["journal_appends"])
        head("store_journal_bytes_total",
             "Response-journal bytes appended.", "counter")
        sample("store_journal_bytes_total", None, s["journal_bytes"])
        head("store_journal_compactions_total",
             "Response-journal in-place compactions.", "counter")
        sample("store_journal_compactions_total", None,
               s["journal_compactions"])
        head("store_journal_torn_lines_total",
             "Torn response-journal lines seen at load (SL605 input).",
             "counter")
        sample("store_journal_torn_lines_total", None,
               s["journal_torn_lines"])
        head("store_segment_appends_total",
             "Segment-log write calls (each ONE O_APPEND write + one "
             "fsync; a batch of docs group-commits as one).", "counter")
        sample("store_segment_appends_total", None, s["segment_appends"])
        head("store_segment_records_total",
             "Trial-state transitions appended to the segment log.",
             "counter")
        sample("store_segment_records_total", None, s["segment_records"])
        head("store_segment_bytes_total",
             "Bytes appended to the segment log.", "counter")
        sample("store_segment_bytes_total", None, s["segment_bytes"])
        head("store_segment_seals_total",
             "Segments sealed (made immutable and manifest-pinned).",
             "counter")
        sample("store_segment_seals_total", None, s["segment_seals"])
        head("store_segment_compactions_total",
             "Segment-log compactions (latest-doc-per-tid folds).",
             "counter")
        sample("store_segment_compactions_total", None,
               s["segment_compactions"])
        head("store_segments_retired_total",
             "Segments retired (unlinked) by compaction.", "counter")
        sample("store_segments_retired_total", None,
               s["segments_retired"])
        head("store_segment_replays_total",
             "O(delta) segment-tail refreshes, by scope.", "counter")
        sample("store_segment_replays_total", {"scope": "delta"},
               s["segment_replays"] - s["segment_replays_full"])
        sample("store_segment_replays_total", {"scope": "full"},
               s["segment_replays_full"])
        head("store_segment_replay_records_total",
             "Docs replayed by segment-tail refreshes (the delta cost "
             "that replaces O(N) directory scans).", "counter")
        sample("store_segment_replay_records_total", None,
               s["segment_replay_records"])
        head("store_segment_torn_lines_total",
             "Torn segment records seen at replay (SL605 input).",
             "counter")
        sample("store_segment_torn_lines_total", None,
               s["segment_torn_lines"])
        head("store_segments_pulled_total",
             "Sealed segments pulled by replica mirrors.", "counter")
        sample("store_segments_pulled_total", None, s["segments_pulled"])
        head("store_segment_pull_bytes_total",
             "Bytes shipped to replica mirrors as sealed segments.",
             "counter")
        sample("store_segment_pull_bytes_total", None,
               s["segment_pull_bytes"])
        head("store_lease_events_total",
             "Lease protocol events (grant/renew/reap/clear).", "counter")
        for event, n in s["lease_events"].items():
            sample("store_lease_events_total", {"event": event}, n)
        head("store_quarantined_docs_total",
             "Torn trial docs quarantined by the reader (SL605 input).",
             "counter")
        sample("store_quarantined_docs_total", None, s["quarantined_docs"])

    if slo is not None:
        head("slo_status",
             "Per-rule SLO status (1 = breaching, 0 = within "
             "objective; SL6xx catalog in docs/observability.md).",
             "gauge")
        for row in slo:
            sample("slo_status", {"rule": row["rule"]},
                   1 if row["status"] == "breach" else 0)
        head("slo_burn_rate",
             "Per-rule error-budget burn rate over the fast/slow "
             "windows (>= 1 means the objective is being violated at "
             "budget-exhausting speed).", "gauge")
        for row in slo:
            for window in ("fast", "slow"):
                sample("slo_burn_rate",
                       {"rule": row["rule"], "window": window},
                       row.get(f"burn_{window}"))
        head("slo_breaches_total",
             "Breach transitions (ok -> breach) per rule since start.",
             "counter")
        for row in slo:
            sample("slo_breaches_total", {"rule": row["rule"]},
                   row.get("breaches_total", 0))

    if control is not None:
        head("control_decisions_total",
             "Closed-loop controller decisions by outcome (proposed/"
             "applied/evaluated/discarded/reverted/held/rearmed).",
             "counter")
        for outcome, n in sorted(control.get("decisions", {}).items()):
            sample("control_decisions_total", {"outcome": outcome}, n)
        head("control_objective",
             "Last evaluated controller objective (weighted warm p99 + "
             "queue depth, duty-cycle tie-break; lower is better).",
             "gauge")
        sample("control_objective", None, control.get("objective"))
        head("control_frozen",
             "1 while the controller is frozen (post-revert backoff; "
             "knobs pinned to the static config).", "gauge")
        sample("control_frozen", None, control.get("frozen", 0))
        head("control_freezes_total",
             "Controller freeze transitions (breach- or exception-"
             "triggered reverts to the static config).", "counter")
        sample("control_freezes_total", None,
               control.get("freezes_total", 0))
        head("control_reclaimed_studies_total",
             "Admission slots reclaimed from SH5xx-stopped studies "
             "(per-study early_stop opt-in).", "counter")
        sample("control_reclaimed_studies_total", None,
               control.get("reclaimed_studies_total", 0))
        head("control_resumed_studies_total",
             "Stopped studies re-admitted via resume.", "counter")
        sample("control_resumed_studies_total", None,
               control.get("resumed_studies_total", 0))

    if build is not None:
        head("build_info",
             "Build/runtime identity (value is always 1; the labels "
             "are the information).", "gauge")
        sample("build_info", dict(build), 1)

    if extra:
        for key, value in sorted(extra.items()):
            head(key, "Ad-hoc gauge.", "gauge")
            sample(key, None, value)

    return "\n".join(lines) + "\n"


def timed_suggest(algo, timings: PhaseTimings):
    """Wrap a suggest function so each call lands in ``timings``."""

    @wraps(algo)
    def wrapper(new_ids, domain, trials, seed, *args, **kwargs):
        with timings.phase("suggest"):
            return algo(new_ids, domain, trials, seed, *args, **kwargs)

    return wrapper


def traced_suggest(algo, log_dir):
    """Wrap a suggest function in a ``jax.profiler.trace`` so its device
    kernels appear in TensorBoard/Perfetto traces under ``log_dir``."""
    import jax

    @wraps(algo)
    def wrapper(new_ids, domain, trials, seed, *args, **kwargs):
        with jax.profiler.trace(str(log_dir)):
            return algo(new_ids, domain, trials, seed, *args, **kwargs)

    return wrapper


@contextlib.contextmanager
def annotate(name):
    """Named region visible in device profiles (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
