"""Tracing & profiling hooks.

The reference has no built-in tracing (SURVEY.md §5) — only module loggers
and ``verbose`` flags.  This module goes further, per the survey's rebuild
note: per-phase driver timings plus ``jax.profiler`` integration so the
device-side suggest kernels can be traced on real TPUs (view with
TensorBoard or Perfetto).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import defaultdict
from functools import wraps

logger = logging.getLogger(__name__)


class PhaseTimings:
    """Accumulated wall-clock per driver phase (suggest / evaluate / ...)."""

    def __init__(self):
        self._total = defaultdict(float)
        self._count = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._total[name] += dt
            self._count[name] += 1

    def record(self, name, seconds):
        self._total[name] += seconds
        self._count[name] += 1

    def summary(self):
        return {
            name: {
                "total_s": round(self._total[name], 6),
                "count": self._count[name],
                "mean_ms": round(1e3 * self._total[name] / max(self._count[name], 1), 3),
            }
            for name in sorted(self._total)
        }

    def log_summary(self, level=logging.INFO):
        for name, stats in self.summary().items():
            logger.log(
                level,
                "phase %-12s total %8.3fs  n=%-5d mean %8.3fms",
                name,
                stats["total_s"],
                stats["count"],
                stats["mean_ms"],
            )


class SpeculationStats:
    """Overlap accounting for the pipelined suggest engine.

    Splits per-suggest wall-clock into **hidden** time (speculative
    dispatch work done while the user objective runs — off the critical
    path) and **exposed** time (work the driver had to wait for: resolving
    a speculative readback, or a fully synchronous suggest after a miss /
    invalidation).  ``hidden_s / (hidden_s + exposed_s)`` is the fraction
    of suggest cost the pipeline removed from the wall clock.
    """

    def __init__(self):
        self.dispatch_s = 0.0  # hidden: speculative launch (host marshal + jit dispatch)
        self.reissue_exposed_s = 0.0  # exposed: re-issue launched at consume time
        self.resolve_s = 0.0  # exposed: blocking readback of a used speculation
        self.sync_s = 0.0  # exposed: synchronous suggest (miss or no speculation)
        self.n_dispatched = 0
        self.n_hypothesis = 0
        self.n_used = 0
        self.n_invalidated = 0
        self.n_sync = 0
        self.n_discarded = 0

    def record_dispatch(self, seconds, hypothesis=False, exposed=False):
        # ``exposed``: the launch ran on the driver's critical path (an
        # invalidation re-issue at consume time), not behind an objective
        if exposed:
            self.reissue_exposed_s += seconds
        else:
            self.dispatch_s += seconds
        self.n_dispatched += 1
        if hypothesis:
            # fit against the hypothetical lands-above history (exact
            # when the prediction holds; see hyperopt_tpu.pipeline)
            self.n_hypothesis += 1

    def record_resolve(self, seconds):
        self.resolve_s += seconds
        self.n_used += 1

    def record_sync(self, seconds):
        self.sync_s += seconds
        self.n_sync += 1

    def record_invalidation(self, n=1):
        self.n_invalidated += n

    def record_discard(self, n=1):
        self.n_discarded += n

    @property
    def hidden_s(self):
        return self.dispatch_s

    @property
    def exposed_s(self):
        return self.resolve_s + self.sync_s + self.reissue_exposed_s

    def summary(self):
        total = self.hidden_s + self.exposed_s
        return {
            "hidden_s": round(self.hidden_s, 6),
            "exposed_s": round(self.exposed_s, 6),
            "hidden_frac": round(self.hidden_s / total, 4) if total else None,
            "resolve_s": round(self.resolve_s, 6),
            "sync_s": round(self.sync_s, 6),
            "reissue_exposed_s": round(self.reissue_exposed_s, 6),
            "n_dispatched": self.n_dispatched,
            "n_hypothesis": self.n_hypothesis,
            "n_used": self.n_used,
            "n_invalidated": self.n_invalidated,
            "n_sync": self.n_sync,
            "n_discarded": self.n_discarded,
        }

    def log_summary(self, level=logging.INFO):
        s = self.summary()
        logger.log(
            level,
            "speculation: hidden %.3fs exposed %.3fs (frac %s) "
            "dispatched=%d (hypothesis=%d) used=%d invalidated=%d "
            "sync=%d discarded=%d",
            s["hidden_s"],
            s["exposed_s"],
            s["hidden_frac"],
            s["n_dispatched"],
            s["n_hypothesis"],
            s["n_used"],
            s["n_invalidated"],
            s["n_sync"],
            s["n_discarded"],
        )


class FaultStats:
    """Fault-tolerance accounting for :mod:`hyperopt_tpu.resilience`.

    Every recovery event in the fault-tolerance layer — lease expiries and
    reclamations, retries and their backoff sleeps, quarantines, device
    re-initializations, CPU fallbacks, dropped stale results, and every
    chaos-injected fault (``chaos_*`` keys) — lands here, so a run can
    assert that injected faults and recoveries balance (the chaos
    campaign's accounting invariant).

    Counters are an open set keyed by event name; the well-known keys are

    - ``lease_expired`` / ``lease_reclaimed`` / ``lease_quarantined`` —
      reaper activity (expiries observed, trials re-queued, trials moved
      to ``JOB_STATE_ERROR`` after ``max_attempts``)
    - ``stale_lock_cleared`` — torn/orphaned lock files removed
    - ``trial_failure`` / ``trial_retried`` / ``trial_quarantined`` —
      retry-policy activity (plus ``backoff_s`` accumulated sleep)
    - ``objective_timeout`` — per-trial watchdog expiries
    - ``stale_result_dropped`` — a worker's result discarded because its
      lease had been reclaimed while it ran
    - ``heartbeat`` — lease renewals
    - ``device_error`` / ``device_reinit`` / ``cpu_fallback`` — device
      recovery activity
    - ``chaos_<site>`` — faults injected by the chaos harness

    Thread-safe: the reaper, worker threads, and the driver all record
    concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = defaultdict(int)
        self._backoff_s = 0.0

    def record(self, event: str, n: int = 1):
        with self._lock:
            self._counts[event] += n

    def record_backoff(self, seconds: float):
        with self._lock:
            self._backoff_s += float(seconds)

    def get(self, event: str) -> int:
        with self._lock:
            return self._counts.get(event, 0)

    @property
    def backoff_s(self) -> float:
        with self._lock:
            return self._backoff_s

    def counts(self) -> dict:
        """Snapshot of all counters (sorted, chaos keys included)."""
        with self._lock:
            return dict(sorted(self._counts.items()))

    def injected(self) -> dict:
        """Just the chaos-injected fault counters, keyed by site."""
        with self._lock:
            return {
                k[len("chaos_"):]: v
                for k, v in sorted(self._counts.items())
                if k.startswith("chaos_")
            }

    def merge(self, other: "FaultStats"):
        """Fold another FaultStats into this one (campaign aggregation)."""
        o = other.counts()
        ob = other.backoff_s
        with self._lock:
            for k, v in o.items():
                self._counts[k] += v
            self._backoff_s += ob

    def summary(self) -> dict:
        out = self.counts()
        out["backoff_s"] = round(self.backoff_s, 6)
        return out

    def log_summary(self, level=logging.INFO):
        s = self.summary()
        if len(s) == 1:  # only backoff_s, nothing happened
            return
        logger.log(
            level,
            "faults: %s",
            " ".join(f"{k}={v}" for k, v in s.items()),
        )


def timed_suggest(algo, timings: PhaseTimings):
    """Wrap a suggest function so each call lands in ``timings``."""

    @wraps(algo)
    def wrapper(new_ids, domain, trials, seed, *args, **kwargs):
        with timings.phase("suggest"):
            return algo(new_ids, domain, trials, seed, *args, **kwargs)

    return wrapper


def traced_suggest(algo, log_dir):
    """Wrap a suggest function in a ``jax.profiler.trace`` so its device
    kernels appear in TensorBoard/Perfetto traces under ``log_dir``."""
    import jax

    @wraps(algo)
    def wrapper(new_ids, domain, trials, seed, *args, **kwargs):
        with jax.profiler.trace(str(log_dir)):
            return algo(new_ids, domain, trials, seed, *args, **kwargs)

    return wrapper


@contextlib.contextmanager
def annotate(name):
    """Named region visible in device profiles (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
