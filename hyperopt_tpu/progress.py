"""Progress reporting for the fmin driver loop.

Reference parity (SURVEY.md §2 #20): ``hyperopt/progress.py`` —
``tqdm_progress_callback`` / ``no_progress_callback``; context managers
yielding an object with ``.update(n)`` and a ``.postfix`` attribute.
"""

import contextlib

from .std_out_err_redirect_tqdm import std_out_err_redirect_tqdm


class _ProgressHandle:
    def __init__(self, pbar=None):
        self._pbar = pbar

    def update(self, n):
        if self._pbar is not None:
            self._pbar.update(n)

    @property
    def postfix(self):
        return getattr(self._pbar, "postfix", None)

    @postfix.setter
    def postfix(self, value):
        if self._pbar is not None:
            self._pbar.set_postfix_str(str(value) if value is not None else "")


@contextlib.contextmanager
def tqdm_progress_callback(initial, total):
    from tqdm import tqdm

    with std_out_err_redirect_tqdm() as orig_stdout:
        with tqdm(
            total=total,
            initial=initial,
            file=orig_stdout,
            dynamic_ncols=True,
            unit="trial",
        ) as pbar:
            yield _ProgressHandle(pbar)


@contextlib.contextmanager
def no_progress_callback(initial, total):
    yield _ProgressHandle(None)


default_callback = tqdm_progress_callback
