"""Search-space DSL implementations + conditionality extraction.

Reference parity (SURVEY.md §2 #3): ``hyperopt/pyll_utils.py`` —
``validate_label`` (~L10-35), ``hp_choice``/``hp_pchoice`` (~L35-90),
``hp_uniform``…``hp_qlognormal``/``hp_randint``/``hp_uniformint``
(~L90-200), ``Cond``/``EQ``/``expr_to_config`` (~L200-280).

Every ``hp_*`` returns a graph of the canonical shape
``float|int(hyperopt_param(label, <dist>(...)))`` so that both the TPU space
compiler (``hyperopt_tpu.vectorize``) and the conditionality walker below can
pattern-match hyperparameters structurally.
"""

from __future__ import annotations

from functools import partial, wraps

from .exceptions import DuplicateLabel, InvalidSpaceError
from .pyll.base import Apply, Literal, as_apply, dfs, scope


def _scalar(v):
    """The plain numeric value of ``v`` (unwrapping a numeric Literal),
    or None when it is an expression we cannot validate statically."""
    if isinstance(v, Literal):
        v = v.obj
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        try:
            import numpy as _np

            if isinstance(v, (_np.integer, _np.floating)):
                return float(v)
        except ImportError:  # pragma: no cover
            pass
        return None
    return float(v)


def _label_str(label):
    return label.obj if isinstance(label, Literal) else label


def _check_bounds(label, low, high):
    """Construction-time guard: low < high (when both are static).  A
    violation fails on device as NaN many trials later; fail here with
    the offending label instead."""
    lo, hi = _scalar(low), _scalar(high)
    if lo is not None and hi is not None and lo >= hi:
        raise InvalidSpaceError(
            f"hyperparameter {_label_str(label)!r}: low={lo:g} must be "
            f"< high={hi:g}",
            label=_label_str(label),
        )


def _check_positive(label, name, value):
    v = _scalar(value)
    if v is not None and v <= 0:
        raise InvalidSpaceError(
            f"hyperparameter {_label_str(label)!r}: {name}={v:g} must be > 0",
            label=_label_str(label),
        )


def _check_choice_labels(label, options):
    """Construction-time duplicate-label guard for choice branches.

    One label naming two DISTINCT nodes across (or inside) branches
    would silently merge their observation histories; today that only
    surfaces at ``expr_to_config`` time (Domain construction) without
    saying *where*.  Detect it when the branches are assembled and name
    both branch paths.  Sharing one node object across branches remains
    legal (intentional conditional reuse)."""
    seen = {}  # label -> (node id, branch index, node)
    for i, opt in enumerate(options):
        try:
            branch = as_apply(opt)
        except Exception:
            continue  # not a pyll graph: nothing to collide with
        for node in dfs(branch):
            if getattr(node, "name", None) != "hyperopt_param":
                continue
            lb = node.pos_args[0].obj
            prev = seen.get(lb)
            if prev is None:
                seen[lb] = (id(node.pos_args[1]), i, node)
            elif prev[0] != id(node.pos_args[1]):
                where = (
                    f"branch {prev[1]} vs branch {i}" if prev[1] != i
                    else f"twice inside branch {i}"
                )
                raise DuplicateLabel(
                    f"label {lb!r} names two distinct hyperparameters "
                    f"under choice {_label_str(label)!r} ({where}); their "
                    f"observation histories would silently merge — give "
                    f"each a unique label, or share one node object for "
                    f"intentional reuse"
                )


def validate_label(f):
    @wraps(f)
    def wrapper(label, *args, **kwargs):
        is_real_string = isinstance(label, str)
        is_literal_string = isinstance(label, Literal) and isinstance(label.obj, str)
        if not is_real_string and not is_literal_string:
            raise TypeError("require string label", label)
        return f(label, *args, **kwargs)

    return wrapper


# ---------------------------------------------------------------------
# hp_* constructors
# ---------------------------------------------------------------------


@validate_label
def hp_choice(label, options):
    """Categorical choice among ``options`` (each may be a nested space)."""
    if isinstance(options, dict):
        raise TypeError(
            "hp.choice takes a list of options; for weighted choices use "
            "hp.pchoice, for named branches embed dicts in the list"
        )
    options = list(options)
    _check_choice_labels(label, options)
    ch = scope.hyperopt_param(label, scope.randint(len(options)))
    return scope.switch(ch, *options)


@validate_label
def hp_pchoice(label, p_options):
    """Weighted choice: ``p_options`` is a list of ``(prob, option)``."""
    p, options = list(zip(*p_options))
    if abs(sum(p) - 1.0) > 1e-5:
        raise ValueError(f"hp.pchoice probabilities must sum to 1, got {sum(p)}")
    _check_choice_labels(label, options)
    ch = scope.hyperopt_param(label, scope.categorical(list(p), len(options)))
    return scope.switch(ch, *options)


@validate_label
def hp_uniform(label, low, high):
    _check_bounds(label, low, high)
    return scope.float(scope.hyperopt_param(label, scope.uniform(low, high)))


@validate_label
def hp_quniform(label, low, high, q):
    _check_bounds(label, low, high)
    _check_positive(label, "q", q)
    return scope.float(scope.hyperopt_param(label, scope.quniform(low, high, q)))


@validate_label
def hp_uniformint(label, low, high, q=1.0):
    _check_bounds(label, low, high)
    _check_positive(label, "q", q)
    return scope.int(scope.hyperopt_param(label, scope.uniformint(low, high, q=q)))


@validate_label
def hp_loguniform(label, low, high):
    _check_bounds(label, low, high)
    return scope.float(scope.hyperopt_param(label, scope.loguniform(low, high)))


@validate_label
def hp_qloguniform(label, low, high, q):
    _check_bounds(label, low, high)
    _check_positive(label, "q", q)
    return scope.float(scope.hyperopt_param(label, scope.qloguniform(low, high, q)))


@validate_label
def hp_normal(label, mu, sigma):
    _check_positive(label, "sigma", sigma)
    return scope.float(scope.hyperopt_param(label, scope.normal(mu, sigma)))


@validate_label
def hp_qnormal(label, mu, sigma, q):
    _check_positive(label, "sigma", sigma)
    _check_positive(label, "q", q)
    return scope.float(scope.hyperopt_param(label, scope.qnormal(mu, sigma, q)))


@validate_label
def hp_lognormal(label, mu, sigma):
    _check_positive(label, "sigma", sigma)
    return scope.float(scope.hyperopt_param(label, scope.lognormal(mu, sigma)))


@validate_label
def hp_qlognormal(label, mu, sigma, q):
    _check_positive(label, "sigma", sigma)
    _check_positive(label, "q", q)
    return scope.float(scope.hyperopt_param(label, scope.qlognormal(mu, sigma, q)))


@validate_label
def hp_randint(label, *args):
    """``hp.randint(label, upper)`` or ``hp.randint(label, low, high)``."""
    if len(args) not in (1, 2):
        raise ValueError("randint requires 1 or 2 bound arguments")
    if len(args) == 1:
        _check_positive(label, "upper", args[0])
    else:
        _check_bounds(label, *args)
    return scope.hyperopt_param(label, scope.randint(*args))


# ---------------------------------------------------------------------
# Conditionality extraction
# ---------------------------------------------------------------------


class Cond:
    """A single condition ``<name> <op> <val>`` on a hyperparameter."""

    def __init__(self, name, val, op):
        self.op = op
        self.name = name
        self.val = val

    def __str__(self):
        return f"Cond{{{self.name} {self.op} {self.val}}}"

    __repr__ = __str__

    def __eq__(self, other):
        return (
            isinstance(other, Cond)
            and self.op == other.op
            and self.name == other.name
            and self.val == other.val
        )

    def __hash__(self):
        return hash((self.op, self.name, self.val))

    def __call__(self, memo):
        """Evaluate against a {label: value} assignment (None = inactive)."""
        if self.name not in memo:
            raise KeyError(self.name)
        v = memo[self.name]
        if v is None:
            return False
        if self.op == "=":
            return v == self.val
        if self.op == ">":
            return v > self.val
        if self.op == "<":
            return v < self.val
        raise NotImplementedError(f"condition op {self.op!r}")


EQ = partial(Cond, op="=")


def _expr_to_config(expr, conditions, hps):
    if expr.name == "switch":
        idx = expr.pos_args[0]
        options = expr.pos_args[1:]
        assert idx.name == "hyperopt_param", (
            "switch driven by a non-hyperparameter index is not a "
            "conditional search-space construct"
        )
        label = idx.pos_args[0].obj
        _expr_to_config(idx, conditions, hps)
        for ii, opt in enumerate(options):
            _expr_to_config(opt, conditions + (EQ(label, ii),), hps)
    elif expr.name == "hyperopt_param":
        label = expr.pos_args[0].obj
        node = expr.pos_args[1]
        if label in hps:
            if hps[label]["node"] is not node:
                raise DuplicateLabel(label)
            hps[label]["conditions"].add(conditions)
        else:
            hps[label] = {
                "node": node,
                "conditions": {conditions},
                "label": label,
            }
    else:
        for child in expr.inputs():
            _expr_to_config(child, conditions, hps)


def _simplify_conditions(hps):
    """If a label is reachable unconditionally, drop all other paths."""
    for v in hps.values():
        if () in v["conditions"]:
            v["conditions"] = {()}


def expr_to_config(expr, conditions, hps):
    """Populate ``hps`` with ``{label: {node, conditions, label}}``.

    ``conditions`` is the tuple of :class:`Cond` assumed true at ``expr``
    (use ``()`` at the root).  Each label's ``conditions`` is a *set of
    conjunctions* (DNF): the label is active if any conjunction holds.
    Raises :class:`DuplicateLabel` if one label names two distinct nodes.
    """
    if conditions is None:
        conditions = ()
    expr = as_apply(expr)
    _expr_to_config(expr, conditions, hps)
    _simplify_conditions(hps)
