"""Structured trials checkpointing via orbax (the SURVEY §7 option).

Reference parity: ``fmin(trials_save_file=...)`` pickles the whole
``Trials`` object every iteration (``hyperopt/fmin.py`` — ``FMinIter.run``
~L130-500, ``trials_save_file`` load ~L500-700).  That mechanism is kept
bit-for-bit (pickle path).  This module adds the TPU-native upgrade:
**versioned, atomic, retained** checkpoints through
``orbax.checkpoint.CheckpointManager`` —

- a crash mid-write can never lose the run: orbax finalizes each step
  with an atomic rename, so the previous step always survives (a torn
  pickle loses everything);
- steps are retained (``max_to_keep``) so a corrupted objective that
  poisons recent trials can be rolled back;
- trial docs are stored as JSON (the same ``$datetime``/``$bytes``
  sentinel codec as the FileTrials queue), so checkpoints are
  inspectable and not tied to pickle/Python versioning.

``fmin`` integration: pass ``trials_save_file`` ending in ``.orbax`` and
the driver saves through this module instead of pickle; resume works the
same way (point a fresh ``fmin`` at the same path).
"""

from __future__ import annotations

import json
import logging
import os
import pickle

from .base import SONify, Trials, trials_from_docs
from .parallel.file_trials import (
    _atomic_write,
    _json_default,
    _json_object_hook,
)

logger = logging.getLogger(__name__)


def is_orbax_path(path) -> bool:
    """fmin's dispatch rule for ``trials_save_file``."""
    return bool(path) and str(path).endswith(".orbax")


def atomic_pickle_dump(obj, path, protocol=-1):
    """Crash-safe pickle for the legacy ``trials_save_file`` path:
    temp file → flush → fsync → atomic rename (the queue's
    ``_atomic_write`` primitive).  A crash mid-save leaves the previous
    checkpoint intact instead of a torn pickle that loses the run."""
    _atomic_write(path, pickle.dumps(obj, protocol=protocol))


class TrialsCheckpointer:
    """Save/restore a ``Trials`` history as orbax-managed JSON steps."""

    def __init__(self, directory, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._last_step = self.manager.latest_step()
        self._last_fingerprint = None

    # -- encoding ------------------------------------------------------
    @staticmethod
    def _encode(docs):
        # SONify first (numpy scalars/arrays -> plain python), then the
        # sentinel codec for datetimes/bytes; round-trip through json so
        # the stored payload is guaranteed plain-JSON
        return json.loads(
            json.dumps(SONify(docs), default=_json_default, sort_keys=True)
        )

    @staticmethod
    def _decode(payload):
        return json.loads(
            json.dumps(payload), object_hook=_json_object_hook
        )

    @staticmethod
    def _fingerprint(trials):
        """Cheap change detector: doc count per state.  Async backends
        mutate existing docs in place (NEW → DONE with results) without
        growing the list, so a pure length check would stop saving once
        the last doc is enqueued and lose the final batch's losses."""
        counts = {}
        for doc in trials.trials:
            counts[doc["state"]] = counts.get(doc["state"], 0) + 1
        return (len(trials.trials), tuple(sorted(counts.items())))

    # -- API -----------------------------------------------------------
    def save(self, trials: Trials) -> bool:
        """Checkpoint the current history as the next step; returns
        False (no-op) if nothing changed since the last save."""
        fp = self._fingerprint(trials)
        if fp == self._last_fingerprint:
            return False
        step = (self._last_step or 0) + 1
        payload = {"format": 1, "docs": self._encode(trials.trials)}
        self.manager.save(step, args=self._ocp.args.JsonSave(payload))
        self.manager.wait_until_finished()
        self._last_step = step
        self._last_fingerprint = fp
        return True

    def _restore_step(self, step: int):
        """One step's decoded docs; raises on a corrupted/torn step."""
        payload = self.manager.restore(
            step, args=self._ocp.args.JsonRestore()
        )
        if not isinstance(payload, dict) or "docs" not in payload:
            raise ValueError(
                f"step {step}: malformed checkpoint payload "
                f"({type(payload).__name__}, no 'docs')"
            )
        return self._decode(payload["docs"])

    def restore(self, step: int | None = None, into: Trials | None = None):
        """Latest (or given) step; None if the directory has no steps.

        When no explicit ``step`` is requested and the latest step turns
        out to be corrupted or torn (a crash mid-finalization, a
        truncated filesystem, a poisoned payload), restore falls back to
        the previous retained steps in descending order instead of
        raising — losing one save interval beats losing the run.  An
        explicitly requested ``step`` still raises on corruption (the
        caller asked for that step, not "the newest readable one").

        ``into``: an EMPTY ``Trials`` (sub)instance to refill — preserves
        the caller's trials subclass and attachments, which a fresh
        ``trials_from_docs`` cannot (fmin's resume path uses this when
        the user passed their own trials object)."""
        if step is not None:
            docs = self._restore_step(int(step))
        else:
            steps = sorted(self.manager.all_steps(), reverse=True)
            if not steps:
                return None
            docs = None
            last_err = None
            for s in steps:
                try:
                    docs = self._restore_step(s)
                except Exception as e:
                    last_err = e
                    logger.warning(
                        "orbax restore: step %d unreadable (%s); falling "
                        "back to the previous retained step", s, e,
                    )
                else:
                    step = s
                    if s != steps[0]:
                        logger.warning(
                            "orbax restore: recovered from retained step "
                            "%d (latest step %d was corrupted)",
                            s, steps[0],
                        )
                    break
            if docs is None:
                raise last_err
        if into is not None:
            if len(into.trials):
                logger.warning(
                    "orbax restore: passed trials object is non-empty; "
                    "keeping it as-is (not refilling from step %d)", step,
                )
                return into
            into._insert_trial_docs(docs)
            into.refresh()
            return into
        return trials_from_docs(docs)

    def steps(self):
        return sorted(self.manager.all_steps())

    def close(self):
        self.manager.close()
