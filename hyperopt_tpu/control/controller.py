"""The closed-loop controller: hyperopt running *on* hyperopt.

A background thread inside :class:`~hyperopt_tpu.service.core
.OptimizationService` that treats the service's own serving knobs
(:mod:`.knobs`) as a bounded ``hp.*`` search space and its own SLO
telemetry (:mod:`.objective`) as the objective.  Each cycle:

1. **propose** — ``tpe.suggest`` over the controller's OWN ``Trials``
   (random warm-up for the first ``n_startup_jobs`` proposals, the
   Bergstra & Bengio exploration discipline), clamped to the guardrail
   bounds derived from the SL6xx catalog;
2. **apply** — the proposal lands in the :class:`~.knobs.KnobSet`; the
   scheduler reads it on its next batch;
3. **observe** — one objective window (:class:`~.objective
   .ObjectiveProbe`); contaminated or traffic-starved windows are
   discarded as failed trials (TPE ignores them);
4. **record** — the loss lands in the Trials (durably, via FileTrials,
   when the service has a root), so a restarted controller resumes its
   optimization history exactly.

Safety is the headline: any SL6xx breach transition during a window —
or any controller exception — triggers an immediate revert to the
static config and a controller FREEZE with exponential re-arm.  Every
decision (proposed / applied / evaluated / discarded / reverted /
rearmed / held) is appended to a bounded ring + durable JSONL log,
surfaced as a flight-recorder provider, and emitted as a
``control.decision`` trace span.
"""

import json
import logging
import os
import threading
import time
from collections import deque

import numpy as np

from .. import tracing
from ..base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    STATUS_FAIL,
    STATUS_OK,
    Domain,
    Trials,
)
from ..utils import coarse_utcnow
from .knobs import guardrail_bounds

logger = logging.getLogger(__name__)

__all__ = ["ControlStats", "Controller", "DEFAULT_TUNED_KNOBS"]

# the knobs the controller searches over (the full KnobSet remains
# settable via /v1/config; admission limits stay operator-owned)
DEFAULT_TUNED_KNOBS = ("batch_window", "max_batch", "max_speculation")

CONTROL_ALGO_PARAMS = {"n_startup_jobs": 5, "n_EI_candidates": 24}


def _null_objective(x):
    return 0.0


class ControlStats:
    """Thread-safe control-plane counters for ``/metrics`` and
    ``/v1/status``.  Constructed by the service unconditionally (the
    actuation counters exist with the controller off), fed by the
    controller thread when ``--self-tune`` is on."""

    def __init__(self):
        self._lock = threading.Lock()
        self._decisions = {}          # guarded-by: _lock  (outcome -> n)
        self._objective = None        # guarded-by: _lock  (last loss)
        self._frozen = False          # guarded-by: _lock
        self._freezes = 0             # guarded-by: _lock
        self._reclaimed = 0           # guarded-by: _lock  (studies stopped)
        self._resumed = 0             # guarded-by: _lock  (studies resumed)

    def record_decision(self, outcome: str):
        with self._lock:
            self._decisions[str(outcome)] = (
                self._decisions.get(str(outcome), 0) + 1
            )

    def set_objective(self, loss):
        with self._lock:
            self._objective = float(loss) if loss is not None else None

    def set_frozen(self, frozen: bool):
        with self._lock:
            if frozen and not self._frozen:
                self._freezes += 1
            self._frozen = bool(frozen)

    def record_reclaimed(self, n: int = 1):
        with self._lock:
            self._reclaimed += int(n)

    def record_resumed(self, n: int = 1):
        with self._lock:
            self._resumed += int(n)

    @property
    def reclaimed_total(self) -> int:
        with self._lock:
            return self._reclaimed

    def control_metrics(self) -> dict:
        """The ``render_prometheus(control=...)`` section."""
        with self._lock:
            return {
                "decisions": dict(self._decisions),
                "objective": self._objective,
                "frozen": 1 if self._frozen else 0,
                "freezes_total": self._freezes,
                "reclaimed_studies_total": self._reclaimed,
                "resumed_studies_total": self._resumed,
            }

    def summary(self) -> dict:
        return self.control_metrics()


class Controller:
    """The self-tuning loop.  One instance per service; its thread is
    started by :meth:`start` and stopped by :meth:`close`.  Tests call
    :meth:`step` directly (one full cycle, synchronous)."""

    # lock-order: _lock (leaf; never held across a window wait or I/O)
    def __init__(self, knobs, probe, rules=None, seed=0, window_s=30.0,
                 interval_s=0.0, trials_dir=None, recorder=None,
                 tracer=None, stats=None, breach_fn=None,
                 algo_params=None, freeze_base_s=60.0,
                 freeze_max_s=3600.0, time_fn=time.monotonic,
                 max_decisions=512):
        self.knobs = knobs
        self.probe = probe
        self.rules = list(rules) if rules is not None else []
        self.seed = int(seed)
        self.window_s = float(window_s)
        self.interval_s = float(interval_s)
        self.recorder = recorder
        self.tracer = tracer
        self.stats = stats if stats is not None else ControlStats()
        # () -> {"transitions": int, "breaching": [rule ids]} — the
        # SL6xx view the safety checks run on (injectable for tests
        # and the forced-breach fixture)
        self.breach_fn = breach_fn if breach_fn is not None else (
            lambda: {"transitions": 0, "breaching": []}
        )
        self.algo_params = dict(CONTROL_ALGO_PARAMS)
        self.algo_params.update(algo_params or {})
        self.freeze_base_s = float(freeze_base_s)
        self.freeze_max_s = float(freeze_max_s)
        self._time = time_fn
        self.tuned = tuple(
            n for n in DEFAULT_TUNED_KNOBS if n in knobs.specs
        )
        self.bounds = self._derive_bounds()
        self.space = self._build_space()
        self.domain = Domain(_null_objective, self.space)
        self.trials_dir = trials_dir
        self.decisions_log_path = (
            os.path.join(trials_dir, "decisions.jsonl")
            if trials_dir else None
        )
        self._lock = threading.Lock()
        self._decisions = deque(maxlen=int(max_decisions))  # guarded-by: _lock
        self._seq = 0                 # guarded-by: _lock  (decision seq)
        self._frozen = False          # guarded-by: _lock
        self._freezes = 0             # guarded-by: _lock
        self._rearm_at = None         # guarded-by: _lock  (monotonic)
        self.rstate = np.random.default_rng(self.seed)
        self.n_draws = 0
        self.trials = self._load_trials()
        self._stop = threading.Event()
        self._thread = None

    # -- space / durability --------------------------------------------
    def _derive_bounds(self) -> dict:
        """Per-tuned-knob (lo, hi): the KnobSpec envelope intersected
        with the SL6xx guardrails and narrowed to a practical band
        around the static config (an int knob may grow at most 4x its
        static value in one campaign — the controller explores, it
        does not teleport)."""
        rails = guardrail_bounds(self.rules)
        static = self.knobs.static_values()
        bounds = {}
        for name in self.tuned:
            spec = self.knobs.specs[name]
            lo, hi = spec.lo, spec.hi
            if name in rails:
                lo = max(lo, spec.kind(rails[name][0]))
                hi = min(hi, spec.kind(rails[name][1]))
            if spec.kind is int:
                hi = min(hi, max(8, int(static.get(name, 0)) * 4))
            bounds[name] = (lo, hi)
        return bounds

    def _build_space(self) -> dict:
        from .. import hp

        space = {}
        for name in self.tuned:
            spec = self.knobs.specs[name]
            lo, hi = self.bounds[name]
            if spec.kind is int:
                space[name] = hp.quniform(name, lo, hi, 1)
            else:
                space[name] = hp.uniform(name, lo, hi)
        return space

    def _load_trials(self):
        """The controller's own Trials — durable (FileTrials) under
        ``trials_dir``, in-memory otherwise.  On a durable resume:
        stranded NEW/RUNNING docs (a kill mid-window) are repaired to
        failed trials, and the proposal RNG fast-forwards past every
        evidenced draw so the next proposal is exactly the one an
        uninterrupted controller would have made."""
        if not self.trials_dir:
            return Trials()
        from ..parallel.file_trials import FileTrials

        trials = FileTrials(self.trials_dir)
        high = -1
        for doc in trials._dynamic_trials:
            high = max(
                high, int(doc.get("misc", {}).get("control_draw", -1))
            )
            if doc["state"] in (JOB_STATE_NEW, JOB_STATE_RUNNING):
                doc["result"] = {
                    "status": STATUS_FAIL, "reason": "interrupted",
                }
                doc["state"] = JOB_STATE_ERROR
                doc["refresh_time"] = coarse_utcnow()
                trials.jobs.write(doc)
        trials.refresh_local()
        self.fast_forward_draws(high + 1)
        if high >= 0:
            logger.info(
                "control: resumed %d prior trials (%d draws)",
                len(trials._dynamic_trials), self.n_draws,
            )
        return trials

    def fast_forward_draws(self, n: int):
        for _ in range(int(n)):
            self.rstate.integers(2 ** 31 - 1)
        self.n_draws = int(n)

    @property
    def durable(self) -> bool:
        return getattr(self.trials, "jobs", None) is not None

    # -- decision record -----------------------------------------------
    def _decision(self, action: str, **fields) -> dict:
        """One flight-recorded, journaled, traced decision record."""
        with self._lock:
            self._seq += 1
            record = {"seq": self._seq, "t": time.time(),
                      "action": str(action)}
            record.update(fields)
            self._decisions.append(record)
        self.stats.record_decision(action)
        if self.decisions_log_path:
            try:
                # CRC-framed append (the response-journal discipline):
                # a mid-write kill tears at most the final record
                with open(self.decisions_log_path, "ab") as f:
                    f.write(tracing.format_record(record))
            except OSError:  # pragma: no cover - best-effort journal
                pass
        self._emit_span(record)
        return record

    def _emit_span(self, record):
        """A ``control.decision`` span per decision.  The controller
        thread owns no request trace, so it begins (and finishes) a
        one-span trace of its own when the tracer samples."""
        tracer = self.tracer
        if tracer is None or not getattr(tracer, "enabled", False):
            return
        trace = tracer.begin()
        if trace is None:
            return
        try:
            with tracing.use_trace(trace):
                attrs = {
                    "action": record["action"],
                    "seq": record["seq"],
                }
                for key in ("loss", "reason", "tid"):
                    if record.get(key) is not None:
                        attrs[key] = record[key]
                if record.get("knobs"):
                    attrs["knobs"] = json.dumps(
                        record["knobs"], sort_keys=True
                    )
                if record.get("fired_rules"):
                    attrs["fired_rules"] = ",".join(
                        record["fired_rules"]
                    )
                with tracing.span("control.decision", **attrs):
                    pass
        finally:
            tracer.finish(trace)

    def recent_decisions(self) -> list:
        """The bounded decision ring, oldest first — the flight
        recorder's ``control`` evidence provider."""
        with self._lock:
            return [dict(r) for r in self._decisions]

    def decision_log_records(self) -> list:
        """Re-read the durable decision journal (restart-surviving;
        CRC-failing torn tail records are skipped, never fatal)."""
        if (
            not self.decisions_log_path
            or not os.path.exists(self.decisions_log_path)
        ):
            return []
        with open(self.decisions_log_path, "rb") as f:
            records, _torn = tracing.parse_trace_log(f.read())
        return records

    # -- freeze / revert ------------------------------------------------
    @property
    def frozen(self) -> bool:
        with self._lock:
            return self._frozen

    def rearm_in_s(self):
        with self._lock:
            if not self._frozen or self._rearm_at is None:
                return None
            return max(self._rearm_at - self._time(), 0.0)

    def _trip(self, reason: str, fired_rules=None):
        """Revert to static + freeze with exponential re-arm — the one
        safety path for breaches AND controller exceptions."""
        try:
            self.knobs.revert(source="controller:revert")
        except Exception:  # pragma: no cover - revert must not raise
            logger.exception("control: revert failed")
        with self._lock:
            self._frozen = True
            self._freezes += 1
            backoff = min(
                self.freeze_base_s * (2 ** (self._freezes - 1)),
                self.freeze_max_s,
            )
            self._rearm_at = self._time() + backoff
        self.stats.set_frozen(True)
        record = self._decision(
            "reverted", reason=reason,
            fired_rules=list(fired_rules or []),
            knobs=self.knobs.values(), rearm_in_s=round(backoff, 3),
        )
        logger.error(
            "control FREEZE (%s): reverted to static config; re-arm "
            "in %.0fs", reason, backoff,
        )
        if self.recorder is not None:
            try:
                self.recorder.dump(
                    "control:revert", context={"decision": record}
                )
            except Exception:  # pragma: no cover - defensive
                logger.exception("control: flight dump failed")

    # -- trial bookkeeping ---------------------------------------------
    def _insert_proposal(self, docs, draw_index):
        for doc in docs:
            doc.setdefault("misc", {})["control_draw"] = int(draw_index)
            doc["state"] = JOB_STATE_RUNNING
        self.trials.insert_trial_docs(docs)
        stored = self.trials._dynamic_trials[-len(docs):]
        if self.durable:
            for doc in stored:
                self.trials.jobs.write(doc)
            self.trials.refresh_local()
        else:
            self.trials.refresh()
        return stored[0]

    def _land_result(self, doc, result):
        doc["result"] = result
        doc["state"] = (
            JOB_STATE_ERROR if result.get("status") == STATUS_FAIL
            else JOB_STATE_DONE
        )
        doc["refresh_time"] = coarse_utcnow()
        if self.durable:
            self.trials.jobs.write(doc)
            self.trials.refresh_local()
        else:
            self.trials.refresh()

    def propose(self) -> tuple:
        """(doc, knob point) — the next TPE proposal over the
        controller's own history, clamped to the guardrail bounds.
        Consumes one seed draw (resume-exact, like study seeds)."""
        from ..algos import tpe
        from ..fmin import space_eval

        seed = int(self.rstate.integers(2 ** 31 - 1))
        draw_index = self.n_draws
        self.n_draws += 1
        new_ids = self.trials.new_trial_ids(1)
        docs = tpe.suggest(
            new_ids, self.domain, self.trials, seed,
            **self.algo_params,
        )
        vals = {
            k: v[0] for k, v in docs[0]["misc"]["vals"].items() if v
        }
        point = self.knobs.clamp(
            space_eval(self.space, vals), bounds=self.bounds
        )
        doc = self._insert_proposal(docs, draw_index)
        return doc, point

    # -- the cycle ------------------------------------------------------
    def step(self) -> str:
        """One control cycle (synchronous; the thread loop and tests
        share it).  Returns the outcome: ``frozen`` / ``rearmed-hold``
        / ``held`` / ``reverted`` / ``discarded`` / ``evaluated`` /
        ``stopped``."""
        now = self._time()
        with self._lock:
            if self._frozen:
                if self._rearm_at is not None and now < self._rearm_at:
                    return "frozen"
                self._frozen = False
        if self.stats is not None and not self.frozen:
            self.stats.set_frozen(False)
        try:
            return self._cycle()
        except Exception as e:
            logger.exception("control cycle failed")
            self._trip(f"exception:{type(e).__name__}")
            return "reverted"

    def _cycle(self) -> str:
        before = self.breach_fn()
        if before.get("breaching"):
            # never tune INTO an active incident — hold at whatever
            # config is live and let the SLO engine's own machinery
            # (and the freeze path, if a transition fires) work
            self._decision(
                "held", reason="active_breach",
                fired_rules=list(before["breaching"]),
            )
            return "held"
        doc, point = self.propose()
        tid = int(doc["tid"])
        self._decision("proposed", tid=tid, knobs=dict(point))
        opened = self.probe.open()
        self.knobs.set_many(point, source="controller")
        self._decision("applied", tid=tid, knobs=dict(point))
        stopped = self._stop.wait(self.window_s)
        if stopped:
            self._land_result(doc, {
                "status": STATUS_FAIL, "reason": "shutdown",
            })
            return "stopped"
        after = self.breach_fn()
        if after.get("transitions", 0) > before.get("transitions", 0):
            self._land_result(doc, {
                "status": STATUS_FAIL, "reason": "breach",
            })
            self._trip("breach", fired_rules=after.get("breaching"))
            return "reverted"
        result = self.probe.close(opened)
        if not result.ok:
            self._land_result(doc, {
                "status": STATUS_FAIL, "reason": result.reason,
            })
            self._decision(
                "discarded", tid=tid, reason=result.reason,
                window=result.to_dict(),
            )
            return "discarded"
        self._land_result(doc, {
            "status": STATUS_OK, "loss": float(result.loss),
            "window": result.to_dict(),
        })
        self.stats.set_objective(result.loss)
        self._decision(
            "evaluated", tid=tid, loss=round(float(result.loss), 6),
            knobs=dict(point), window=result.to_dict(),
        )
        return "evaluated"

    # -- lifecycle ------------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="hyperopt-control", daemon=True
        )
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            outcome = self.step()
            if self._stop.is_set():
                return
            if outcome == "frozen":
                wait = self.rearm_in_s()
                self._stop.wait(
                    min(wait, 1.0) if wait is not None else 1.0
                )
            elif outcome == "held":
                # an active breach: back off a full window before
                # looking again
                self._stop.wait(max(self.window_s, 1.0))
            elif self.interval_s > 0:
                self._stop.wait(self.interval_s)

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    # -- read surface ---------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            frozen = self._frozen
            freezes = self._freezes
            n_decisions = self._seq
        rearm = self.rearm_in_s()
        n_done = n_failed = 0
        for doc in self.trials._dynamic_trials:
            if doc["state"] == JOB_STATE_DONE:
                n_done += 1
            elif doc["state"] == JOB_STATE_ERROR:
                n_failed += 1
        return {
            "frozen": frozen,
            "freezes_total": freezes,
            "rearm_in_s": round(rearm, 3) if rearm is not None else None,
            "n_decisions": n_decisions,
            "n_trials": len(self.trials._dynamic_trials),
            "n_evaluated": n_done,
            "n_discarded": n_failed,
            "n_draws": self.n_draws,
            "window_s": self.window_s,
            "seed": self.seed,
            "durable": self.durable,
            "tuned": list(self.tuned),
            "bounds": {
                k: [self.bounds[k][0], self.bounds[k][1]]
                for k in sorted(self.bounds)
            },
        }
