"""hyperopt_tpu.control — the closed-loop control plane.

The service tunes its own serving knobs with its own optimizer:
a :class:`~.knobs.KnobSet` exposes the scheduler's live parameters
(batch window, batch size k, admission limit, speculation depth) as a
thread-safe runtime-settable table; a :class:`~.controller.Controller`
thread runs ``tpe.suggest`` over a bounded ``hp.*`` space of those
knobs, scoring each configuration over one SLO snapshot window
(:class:`~.objective.ObjectiveProbe`) and journaling its own Trials
durably so a restart resumes the optimization exactly; and
:mod:`.actuation` wires SH5xx search health into admission (stalled
studies release their slots).  Safety: guardrail-clamped proposals,
breach-triggered revert-to-static, exponential freeze/re-arm, and a
flight-recorded + traced decision log.  See ``docs/control.md``.
"""

from .actuation import STOP_RULES, build_stop_fn, evaluate_stop
from .controller import (
    DEFAULT_TUNED_KNOBS,
    Controller,
    ControlStats,
)
from .knobs import KNOB_SPECS, KnobSet, KnobSpec, guardrail_bounds
from .objective import ObjectiveProbe, WindowResult

__all__ = [
    "Controller",
    "ControlStats",
    "DEFAULT_TUNED_KNOBS",
    "KNOB_SPECS",
    "KnobSet",
    "KnobSpec",
    "ObjectiveProbe",
    "STOP_RULES",
    "WindowResult",
    "build_stop_fn",
    "evaluate_stop",
    "guardrail_bounds",
]
