"""Live serving knobs — the thread-safe registry the scheduler reads.

The service's tunable serving parameters (batch window, batch size k,
admission limit, speculation depth) historically froze at construction:
``SuggestScheduler`` copied them into attributes and nothing could move
them without a restart.  :class:`KnobSet` replaces the frozen copies
with one lock-guarded table that the scheduler reads PER BATCH, so a
runtime change (``POST /v1/config``, or the closed-loop controller in
:mod:`.controller`) takes effect on the very next batch — and the
static constructor values remain pinned as the always-available revert
target.

Every mutation is validated against the knob's :class:`KnobSpec`
(type, bounds), recorded in a bounded in-memory provenance ring, and —
when the service runs with a durable root — appended to a JSONL
provenance journal, so "who changed what, when, from what to what" is
answerable after a restart.

With no mutations applied, :meth:`KnobSet.get` returns exactly the
constructor values: the control-plane-off service is behaviorally
identical to the pre-KnobSet service (machine-checked in
``tests/test_control.py``).
"""

import os
import threading
import time
from collections import deque

from ..tracing import format_record, parse_trace_log

__all__ = ["KnobSpec", "KnobSet", "KNOB_SPECS", "guardrail_bounds"]


class KnobSpec:
    """One knob's contract: name, scalar type, and hard bounds.

    The bounds here are the VALIDATION envelope (what ``/v1/config``
    will accept at all); the controller additionally clamps its
    proposals to the narrower guardrail bounds derived from the SL6xx
    rule catalog (:func:`guardrail_bounds`).
    """

    __slots__ = ("name", "kind", "lo", "hi", "doc")

    def __init__(self, name, kind, lo, hi, doc=""):
        self.name = str(name)
        self.kind = kind          # int or float
        self.lo = kind(lo)
        self.hi = kind(hi)
        self.doc = str(doc)

    def coerce(self, value):
        """Type-coerce only, no range check — the constructor-args
        path: static values are the operator's ground truth even when
        they sit outside the runtime-write envelope (``max_queue=0``
        as deliberate admission-off, say)."""
        try:
            if self.kind is int:
                # refuse silent float truncation: 3.7 is not an int
                if isinstance(value, float) and not value.is_integer():
                    raise ValueError
                return int(value)
            return float(value)
        except (TypeError, ValueError):
            raise ValueError(
                f"knob {self.name!r} expects {self.kind.__name__}, "
                f"got {value!r}"
            )

    def validate(self, value):
        """Coerce ``value`` to this knob's type and range-check it.
        Raises ``ValueError`` on a type mismatch or an out-of-bounds
        value — the ``/v1/config`` 400 path."""
        coerced = self.coerce(value)
        if not (self.lo <= coerced <= self.hi):
            raise ValueError(
                f"knob {self.name!r} value {coerced!r} outside "
                f"[{self.lo}, {self.hi}]"
            )
        return coerced

    def clamp(self, value):
        """Coerce and clamp into bounds (the controller's proposal
        path — a TPE point just outside the envelope is pulled to the
        edge, never rejected)."""
        coerced = self.kind(value)
        return max(self.lo, min(self.hi, coerced))

    def to_dict(self):
        return {
            "name": self.name,
            "type": self.kind.__name__,
            "lo": self.lo,
            "hi": self.hi,
            "doc": self.doc,
        }


# the serving-knob catalog: every runtime-tunable parameter of the
# suggest plane.  ``max_speculation`` bounds the number of CONCURRENT
# cold-containment background compiles (0 = unbounded, today's
# behavior); it only matters with --cold-fallback on.
KNOB_SPECS = (
    KnobSpec(
        "batch_window", float, 0.0, 0.5,
        doc="seconds the scheduler holds a >1 batch open for stragglers",
    ),
    KnobSpec(
        "max_batch", int, 1, 1024,
        doc="max suggest requests fused into one device program (k)",
    ),
    KnobSpec(
        "max_queue", int, 1, 65536,
        doc="admission limit: queued suggests beyond this get 429",
    ),
    KnobSpec(
        "max_speculation", int, 0, 64,
        doc="max concurrent background cold-containment compiles "
            "(0 = unbounded)",
    ),
)


def guardrail_bounds(rules):
    """Per-knob (lo, hi) overrides derived from the SL6xx rule catalog
    — the controller's proposal clamp.

    The derivation is deliberately conservative: the batch window is
    pure added latency on every coalesced batch, so its ceiling is a
    small fraction of SL602's absolute p99 bound (a controller that
    proposed ``p99_bound_s`` itself would engineer the breach it is
    supposed to avoid).  Knobs without a rule-derived bound keep their
    :data:`KNOB_SPECS` envelope.
    """
    bounds = {}
    for rule in rules or ():
        rule_id = getattr(rule, "rule_id", None)
        try:
            obj = rule.objective()
        except Exception:
            continue
        if rule_id == "SL602" and obj.get("p99_bound_s"):
            spec = {s.name: s for s in KNOB_SPECS}["batch_window"]
            hi = min(spec.hi, float(obj["p99_bound_s"]) / 20.0)
            bounds["batch_window"] = (spec.lo, hi)
    return bounds


class KnobSet:
    """The live knob table.  Thread-safe: HTTP handler threads
    (``POST /v1/config``), the controller thread, and the scheduler
    thread read/write concurrently.
    """

    # lock-order: _lock (leaf — never held across I/O other than the
    # provenance append, which is a single O_APPEND write)
    def __init__(self, static=None, journal_path=None,
                 specs=KNOB_SPECS, max_provenance=256):
        self.specs = {s.name: s for s in specs}
        self._lock = threading.Lock()
        values = {s.name: s.kind(s.lo) for s in specs}
        for name, value in dict(static or {}).items():
            if name not in self.specs:
                raise ValueError(f"unknown knob {name!r}")
            values[name] = self.specs[name].coerce(value)
        # the static (constructor) config — the revert target; frozen
        self._static = dict(values)
        self._values = dict(values)   # guarded-by: _lock
        self._provenance = deque(maxlen=int(max_provenance))  # guarded-by: _lock
        self._n_changes = 0           # guarded-by: _lock
        self.journal_path = journal_path
        if journal_path:
            os.makedirs(os.path.dirname(journal_path), exist_ok=True)

    # -- reads ---------------------------------------------------------
    def get(self, name):
        with self._lock:
            return self._values[name]

    def values(self) -> dict:
        with self._lock:
            return dict(self._values)

    def static_values(self) -> dict:
        return dict(self._static)

    @property
    def is_static(self) -> bool:
        with self._lock:
            return self._values == self._static

    @property
    def n_changes(self) -> int:
        with self._lock:
            return self._n_changes

    def provenance(self) -> list:
        """The bounded in-memory change history, oldest first."""
        with self._lock:
            return [dict(r) for r in self._provenance]

    # -- mutation ------------------------------------------------------
    def set_many(self, changes: dict, source: str) -> dict:
        """Validate and apply a batch of knob changes atomically.
        Returns the post-apply values.  Raises ``ValueError`` on ANY
        invalid name/value — all-or-nothing, so a half-valid request
        can never leave the set in a mixed state."""
        validated = {}
        for name, value in dict(changes).items():
            spec = self.specs.get(str(name))
            if spec is None:
                raise ValueError(f"unknown knob {name!r}")
            validated[spec.name] = spec.validate(value)
        return self._apply(validated, source)

    def _apply(self, validated: dict, source: str) -> dict:
        with self._lock:
            before = {k: self._values[k] for k in validated}
            delta = {
                k: v for k, v in validated.items() if before[k] != v
            }
            self._values.update(validated)
            self._n_changes += 1
            record = {
                "t": time.time(),
                "source": str(source),
                "changes": dict(validated),
                "before": before,
                "values": dict(self._values),
                "noop": not delta,
            }
            self._provenance.append(record)
            after = dict(self._values)
        self._append_journal(record)
        return after

    def clamp(self, changes: dict, bounds=None) -> dict:
        """Coerce ``changes`` into the validation envelope (and the
        narrower ``bounds`` overrides when given) WITHOUT applying —
        the controller runs every TPE proposal through this before
        :meth:`set_many`."""
        out = {}
        for name, value in dict(changes).items():
            spec = self.specs[str(name)]
            clamped = spec.clamp(value)
            if bounds and name in bounds:
                lo, hi = bounds[name]
                clamped = max(spec.kind(lo), min(spec.kind(hi), clamped))
            out[spec.name] = clamped
        return out

    def revert(self, source: str) -> dict:
        """Restore the static (constructor) config — the safety path.
        Journaled like any other change, but never re-range-checked:
        the constructor values are legal by definition, even when they
        sit outside the runtime-write envelope."""
        return self._apply(dict(self._static), source=source)

    def _append_journal(self, record):
        if not self.journal_path:
            return
        try:
            # CRC-framed append (the response-journal discipline): a
            # mid-write kill tears at most the final record, and the
            # reader proves it torn instead of guessing
            with open(self.journal_path, "ab") as f:
                f.write(format_record(record))
        except OSError:  # pragma: no cover - provenance is best-effort
            pass

    def journal_records(self) -> list:
        """Re-read the durable provenance journal (restart-surviving
        history; empty without a journal path).  CRC-failing tail
        records from a mid-append kill are skipped, never fatal."""
        if not self.journal_path or not os.path.exists(self.journal_path):
            return []
        with open(self.journal_path, "rb") as f:
            records, _torn = parse_trace_log(f.read())
        return records

    def describe(self) -> dict:
        """The ``GET /v1/config`` knob block: specs + live values +
        static values."""
        with self._lock:
            values = dict(self._values)
            n_changes = self._n_changes
        return {
            "knobs": {
                name: {
                    **spec.to_dict(),
                    "value": values[name],
                    "static": self._static[name],
                }
                for name, spec in sorted(self.specs.items())
            },
            "is_static": values == self._static,
            "n_changes": n_changes,
        }
