"""The controller's objective: one SLO-window delta, scored.

A candidate knob configuration is evaluated over exactly one
observation window using the same delta machinery the SLO engine runs
on (PR 9): snapshot the service's cumulative counters and the warm
suggest histogram at window open, snapshot again at close, and score
the DELTA — never the process-lifetime aggregate, which would let an
old incident bias every future decision.

Score (lower is better)::

    loss = warm_p99_s + queue_weight * mean_queue_depth
           - duty_tiebreak * duty_cycle

The p99 term dominates (it is the SLO the service sells), queue depth
weighs sustained backlog the p99 alone can hide on a quiet tenant, and
the duty-cycle term is a pure tie-breaker (``duty_tiebreak`` is small
enough that it can never trade against a millisecond of p99).

Steady-state convention (PR 7/9): a window containing a request-path
XLA compile event or a chaos injection is CONTAMINATED — the
measurement is real cost but meaningless as a comparison between knob
settings, so the trial is discarded (recorded as a failed trial; TPE
ignores it).  A window with fewer than ``min_warm`` warm suggests is
insufficient traffic and likewise discarded.
"""

import time

from ..observability import quantile_from_counts

__all__ = ["ObjectiveProbe", "WindowResult"]


def _hist_delta(cur, base):
    counts = [
        c - b for c, b in zip(cur["counts"], base["counts"])
    ]
    return {
        "edges": cur["edges"],
        "counts": counts,
        "total": cur["total"] - base["total"],
        "sum_s": cur["sum_s"] - base["sum_s"],
    }


class WindowResult:
    """One evaluated window: either a usable loss or a discard
    reason."""

    __slots__ = (
        "ok", "reason", "loss", "warm_p99_s", "mean_queue_depth",
        "duty_cycle", "warm_count", "wall_s",
    )

    def __init__(self, ok, reason=None, loss=None, warm_p99_s=None,
                 mean_queue_depth=None, duty_cycle=None, warm_count=0,
                 wall_s=0.0):
        self.ok = ok
        self.reason = reason
        self.loss = loss
        self.warm_p99_s = warm_p99_s
        self.mean_queue_depth = mean_queue_depth
        self.duty_cycle = duty_cycle
        self.warm_count = warm_count
        self.wall_s = wall_s

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "reason": self.reason,
            "loss": self.loss,
            "warm_p99_s": self.warm_p99_s,
            "mean_queue_depth": self.mean_queue_depth,
            "duty_cycle": self.duty_cycle,
            "warm_count": self.warm_count,
            "wall_s": round(self.wall_s, 3),
        }


class ObjectiveProbe:
    """Open/close snapshot pairs over the service's live stats.

    Stateless between windows (each :meth:`open` returns a snapshot
    the caller holds), so overlapping evaluations cannot corrupt each
    other and the controller can drop a window on revert without any
    cleanup.
    """

    def __init__(self, service_stats, device_stats=None,
                 fault_stats=None, queue_weight=0.010,
                 duty_tiebreak=1e-4, min_warm=5,
                 time_fn=time.monotonic):
        self.service_stats = service_stats
        self.device_stats = device_stats
        self.fault_stats = fault_stats
        # seconds of loss per unit of mean queue depth: ~10ms per
        # queued request keeps backlog visible without drowning p99
        self.queue_weight = float(queue_weight)
        self.duty_tiebreak = float(duty_tiebreak)
        self.min_warm = int(min_warm)
        self._time = time_fn

    def open(self) -> dict:
        """Snapshot every cumulative source the close-side delta
        needs."""
        snap = {
            "t": self._time(),
            "warm_hist": self.service_stats.warm_hist_state(),
            "counters": self.service_stats.slo_counters(),
            "compile_events": self.service_stats.n_compile_events,
        }
        if self.device_stats is not None:
            snap["device"] = self.device_stats.slo_counters()
        if self.fault_stats is not None:
            snap["injected"] = sum(
                self.fault_stats.injected().values()
            )
        return snap

    def close(self, opened: dict) -> WindowResult:
        """Delta against ``opened`` and score it (or discard)."""
        wall_s = max(self._time() - opened["t"], 1e-9)
        # contamination checks FIRST — a contaminated window's numbers
        # are never even computed, matching the SLO engine's
        # steady-state discipline
        if self.service_stats.n_compile_events > opened["compile_events"]:
            return WindowResult(
                False, reason="contaminated:compile", wall_s=wall_s
            )
        if self.fault_stats is not None:
            injected = sum(self.fault_stats.injected().values())
            if injected > opened.get("injected", 0):
                return WindowResult(
                    False, reason="contaminated:chaos", wall_s=wall_s
                )
        warm = _hist_delta(
            self.service_stats.warm_hist_state(), opened["warm_hist"]
        )
        if warm["total"] < self.min_warm:
            return WindowResult(
                False, reason="insufficient_traffic",
                warm_count=warm["total"], wall_s=wall_s,
            )
        p99 = quantile_from_counts(warm["edges"], warm["counts"], 0.99)
        if p99 is None:
            return WindowResult(
                False, reason="insufficient_traffic",
                warm_count=warm["total"], wall_s=wall_s,
            )
        counters = self.service_stats.slo_counters()
        depth_sum = (
            counters.get("queue_depth_sum", 0)
            - opened["counters"].get("queue_depth_sum", 0)
        )
        depth_n = (
            counters.get("queue_depth_samples", 0)
            - opened["counters"].get("queue_depth_samples", 0)
        )
        mean_depth = (depth_sum / depth_n) if depth_n > 0 else 0.0
        duty = None
        if self.device_stats is not None and "device" in opened:
            dev = self.device_stats.slo_counters()
            busy = dev["busy_s"] - opened["device"]["busy_s"]
            duty = min(max(busy / wall_s, 0.0), 1.0)
        loss = float(p99) + self.queue_weight * mean_depth
        if duty is not None:
            # tie-breaker only: prefer the busier device at equal
            # latency/backlog (throughput per watt), never trade
            # against them
            loss -= self.duty_tiebreak * duty
        return WindowResult(
            True, loss=loss, warm_p99_s=float(p99),
            mean_queue_depth=mean_depth, duty_cycle=duty,
            warm_count=warm["total"], wall_s=wall_s,
        )
