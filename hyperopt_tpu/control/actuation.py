"""Fleet actuation: SH5xx search health wired into admission.

The second loop the control plane closes (ROADMAP "close the loop"):
a study that has provably stopped making progress — SH502 STALLED per
:func:`~hyperopt_tpu.early_stop.no_progress_stop`'s criterion, or
SH505 SPACE_EXHAUSTED — is holding an admission slot
(``max_studies``) that a queued study could use.  With the per-study
``early_stop`` opt-in (default OFF — set at create), the service
checks the criterion after every landed report; a firing study
transitions to a terminal ``stopped`` status, its admission slot is
released (the registry's capacity check counts only active studies),
and the stop surfaces in ``/v1/studies/<id>``.  Every reclaim is
counted (``hyperopt_control_reclaimed_studies_total``) and reversible
(``resume_study`` re-admits the study, subject to capacity).

This module holds the pure pieces — the criterion evaluation and the
stop-record shape; the locking and registry bookkeeping live in
:mod:`hyperopt_tpu.service.core`.
"""

import time

from ..early_stop import no_progress_stop

__all__ = ["build_stop_fn", "evaluate_stop", "STOP_RULES"]

# the SH5xx verdicts that reclaim an admission slot: a STALLED search
# past the no-progress window, or a space with nothing left to sample
STOP_RULES = ("SH502", "SH505")


def build_stop_fn(config: dict, n_startup_jobs=20):
    """The per-study hook from an ``early_stop`` create config::

        {"iteration_stop_count": 20, "percent_increase": 0.0}

    Wraps :func:`~hyperopt_tpu.early_stop.no_progress_stop` with the
    study's own startup-jobs count (the random phase must never trip
    the stall window).  Raises ``ValueError`` on a malformed config —
    the create-path 400."""
    if not isinstance(config, dict):
        raise ValueError(
            f"early_stop must be a config dict, got {config!r}"
        )
    unknown = set(config) - {"iteration_stop_count", "percent_increase"}
    if unknown:
        raise ValueError(
            f"unknown early_stop keys: {sorted(unknown)}"
        )
    iteration_stop_count = int(config.get("iteration_stop_count", 20))
    if iteration_stop_count < 1:
        raise ValueError("iteration_stop_count must be >= 1")
    percent_increase = float(config.get("percent_increase", 0.0))
    return no_progress_stop(
        iteration_stop_count=iteration_stop_count,
        percent_increase=percent_increase,
        n_startup_jobs=int(n_startup_jobs),
    )


def evaluate_stop(stop_fn, trials):
    """None, or the terminal stop record for a study whose criterion
    fired.  Caller holds the study lock (the trials object is read).

    ``no_progress_stop`` fires on SH502 specifically; SPACE_EXHAUSTED
    (SH505) is checked from the same health evaluation — an exhausted
    space cannot progress by definition, so it reclaims the slot under
    the same opt-in."""
    stalled, _ = stop_fn(trials)
    health = stop_fn.search_stats.health()
    fired = [
        r for r in health["rules"] if r["rule"] in STOP_RULES
    ]
    if not stalled and not fired:
        return None
    return {
        "t": time.time(),
        "rule": fired[0]["rule"] if fired else "SH502",
        "rules": [r["rule"] for r in fired],
        "detail": (
            fired[0]["detail"] if fired else "no-progress stop fired"
        ),
        "state": health["state"],
    }
