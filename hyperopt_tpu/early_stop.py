"""Early-stopping policies.

Reference parity (SURVEY.md §2 #19): ``hyperopt/early_stop.py`` —
``no_progress_loss(iteration_stop_count, percent_increase)``.
"""

import logging

logger = logging.getLogger(__name__)


def no_progress_loss(iteration_stop_count=20, percent_increase=0.0):
    """Stop if the best loss has not improved for ``iteration_stop_count``
    consecutive trials (improvement must beat ``percent_increase`` %).

    Returns a callable with the early_stop_fn protocol:
    ``(trials, *args) -> (stop: bool, new_args: list)``.
    """

    def stop_fn(trials, best_loss=None, iteration_no_progress=0):
        new_loss = trials.trials[len(trials.trials) - 1]["result"].get("loss")
        if best_loss is None:
            return False, [new_loss, iteration_no_progress + 1]
        best_loss_threshold = best_loss - abs(best_loss * (percent_increase / 100.0))
        if new_loss is not None and new_loss < best_loss_threshold:
            best_loss = new_loss
            iteration_no_progress = 0
        else:
            iteration_no_progress += 1
            logger.debug(
                "No progress made: %d iteration on %d. best_loss=%.2f, new_loss=%.2f",
                iteration_no_progress,
                iteration_stop_count,
                best_loss if best_loss is not None else float("nan"),
                new_loss if new_loss is not None else float("nan"),
            )
        return (
            iteration_no_progress >= iteration_stop_count,
            [best_loss, iteration_no_progress],
        )

    return stop_fn
