"""Early-stopping policies.

Reference parity (SURVEY.md §2 #19): ``hyperopt/early_stop.py`` —
``no_progress_loss(iteration_stop_count, percent_increase)``.

Beyond the reference: :func:`no_progress_stop` consumes the
search-health telemetry layer (:mod:`hyperopt_tpu.diagnostics`) — it
halts on the SH502 STALLED verdict, which shares its definition with
the ``/v1/study_status`` health block and the ``hyperopt_study_health``
fleet gauges, so "the driver stopped" and "the dashboard says STALLED"
can never disagree.
"""

import logging

logger = logging.getLogger(__name__)


def no_progress_loss(iteration_stop_count=20, percent_increase=0.0):
    """Stop if the best loss has not improved for ``iteration_stop_count``
    consecutive trials (improvement must beat ``percent_increase`` %).

    Returns a callable with the early_stop_fn protocol:
    ``(trials, *args) -> (stop: bool, new_args: list)``.
    """

    def stop_fn(trials, best_loss=None, iteration_no_progress=0):
        new_loss = trials.trials[len(trials.trials) - 1]["result"].get("loss")
        if best_loss is None:
            return False, [new_loss, iteration_no_progress + 1]
        best_loss_threshold = best_loss - abs(best_loss * (percent_increase / 100.0))
        if new_loss is not None and new_loss < best_loss_threshold:
            best_loss = new_loss
            iteration_no_progress = 0
        else:
            iteration_no_progress += 1
            logger.debug(
                "No progress made: %d iteration on %d. best_loss=%.2f, new_loss=%.2f",
                iteration_no_progress,
                iteration_stop_count,
                best_loss if best_loss is not None else float("nan"),
                new_loss if new_loss is not None else float("nan"),
            )
        return (
            iteration_no_progress >= iteration_stop_count,
            [best_loss, iteration_no_progress],
        )

    return stop_fn


def no_progress_stop(iteration_stop_count=20, percent_increase=0.0,
                     n_startup_jobs=20, search_stats=None):
    """Opt-in early stop driven by the SH5xx health classifier: halt
    when the run's :class:`~hyperopt_tpu.diagnostics.SearchStats` fires
    **SH502 STALLED** — no best-loss improvement (beyond
    ``percent_increase`` % of the window-ago best) over the last
    ``iteration_stop_count`` completed trials, evaluated only after the
    ``n_startup_jobs`` warm-up (random-phase noise must never trip it).

    Differences from :func:`no_progress_loss`: the verdict is computed
    from the *best-so-far trail* (an error or NaN trial cannot reset the
    stall counter the way ``no_progress_loss``'s last-loss comparison
    can), warm-up is excluded by construction, and the same rule id the
    fleet dashboards show is the one that stopped the run.

    ``search_stats``: pass the run's shared
    :class:`~hyperopt_tpu.diagnostics.SearchStats` (e.g.
    ``fmin(search_stats=...)``) to reuse its counters; by default the
    hook owns a private instance fed incrementally from the trials
    object each callback.

    Returns a callable with the ``early_stop_fn`` protocol:
    ``(trials, *args) -> (stop: bool, new_args: list)``.
    """
    from .diagnostics import SearchStats

    stats = search_stats if search_stats is not None else SearchStats(
        n_startup_jobs=n_startup_jobs,
        stall_window=iteration_stop_count,
        stall_rel_improve=percent_increase / 100.0,
    )

    def stop_fn(trials, *args):
        stats.observe_trials(trials)
        health = stats.health()
        sh502 = next(
            (r for r in health["rules"] if r["rule"] == "SH502"), None
        )
        if sh502 is not None:
            # the hook acts on SH502 specifically, so log ITS detail —
            # a co-fired higher-priority rule may own health["state"]
            logger.info("no_progress_stop: %s", sh502["detail"])
        return sh502 is not None, []

    stop_fn.search_stats = stats
    return stop_fn
