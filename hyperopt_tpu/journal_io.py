"""Shared CRC-framed ``O_APPEND`` journal I/O.

THE append/resync/compact discipline, in one place.  Four journals in
this codebase independently grew the same on-disk idiom — the response
journal (``service.core.ResponseJournal``), the chaos ``injection_log``,
the compile ledger, and the trace log — and the segmented trial store
makes a fifth.  Each record is written by :func:`tracing.format_record`
as ``\\n<crc32 hex> <json>`` in ONE buffer and issued as ONE
``os.write`` on an ``O_APPEND`` handle, so:

- a torn tail (power loss, ``kill -9`` mid-append) garbles at most the
  record being written, never an acknowledged one;
- the next append's **leading newline** re-synchronizes the reader
  regardless of where the tear landed;
- concurrent appenders (threads or processes on a local filesystem)
  interleave at record granularity, never mid-record.

Readers (:func:`read_records`) skip torn lines and report their count;
callers decide whether a torn count is routine (an active journal tail
after a crash) or a finding (a sealed, immutable segment).

:func:`compact_records` is the matching rewrite half: the latest live
records land in a fresh file published by atomic replace
(``file_trials._atomic_write`` — tmp sibling, fsync, ``os.replace``),
so a crash mid-compaction leaves either the old file or the new one,
never a half-written hybrid.

The durability rules here are machine-enforced by
``analysis.durability_lint`` (DL403: one framed write per append;
DL402/DL404: the replace idiom), which is why the framing expression
stays inline in each appending function.
"""

from __future__ import annotations

import json
import os
import sys
import time
import zlib

from . import tracing

__all__ = [
    "append_record",
    "append_records",
    "frame_record",
    "read_records",
    "read_records_bytes",
    "compact_records",
]


def _stats():
    """The process-wide StoreStats, at zero import cost when the store
    module was never loaded (a sys.modules miss, not an import)."""
    mod = sys.modules.get("hyperopt_tpu.parallel.file_trials")
    return mod.store_stats() if mod is not None else None


def frame_record(payload, *, default=None) -> bytes:
    """One CRC-framed record (``tracing.format_record``) — for callers
    assembling a compaction/replication blob themselves."""
    return tracing.format_record(payload, default=default)


def append_records(path, payloads, *, default=None, fsync=True,
                   fsync_kind="journal", with_offset=False):
    """Append a batch of records as ONE ``O_APPEND`` write (group
    commit): every payload is CRC-framed individually, the frames are
    joined into a single buffer, and one write + (optionally) one
    ``fsync`` covers the whole batch.  Returns bytes written — or
    ``(bytes_written, end_offset)`` with ``with_offset`` (the segment
    store's post-append seal-race check needs to know exactly where its
    bytes landed).

    ``default`` passes through to ``json.dumps`` for codec-bearing
    payloads (datetimes, bytes — the trial-doc codec).  ``fsync=False``
    is for advisory logs (the chaos injection log) whose loss at a
    crash is acceptable; durable journals must keep the default.
    """
    blob = b"".join(
        tracing.format_record(p, default=default) for p in payloads
    )
    t0 = time.perf_counter()
    fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    try:
        os.write(fd, blob)  # ONE write: a tear garbles at most this batch
        end = os.lseek(fd, 0, os.SEEK_CUR)
        if fsync:
            os.fsync(fd)
    finally:
        os.close(fd)
    if fsync:
        stats = _stats()
        if stats is not None:
            stats.record_fsync(
                time.perf_counter() - t0, kind=fsync_kind,
                nbytes=len(blob),
            )
    if with_offset:
        return len(blob), end
    return len(blob)


def append_record(path, payload, **kwargs):
    """Append ONE CRC-framed record (see :func:`append_records`)."""
    return append_records(path, [payload], **kwargs)


def read_records_bytes(raw: bytes, *, object_hook=None):
    """(records, n_torn) from raw journal bytes.  Lines failing their
    CRC or JSON parse count as torn and are skipped — after a mid-write
    SIGKILL only the final append can legitimately be torn."""
    records, torn = [], 0
    for line in raw.split(b"\n"):
        line = line.strip()
        if not line:
            continue
        try:
            crc_hex, body = line.split(b" ", 1)
            if (zlib.crc32(body) & 0xFFFFFFFF) != int(crc_hex, 16):
                raise ValueError("crc mismatch")
            records.append(
                json.loads(body.decode(), object_hook=object_hook)
            )
        except (ValueError, json.JSONDecodeError, UnicodeDecodeError):
            torn += 1
    return records, torn


def read_records(path, *, object_hook=None, missing_ok=True):
    """(records, n_torn) for a journal file.  A missing file reads as
    empty when ``missing_ok`` (a journal that was never appended to)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        if missing_ok:
            return [], 0
        raise
    return read_records_bytes(raw, object_hook=object_hook)


def compact_records(path, payloads, *, default=None,
                    fsync_kind="journal"):
    """Rewrite ``path`` to exactly ``payloads`` (CRC-framed) by atomic
    replace — the compaction half of the journal discipline.  Crash-safe
    at every instruction: the tmp sibling is fsync'd before ``replace``
    publishes it, so readers see the old file or the new one, never a
    partial rewrite.  Returns bytes written."""
    # late import: journal_io must stay importable without the store
    # package (tracing-only consumers), and file_trials imports journal
    # consumers transitively
    from .parallel.file_trials import _atomic_write

    blob = b"".join(
        tracing.format_record(p, default=default) for p in payloads
    )
    return _atomic_write(path, blob, fsync_kind=fsync_kind)
