"""Post-hoc visualization of trials.

Reference parity (SURVEY.md §2 #21): ``hyperopt/plotting.py`` —
``main_plot_history`` (loss vs trial time, colored by status),
``main_plot_histogram``, ``main_plot_vars`` (per-hyperparameter scatter of
loss with log-scale detection), ``main_plot_1D_attachment`` (per-trial
1-D attachment curves, darker for lower loss).

matplotlib is imported lazily so headless installs without it can use the
rest of the framework; pass ``do_show=False`` to compose into figures.
"""

from __future__ import annotations

import logging
import math

import numpy as np

from .base import STATUS_OK

logger = logging.getLogger(__name__)

default_status_colors = {
    "new": "k",
    "running": "g",
    "ok": "b",
    "fail": "r",
}


def _plt():
    import matplotlib.pyplot as plt

    return plt


def main_plot_history(trials, do_show=True, status_colors=None, title="Loss History"):
    """Scatter of loss per trial index, colored by status, with a
    best-so-far line."""
    plt = _plt()
    if status_colors is None:
        status_colors = default_status_colors

    Xs, Ys, Cs, ok = [], [], [], []
    for i, trial in enumerate(trials.trials):
        status = trial["result"].get("status", "new")
        loss = trial["result"].get("loss")
        if loss is None or (isinstance(loss, float) and math.isnan(loss)):
            continue
        Xs.append(i)
        Ys.append(float(loss))
        Cs.append(status_colors.get(status, "k"))
        if status == STATUS_OK:
            ok.append((i, float(loss)))
    plt.scatter(Xs, Ys, c=Cs, s=12)
    if ok:  # best-so-far envelope over ok trials
        xs, ys = zip(*ok)
        best = np.minimum.accumulate(ys)
        plt.plot(xs, best, color="g", label="best so far")
        plt.legend()
    plt.xlabel("trial")
    plt.ylabel("loss")
    plt.title(title)
    if do_show:
        plt.show()
    return plt.gcf()


def main_plot_histogram(trials, do_show=True, title="Loss Histogram"):
    """Histogram of completed-trial losses."""
    plt = _plt()
    status_ok = [
        float(t["result"]["loss"])
        for t in trials.trials
        if t["result"].get("status") == STATUS_OK
        and t["result"].get("loss") is not None
    ]
    if not status_ok:
        logger.warning("main_plot_histogram: no ok trials")
        return None
    plt.hist(status_ok, bins=min(50, max(10, len(status_ok) // 5)))
    plt.xlabel("loss")
    plt.ylabel("frequency")
    plt.title(f"{title}: {len(status_ok)} ok trials")
    if do_show:
        plt.show()
    return plt.gcf()


def main_plot_1D_attachment(
    trials,
    attachment_name,
    do_show=True,
    colorize_by_loss=True,
    max_darkness=0.5,
    num_trials=None,
    preprocessing_fn=lambda x: x,
):
    """One line per trial of a 1-D per-trial attachment (e.g. a learning
    curve stored via ``ctrl.attachments[name] = …``), darker for lower
    loss (reference parity: ``hyperopt/plotting.py —
    main_plot_1D_attachment``).

    ``preprocessing_fn`` maps the stored attachment value (often pickled
    bytes) to a 1-D sequence; ``num_trials`` limits to the most recent N.
    """
    plt = _plt()
    docs = trials.trials if num_trials is None else trials.trials[-num_trials:]
    losses = [
        t["result"].get("loss")
        for t in docs
        if t["result"].get("status") == STATUS_OK
        and t["result"].get("loss") is not None
    ]
    lo = min(losses) if losses else 0.0
    hi = max(losses) if losses else 1.0
    span = (hi - lo) or 1.0
    n_plotted = 0
    for t in docs:
        att = trials.trial_attachments(t)
        if attachment_name not in att:
            continue
        ys = np.asarray(preprocessing_fn(att[attachment_name]), dtype=float)
        if ys.ndim != 1:
            logger.warning(
                "main_plot_1D_attachment: %r on tid %s is not 1-D (shape %s)",
                attachment_name, t.get("tid"), ys.shape,
            )
            continue
        loss = t["result"].get("loss")
        if colorize_by_loss and loss is not None:
            # lo/hi come from OK trials only, but any doc may carry the
            # attachment (e.g. a failed trial with a worse loss) — clamp
            # so the alpha stays a valid color component
            frac = (float(loss) - lo) / span
            darkness = max_darkness * min(1.0, max(0.0, 1.0 - frac))
        else:
            darkness = max_darkness
        plt.plot(ys, color=(0.0, 0.0, 0.0, min(1.0, darkness + 0.1)))
        n_plotted += 1
    if not n_plotted:
        logger.warning(
            "main_plot_1D_attachment: no trials carry attachment %r",
            attachment_name,
        )
    plt.xlabel("index")
    plt.ylabel(attachment_name)
    plt.title(f"{attachment_name} across {n_plotted} trials")
    if do_show:
        plt.show()
    return plt.gcf()


def _looks_log_scaled(vals):
    vals = np.asarray(vals, dtype=float)
    if len(vals) < 4 or np.any(vals <= 0):
        return False
    spread = vals.max() / max(vals.min(), 1e-300)
    return spread > 100.0


def main_plot_vars(
    trials,
    do_show=True,
    colorize_best=None,
    columns=3,
    arrange_by_loss=False,
):
    """Per-hyperparameter scatter of (value, loss); log-scales axes for
    parameters spanning >2 decades (the reference's heuristic)."""
    plt = _plt()
    if not trials.trials:
        logger.warning("main_plot_vars: no trials")
        return None
    idxs, vals = trials.idxs_vals
    losses = trials.losses()
    loss_by_tid = {
        t["tid"]: t["result"].get("loss")
        for t in trials.trials
        if t["result"].get("status") == STATUS_OK
    }
    labels = sorted(vals.keys())
    if not labels:
        return None
    rows = int(np.ceil(len(labels) / columns))
    fig, axes = plt.subplots(
        rows, columns, figsize=(4 * columns, 3 * rows), squeeze=False
    )
    finite_losses = [l for l in losses if l is not None]
    if colorize_best and finite_losses:
        cutoff = float(np.sort(finite_losses)[: int(colorize_best)][-1])
    else:
        cutoff = None
    for ax_i, label in enumerate(labels):
        ax = axes[ax_i // columns][ax_i % columns]
        pts = [
            (v, loss_by_tid[t])
            for t, v in zip(idxs[label], vals[label])
            if loss_by_tid.get(t) is not None
        ]
        if not pts:
            ax.set_title(f"{label} (no data)")
            continue
        xs, ys = zip(*pts)
        if cutoff is not None:
            colors = ["r" if y <= cutoff else "b" for y in ys]
        else:
            colors = "b"
        ax.scatter(xs, ys, c=colors, s=8)
        try:
            if _looks_log_scaled(xs):
                ax.set_xscale("log")
        except (TypeError, ValueError):
            pass
        ax.set_title(label)
        ax.set_ylabel("loss")
    for ax_i in range(len(labels), rows * columns):
        axes[ax_i // columns][ax_i % columns].axis("off")
    fig.tight_layout()
    if do_show:
        plt.show()
    return fig
