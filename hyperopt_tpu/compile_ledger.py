"""Compile-plane observability: a persistent compile ledger and the
AOT warmup driver that replays it before ``/readyz`` goes green.

PR 9's warm/cold latency split proved the service tail is a *compile*
problem (warm p99 4.5 s vs cold p99 58.9 s in ``SLO_SERVE.json``), and
``BENCH_TPU_100k.json`` records 50.7 s of warmup re-paid on every
restart.  This module closes that loop:

- :class:`CompileLedger` — a crash-consistent, per-host JSONL ledger
  (``O_APPEND`` single-write records, ``\\n<crc32 hex> <json>`` — the
  PR 5 journal discipline via :func:`tracing.format_record`) of every
  XLA compile the ``tpe_device`` observers see, keyed by
  ``tpe_device.compile_key(sig, shapes)`` (the shared attribution key
  of PR 6-9) with duration, trial-count bucket, family composition,
  backend, a jax/library version fingerprint, and whether the compile
  was served from the persistent XLA program cache (``cache_hit``) or
  traced+compiled from scratch.  Each record also carries the full
  ``(sig, shapes)`` pair — *enough to rebuild the exact fused program*
  (zero-filled arguments at the recorded shapes reproduce the jit
  cache key), which is what makes ledger-driven warmup possible with
  no study state at all.
- :class:`CompileLedgerRecorder` — the observer pair that feeds the
  ledger from the existing ``tpe_device`` hooks: the suggest observer's
  completion callback stamps duration and the cache-hit delta for every
  dispatch whose launch carried an XLA retrace.
- :class:`WarmupDriver` — at service startup, BEFORE ``/readyz`` goes
  green, replays the ledger's bucket×family grid (fingerprint-matching
  records only — a ledger written by an older jax must not mark
  buckets warm) plus the grid predicted from recovered studies'
  current trial counts (a dry ``suggest_prepare`` probe per study —
  the same inventory the ``RecompilationAuditor.bucket_summary``
  counts), through the REAL dispatch path
  (``tpe_device.multi_family_suggest_async``) off-thread, with
  per-bucket state (pending/compiling/warm/skipped/error) and an ETA
  derived from ledger durations — the ``GET /v1/warmup`` document.
- :func:`enable_persistent_cache` — wires
  ``jax.config.jax_compilation_cache_dir`` (server CLI
  ``--compile-cache-dir``) so a ``kill -9`` restart re-pays near-zero
  compile time, and installs a ``jax.monitoring`` listener so the
  cache's own effectiveness is observed (``cache_hit`` on ledger
  records, ``hyperopt_compile_cache_hits_total`` on ``/metrics``).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from . import journal_io, tracing

logger = logging.getLogger(__name__)

LEDGER_FILENAME = "compile_ledger.jsonl"
# compact the ledger file once appends exceed this multiple of the live
# (distinct-key) entry count — the journal's in-place rewrite discipline
COMPACT_APPEND_FACTOR = 8


# ---------------------------------------------------------------------
# fingerprint + persistent-cache wiring
# ---------------------------------------------------------------------


def fingerprint() -> dict:
    """The ledger's validity scope: jax + library version, backend, and
    the DEVICE TOPOLOGY this process serves on.  A record written under
    a different fingerprint must never mark a bucket warm — an older
    jax's executables (and jit cache keys) are not this process's, and
    a single-chip ledger entry replayed onto a mesh (or vice versa)
    would warm the WRONG program grid: the mesh is part of the jit
    statics, so the sharded and unsharded programs are different
    executables end to end."""
    import jax

    try:
        from . import __version__ as version
    except ImportError:  # pragma: no cover - defensive
        version = "unknown"
    return {
        "version": str(version),
        "jax": str(jax.__version__),
        "backend": str(jax.default_backend()),
        "topology": current_topology(),
    }


# The serving topology this process compiles under: backend + local
# device count + mesh shape ("off" when the service dispatches
# single-chip).  Stamped into every fingerprint; the service sets it
# once at startup from its resolved --mesh flag.
_topology_lock = threading.Lock()
_topology_mesh = "off"  # guarded-by: _topology_lock


def set_topology(mesh) -> dict:
    """Register the serving mesh (any form :func:`~hyperopt_tpu
    .parallel.sharding.mesh_shape_str` accepts) in the process
    fingerprint; returns the resulting topology dict."""
    from .parallel.sharding import mesh_shape_str

    global _topology_mesh
    shape = mesh_shape_str(mesh)
    with _topology_lock:
        _topology_mesh = shape
    return current_topology()


def current_topology() -> dict:
    import jax

    with _topology_lock:
        mesh = _topology_mesh
    return {
        "backend": str(jax.default_backend()),
        "device_count": int(jax.device_count()),
        "mesh": mesh,
    }


# process-global cache-hit accounting fed by jax.monitoring (no
# unregister API, so the listener installs once and counts forever)
_cache_events_lock = threading.Lock()
_cache_events = {"hits": 0, "misses": 0}  # guarded-by: _cache_events_lock
_listener_installed = False  # guarded-by: _cache_events_lock


def _on_jax_event(name, **kwargs):
    if name == "/jax/compilation_cache/cache_hits":
        with _cache_events_lock:
            _cache_events["hits"] += 1
    elif name == "/jax/compilation_cache/cache_misses":
        with _cache_events_lock:
            _cache_events["misses"] += 1


def install_cache_listener() -> bool:
    """Count persistent-cache hits/misses via ``jax.monitoring`` (safe
    to call repeatedly; returns False when the jax build lacks the
    listener API)."""
    global _listener_installed
    # check + register + flip under ONE lock hold: a raced double
    # registration would double-count every cache event forever (jax
    # has no unregister API)
    with _cache_events_lock:
        if _listener_installed:
            return True
        try:
            import jax

            jax.monitoring.register_event_listener(_on_jax_event)
        except Exception:  # pragma: no cover - old jax
            return False
        _listener_installed = True
    return True


def cache_hit_count() -> int:
    with _cache_events_lock:
        return _cache_events["hits"]


def cache_event_counts() -> dict:
    with _cache_events_lock:
        return dict(_cache_events)


def enable_persistent_cache(cache_dir) -> bool:
    """Point jax's persistent XLA program cache at ``cache_dir`` (and
    drop the min-compile-time/entry-size floors so the fused suggest
    programs always land in it), then install the hit/miss listener.
    Returns False (and leaves the config untouched) on failure."""
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        logger.exception(
            "could not enable the persistent compile cache at %r", cache_dir
        )
        return False
    install_cache_listener()
    logger.info("persistent XLA compile cache: %s", cache_dir)
    return True


# ---------------------------------------------------------------------
# (sig, shapes) codec — the replayable program identity
# ---------------------------------------------------------------------


MESH_TOKEN = "__mesh__"


def _jsonable_default(obj):
    """JSON fallback for non-scalar statics: a live Mesh serializes as
    its shape token (``{"__mesh__": "DPxSP"}``) — replay substitutes
    the process's CURRENT mesh when (and only when) the shape matches,
    which the topology fingerprint already guarantees for records that
    reach warmup at all."""
    try:
        from jax.sharding import Mesh

        if isinstance(obj, Mesh):
            from .parallel.sharding import mesh_shape_str

            return {MESH_TOKEN: mesh_shape_str(obj)}
    except Exception:  # pragma: no cover - defensive
        pass
    raise TypeError(
        f"unserializable static {type(obj).__name__!r} in compile record"
    )


def sig_shapes_jsonable(sig, shapes):
    """The JSON form of one ``(sig, shapes)`` trace-observer pair.
    Tuples become lists; every leaf is a scalar (a Mesh static becomes
    its shape token) — the round trip back through
    :func:`requests_from_record` rebuilds value-equal statics, and zero
    arrays at the recorded shapes rebuild the jit cache key."""
    return json.loads(json.dumps([sig, shapes], default=_jsonable_default))


def _key_from_jsonable(jsonable) -> str:
    return json.dumps(jsonable, sort_keys=True)


def replay_key(sig, shapes) -> str:
    """Canonical string identity of one fused program — shared between
    live dispatches and ledger records, whatever side serialized it."""
    return _key_from_jsonable(sig_shapes_jsonable(sig, shapes))


def requests_from_record(rec, mesh=None):
    """Rebuild the ``(kind, args, statics)`` request list of a ledger
    record — zero-filled arguments at the recorded shapes/dtypes, which
    reproduce the exact jit cache key the original dispatch traced.

    ``mesh``: the process's live serving mesh (any form
    ``sharding.resolve_mesh`` accepts).  A record whose program was
    mesh-sharded carries the shape token; replay substitutes the live
    mesh when the shapes match — topology-aware warmup warms the
    SHARDED program grid.  Returns None when the record is not
    replayable (no sig/shapes, or a mesh token this process's topology
    cannot satisfy)."""
    import numpy as np

    sig = rec.get("sig")
    shapes = rec.get("shapes")
    if not sig or not shapes or len(sig) != len(shapes):
        return None
    live_mesh = None
    if mesh is not None:
        from .parallel.sharding import resolve_mesh

        live_mesh = resolve_mesh(mesh)
    requests = []
    for (kind, st_items), fam_shapes in zip(sig, shapes):
        statics = {str(k): _static_value(v) for k, v in st_items}
        rec_mesh = statics.get("mesh")
        if isinstance(rec_mesh, dict) and MESH_TOKEN in rec_mesh:
            from .parallel.sharding import mesh_shape_str

            if (
                live_mesh is None
                or mesh_shape_str(live_mesh) != rec_mesh[MESH_TOKEN]
            ):
                return None  # sharded program, topology unavailable
            statics["mesh"] = live_mesh
        elif rec_mesh is not None:
            return None  # unrecognized mesh encoding (older record)
        try:
            # a TUPLE, exactly like suggest_prepare builds: the args
            # container is part of the jit pytree structure — a list
            # here would silently retrace on the first real dispatch
            args = tuple(
                np.zeros(tuple(int(d) for d in shape), dtype=str(dtype))
                for shape, dtype in fam_shapes
            )
        except TypeError:
            return None
        requests.append((str(kind), args, statics))
    return requests


def _static_value(v):
    """JSON leaves back to the static's original type (tuples in
    statics would arrive as lists; current statics are all scalars,
    but a nested tuple must rebuild hashable for the jit cache key)."""
    if isinstance(v, list):
        return tuple(_static_value(x) for x in v)
    return v


# ---------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------


class CompileLedger:
    """Bounded, crash-consistent compile ledger for one service root.

    On-disk format: append-only JSONL, one ``O_APPEND`` write of
    ``\\n<crc32 hex> <json>`` per record (``tracing.format_record``).
    A torn tail (power loss / ``kill -9`` mid-write) garbles at most
    the record being written; the next append's leading newline
    re-synchronizes the reader (``tracing.parse_trace_log``).  The
    in-memory view keeps the LATEST record per program identity
    (:func:`replay_key`); the file compacts in place (atomic replace)
    once appends exceed ``COMPACT_APPEND_FACTOR``x the live count.

    ``path=None`` keeps the ledger in memory only (an ephemeral server
    still gets warm-key accounting and /v1/warmup, just no restart
    memory).
    """

    # lock-order: _lock
    def __init__(self, path=None):
        self.path = path
        self._lock = threading.Lock()
        self._by_key = {}  # guarded-by: _lock  (replay_key -> record)
        self._order = []  # guarded-by: _lock  (replay keys, oldest first)
        self._seq = 0  # guarded-by: _lock
        self._appends_since_compact = 0  # guarded-by: _lock
        self.n_torn_lines = 0  # from the last load; read-only after init
        self._n_recorded = 0  # guarded-by: _lock  (this process's appends)
        if self.path:
            self._load()

    def _load(self):
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        records, self.n_torn_lines = tracing.parse_trace_log(raw)
        if self.n_torn_lines:
            logger.warning(
                "compile ledger %s: %d torn line(s) skipped (crash-"
                "consistent resync)", self.path, self.n_torn_lines,
            )
        records.sort(key=lambda r: int(r.get("seq", 0)))
        with self._lock:
            for rec in records:
                key = rec.get("replay_key") or replay_key(
                    rec.get("sig") or [], rec.get("shapes") or []
                )
                if key not in self._by_key:
                    self._order.append(key)
                self._by_key[key] = rec
                self._seq = max(self._seq, int(rec.get("seq", 0)))

    def record_compile(self, sig, shapes, duration_s, cache_hit=False,
                       fp=None, n_requests=None, source="dispatch"):
        """Journal one observed XLA compile of the fused suggest
        program.  ``sig``/``shapes`` are exactly what a
        ``tpe_device._trace_observers`` entry receives; the record is
        self-sufficient for replay (see :func:`requests_from_record`)."""
        from .algos import tpe_device

        bucket, families = tpe_device.compile_key(sig, shapes)
        jsonable = sig_shapes_jsonable(sig, shapes)
        key = _key_from_jsonable(jsonable)  # == replay_key(sig, shapes)
        with self._lock:
            self._seq += 1
            self._n_recorded += 1
            rec = {
                "seq": self._seq,
                "bucket": int(bucket),
                "families": str(families),
                "duration_s": round(float(duration_s), 6),
                "cache_hit": bool(cache_hit),
                "source": str(source),
                "fingerprint": dict(fp) if fp is not None else fingerprint(),
                "n_requests": (
                    int(n_requests) if n_requests is not None else None
                ),
                "sig": jsonable[0],
                "shapes": jsonable[1],
                "replay_key": key,
            }
            if key not in self._by_key:
                self._order.append(key)
            self._by_key[key] = rec
            if self.path:
                # one crash-atomic O_APPEND write + fsync — a torn
                # tail garbles at most this record, resync'd on load.
                # The ledger lock deliberately serializes journal I/O:
                # appends must land in seq order and must not
                # interleave with the compaction rewrite below.
                journal_io.append_record(  # lint: disable=RL305
                    self.path, rec, fsync_kind="ledger"
                )
                self._appends_since_compact += 1
                if self._appends_since_compact > (
                    COMPACT_APPEND_FACTOR * max(len(self._order), 1)
                ):
                    # compaction: rewrite with only the live (latest-
                    # per-key) entries — atomic replace, crash-safe
                    journal_io.compact_records(
                        self.path,
                        [self._by_key[k] for k in self._order],
                        fsync_kind="ledger",
                    )
                    self._appends_since_compact = 0
        return rec

    # -- reads ---------------------------------------------------------
    def entries(self, current_fingerprint=None):
        """Latest record per program identity, oldest first.  With
        ``current_fingerprint``, stale records (written by a different
        jax/library/backend) are EXCLUDED — the fingerprint gate that
        keeps an old ledger from marking buckets warm it cannot warm."""
        with self._lock:
            recs = [self._by_key[k] for k in self._order]
        if current_fingerprint is None:
            return recs
        return [
            r for r in recs
            if r.get("fingerprint") == dict(current_fingerprint)
        ]

    def grid(self, current_fingerprint=None) -> dict:
        """{(bucket, families): {"n", "duration_s", "cache_hits"}} over
        the live entries — the bucket×family inventory the warmup
        report and /v1/warmup aggregate by."""
        out = {}
        for rec in self.entries(current_fingerprint=current_fingerprint):
            key = (int(rec.get("bucket", 0)), str(rec.get("families")))
            slot = out.setdefault(
                key, {"n": 0, "duration_s": 0.0, "cache_hits": 0}
            )
            slot["n"] += 1
            slot["duration_s"] = max(
                slot["duration_s"], float(rec.get("duration_s") or 0.0)
            )
            slot["cache_hits"] += 1 if rec.get("cache_hit") else 0
        return out

    def __len__(self):
        with self._lock:
            return len(self._order)

    def summary(self) -> dict:
        with self._lock:
            recs = [self._by_key[k] for k in self._order]
            n_recorded = self._n_recorded
        return {
            "path": self.path,
            "entries": len(recs),
            "recorded_this_process": n_recorded,
            "torn_lines": self.n_torn_lines,
            "cache_hits": sum(1 for r in recs if r.get("cache_hit")),
            "total_compile_s": round(
                sum(float(r.get("duration_s") or 0.0) for r in recs), 3
            ),
            "cache_events": cache_event_counts(),
        }


# ---------------------------------------------------------------------
# the recorder (tpe_device observer pair)
# ---------------------------------------------------------------------


class CompileLedgerRecorder:
    """Feeds the ledger from the existing ``tpe_device`` dispatch
    observers: for every fused dispatch whose launch carried an XLA
    retrace (``event["compiled"]``), append one ledger record with the
    launch duration (trace + compile happen synchronously inside the
    jitted call) and the persistent-cache hit delta across the launch.

    ``cache_hit`` is a windowed attribution (dispatch → resolve delta
    of a process-global counter): cold launches serialize on
    ``tpe_device._cold_launch_lock``, so two compiles never overlap,
    but another thread's compile landing in THIS dispatch's
    launch→resolve gap can still mislabel — acceptable for an
    effectiveness signal, not an exact per-program ledger field.
    """

    def __init__(self, ledger: CompileLedger):
        self.ledger = ledger
        self._observer = None
        self._fp = None  # stamped lazily (jax initialized by 1st dispatch)

    def install(self):
        from .algos import tpe_device

        if self._observer is not None:
            return self
        ledger = self.ledger
        recorder = self

        def on_dispatch(requests):
            # steady-state cost is ONE closure + a counter read: the
            # (sig, shapes) identity is derived lazily, only for the
            # rare dispatch that actually compiled (shape/dtype
            # metadata stays readable even if a buffer was donated by
            # a later history append)
            hits_before = cache_hit_count()

            def on_done(event):
                if not event.get("compiled"):
                    return
                if recorder._fp is None:
                    recorder._fp = fingerprint()
                try:
                    sig = tpe_device._multi_sig(requests)
                    shapes = tpe_device.args_shapes(
                        [args for _, args, _ in requests]
                    )
                    ledger.record_compile(
                        sig, shapes,
                        duration_s=float(event.get("launch_s") or 0.0),
                        cache_hit=cache_hit_count() > hits_before,
                        fp=recorder._fp,
                        n_requests=event.get("n_requests"),
                    )
                except Exception:  # observer callbacks must not raise
                    logger.exception("compile-ledger record failed")

            return on_done

        tpe_device._suggest_observers.append(on_dispatch)
        self._observer = on_dispatch
        return self

    def uninstall(self):
        if self._observer is None:
            return
        from .algos import tpe_device

        try:
            tpe_device._suggest_observers.remove(self._observer)
        except ValueError:
            pass
        self._observer = None


# ---------------------------------------------------------------------
# the warmup driver
# ---------------------------------------------------------------------

STATE_PENDING = "pending"
STATE_COMPILING = "compiling"
STATE_WARM = "warm"
STATE_SKIPPED = "skipped"
STATE_ERROR = "error"


class _WarmupItem:
    __slots__ = (
        "bucket", "families", "key", "source", "state", "est_s",
        "actual_s", "requests", "detail",
    )

    def __init__(self, bucket, families, key, source, est_s=None,
                 requests=None):
        self.bucket = int(bucket)
        self.families = str(families)
        self.key = key
        self.source = source  # "ledger" | "predicted"
        self.state = STATE_PENDING
        self.est_s = est_s
        self.actual_s = None
        self.requests = requests
        self.detail = None

    def row(self) -> dict:
        return {
            "bucket": self.bucket,
            "families": self.families,
            "source": self.source,
            "state": self.state,
            "est_s": (
                round(self.est_s, 4) if self.est_s is not None else None
            ),
            "actual_s": (
                round(self.actual_s, 4) if self.actual_s is not None
                else None
            ),
            "detail": self.detail,
        }


class WarmupDriver:
    """Replays the predicted compile grid through the real dispatch
    path before the service reports ready.

    Grid sources, deduplicated by program identity and skipping
    programs this process already traced (``tpe_device.is_warm``):

    - the ledger's fingerprint-matching records (replayed from their
      recorded shapes — no study state needed);
    - a dry ``Study.prepare`` probe per recovered study (the program
      its NEXT suggest will dispatch at the current trial-count
      bucket) — the same per-bucket inventory the
      ``RecompilationAuditor.bucket_summary`` counts.

    ``run()`` executes on a daemon thread (``start()``); ``/readyz``
    gates on :attr:`finished` — *finished*, not *fully warm*: an item
    that errors is reported, never allowed to wedge readiness forever.
    """

    # lock-order: _lock  (never held across a dispatch or a study lock)
    def __init__(self, ledger: CompileLedger = None, studies=(),
                 device_recovery=None, enabled=True, mesh=None):
        self.ledger = ledger
        self._studies = list(studies)
        self.device_recovery = device_recovery
        self.enabled = bool(enabled)
        # the serving mesh: ledger records of SHARDED programs replay
        # against it (topology-aware warmup); the predicted study
        # probes already carry it via Study.prepare
        self.mesh = mesh
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock
        self._planned = False  # guarded-by: _lock
        self._started_at = None  # guarded-by: _lock
        self._finished_at = None  # guarded-by: _lock
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._thread = None
        self._plan_error = None  # guarded-by: _lock
        if not self.enabled:
            self._done.set()

    # -- planning ------------------------------------------------------
    def plan(self):
        """Build the item list (idempotent).  Probing runs under each
        study's lock; ledger decoding never touches the device."""
        from .algos import tpe_device

        with self._lock:
            if self._planned:
                return [i.row() for i in self._items]
            self._planned = True
        items, seen = [], set()

        def add(item):
            if item.key in seen:
                return
            seen.add(item.key)
            items.append(item)

        if self.ledger is not None:
            try:
                fp = fingerprint()
            except Exception:  # pragma: no cover - defensive
                fp = None
            n_stale = 0
            if fp is not None:
                n_stale = len(self.ledger.entries()) - len(
                    self.ledger.entries(current_fingerprint=fp)
                )
            if n_stale:
                logger.warning(
                    "compile ledger: %d stale entr%s (fingerprint "
                    "mismatch) excluded from warmup", n_stale,
                    "y" if n_stale == 1 else "ies",
                )
            for rec in self.ledger.entries(current_fingerprint=fp):
                item = _WarmupItem(
                    rec.get("bucket", 0), rec.get("families"),
                    rec.get("replay_key"), "ledger",
                    est_s=float(rec.get("duration_s") or 0.0) or None,
                )
                requests = requests_from_record(rec, mesh=self.mesh)
                if requests is None:
                    item.state = STATE_SKIPPED
                    item.detail = "record not replayable"
                elif tpe_device.is_warm(requests):
                    item.state = STATE_WARM
                    item.detail = "already traced this process"
                else:
                    item.requests = requests
                add(item)
        for study in self._studies:
            try:
                with study.lock:
                    # a DRY prepare: ids are placeholders (k=1 is the
                    # static; docs are only built by finish, which never
                    # runs) and the probe consumes no seed or trial id
                    prep = study.prepare([0], 0)
            except Exception as e:
                logger.warning(
                    "warmup probe failed for study %r: %s",
                    getattr(study, "study_id", "?"), e,
                )
                continue
            if prep is None:
                continue  # host-side path (startup) — nothing to warm
            requests = prep[0]
            sig = tpe_device._multi_sig(requests)
            shapes = tpe_device.args_shapes(
                [args for _, args, _ in requests]
            )
            bucket, families = tpe_device.compile_key(sig, shapes)
            key = replay_key(sig, shapes)
            est = None
            if self.ledger is not None:
                prior = self.ledger.grid().get((bucket, families))
                est = prior["duration_s"] if prior else None
            item = _WarmupItem(
                bucket, families, key, "predicted", est_s=est,
                requests=requests,
            )
            if tpe_device.is_warm(requests):
                item.state = STATE_WARM
                item.detail = "already traced this process"
                item.requests = None
            add(item)
        with self._lock:
            self._items = items
        return [i.row() for i in items]

    # -- execution -----------------------------------------------------
    def start(self):
        if not self.enabled:
            return self
        with self._lock:
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._run, name="hyperopt-compile-warmup",
                daemon=True,
            )
        self._thread.start()
        return self

    def _run(self):
        with self._lock:
            self._started_at = time.monotonic()
        try:
            try:
                self.plan()
            except Exception as e:
                # an aborted plan must not be SILENT: readiness still
                # goes green (finished, by design), but /v1/warmup and
                # the /readyz body carry the error
                logger.exception("warmup planning failed")
                with self._lock:
                    self._plan_error = repr(e)
                return
            with self._lock:
                items = list(self._items)
            for item in items:
                if self._cancel.is_set():
                    # service closing: skip the remaining grid (a
                    # mid-flight compile cannot be aborted, but no NEW
                    # ones start — a dead service's warmup must not
                    # keep the cold-launch lock busy for its successor)
                    with self._lock:
                        if item.state == STATE_PENDING:
                            item.state = STATE_SKIPPED
                            item.detail = "cancelled (service closed)"
                    continue
                if item.state != STATE_PENDING:
                    continue
                self._warm_one(item)
        finally:
            with self._lock:
                self._finished_at = time.monotonic()
            self._done.set()

    def _warm_one(self, item):
        from .algos import tpe_device

        with self._lock:
            item.state = STATE_COMPILING
        t0 = time.perf_counter()

        def dispatch():
            tpe_device.multi_family_suggest_async(item.requests)()

        try:
            # marked background: a request overlapping a warmup compile
            # (nothing blocks pre-ready suggests) is not cold
            with tpe_device.background_compiles():
                if self.device_recovery is not None:
                    self.device_recovery.run(dispatch)
                else:
                    dispatch()
        except Exception as e:
            logger.warning(
                "warmup compile failed for bucket %d (%s): %r",
                item.bucket, item.families, e,
            )
            with self._lock:
                item.state = STATE_ERROR
                item.detail = repr(e)
                item.requests = None
            return
        with self._lock:
            item.state = STATE_WARM
            item.actual_s = time.perf_counter() - t0
            item.requests = None  # drop the dummy buffers

    def stop(self, timeout=10.0):
        """Cancel remaining items and wait for the thread to exit (a
        mid-flight compile finishes; nothing new starts).  Called by
        ``OptimizationService.close``."""
        self._cancel.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)

    # -- surfaces ------------------------------------------------------
    @property
    def finished(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout=None) -> bool:
        return self._done.wait(timeout)

    def counts(self) -> dict:
        with self._lock:
            items = list(self._items)
        c = {
            STATE_PENDING: 0, STATE_COMPILING: 0, STATE_WARM: 0,
            STATE_SKIPPED: 0, STATE_ERROR: 0,
        }
        for item in items:
            c[item.state] += 1
        return c

    def progress_brief(self) -> dict:
        """The ``/readyz`` body's warmup block — enough for a blocked
        ``ServiceClient.wait_ready`` log line to be actionable."""
        c = self.counts()
        total = sum(c.values())
        with self._lock:
            plan_error = self._plan_error
        out = {
            "enabled": self.enabled,
            "finished": self.finished,
            "warmed": c[STATE_WARM],
            "total": total,
            "compiling": c[STATE_COMPILING],
            "eta_s": self._eta_s(),
        }
        if plan_error is not None:
            out["plan_error"] = plan_error
        return out

    def _eta_s(self):
        with self._lock:
            items = list(self._items)
        remaining = [
            i for i in items
            if i.state in (STATE_PENDING, STATE_COMPILING)
        ]
        if not remaining:
            return 0.0
        known = [i.est_s for i in remaining if i.est_s]
        default = (
            sum(known) / len(known) if known else None
        )
        if default is None:
            done = [i.actual_s for i in items if i.actual_s]
            default = sum(done) / len(done) if done else None
        if default is None:
            return None
        return round(
            sum(i.est_s if i.est_s else default for i in remaining), 3
        )

    def status(self) -> dict:
        """The full ``GET /v1/warmup`` document."""
        with self._lock:
            items = [i.row() for i in self._items]
            started = self._started_at
            finished_t = self._finished_at
        brief = self.progress_brief()
        brief.update({
            "items": items,
            "elapsed_s": (
                round((finished_t or time.monotonic()) - started, 3)
                if started is not None else None
            ),
            "ledger": (
                self.ledger.summary() if self.ledger is not None else None
            ),
        })
        return brief
