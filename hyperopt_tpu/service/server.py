"""HTTP front-end for the optimization service (stdlib only).

``ThreadingHTTPServer`` + JSON bodies over localhost — one handler
thread per in-flight request, which is exactly what the continuous
-batching scheduler wants: every blocked ``/suggest`` is a queued
request the next batch can coalesce.

API (all JSON unless noted)::

    GET  /healthz                         liveness probe
    GET  /readyz                          readiness probe: 200 iff the
                                          registry recovered, the startup
                                          fsck left the store clean, and
                                          the device answered its warm
                                          probe (503 otherwise)
    GET  /metrics                         Prometheus text exposition
    GET  /v1/status                       service-wide stats snapshot
    GET  /v1/alerts                       SL6xx SLO rule table (status,
                                          multi-window burn rates,
                                          breaching subset, flight-
                                          recorder state)
    GET  /v1/warmup                       AOT compile-warmup progress:
                                          per-bucket state (pending/
                                          compiling/warm/skipped/error),
                                          ETA from ledger durations,
                                          compile-ledger summary
    GET  /v1/replicas                     replica-plane document: this
                                          replica's identity, held
                                          studies, takeover log, and the
                                          live replica directory
    GET  /v1/studies                      {"studies": [id, ...]}
    GET  /v1/studies/<id>                 study status document
    POST /v1/studies                      create: {"study_id", "space_b64",
                                          "seed", "algo", "algo_params",
                                          "exist_ok"}
    POST /v1/studies/<id>/suggest         {"n": 1} -> {"trials": [{"tid",
                                          "vals"}, ...]}
    POST /v1/studies/<id>/report          {"tid", "loss", "status"} or
                                          {"tid", "result": {...}}
    POST /v1/shutdown                     drain + stop (localhost control)

Error contract: over-admission returns **429** with a ``Retry-After``
header (retry is always safe — a rejected request had no side effects);
a draining server returns **503**; unknown studies **404**; create
collisions **409**; malformed requests **400**.  Suggest waits are
bounded by the service's ``suggest_timeout`` and surface as **504**.
In multi-replica mode a study served by another replica answers **307
Temporary Redirect** with a ``Location`` header and an ``owner_url``
body field (re-issue the same body there; idempotency keys make the
re-send safe), or a retryable **503** while the owner is unknown
(mid-migration).

Exactly-once contract: the mutating routes (``create``, ``suggest``,
``report``) accept a client-generated ``idempotency_key`` in the body.
A retried request with the same key returns the journaled response
**byte-identical** (these routes serialize through one canonical
encoder) with no second seed draw, trial insert, or loss landing —
which is what makes the client's automatic retry of a connection reset
or timeout safe.  Handler reads are bounded by a socket timeout so a
slow-loris client ties up one handler thread for at most that long.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import tracing
from ..base import STATUS_OK
from .core import (
    BackpressureError,
    NotOwner,
    OptimizationService,
    ServiceDraining,
    StudyExists,
    StudyNotFound,
    StudyStopped,
    _active_chaos,
    canonical_json,
    decode_space,
)

logger = logging.getLogger(__name__)


class _ServiceHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    service: OptimizationService = None


class _Handler(BaseHTTPRequestHandler):
    server_version = "hyperopt-tpu-service/0.1"
    protocol_version = "HTTP/1.1"
    # bound every socket read: a slow-loris client that trickles its
    # request bytes forever holds ONE handler thread for at most this
    # long before the read times out and the connection is dropped
    timeout = 30.0

    # -- plumbing ------------------------------------------------------
    def log_message(self, fmt, *args):  # route access logs to logging
        logger.debug("%s - %s", self.address_string(), fmt % args)

    def _read_json(self):
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return {}
        raw = self.rfile.read(length)
        try:
            return json.loads(raw.decode())
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise ValueError(f"malformed JSON body: {e}")

    def _send(self, code, payload, content_type="application/json",
              headers=()):
        body = (
            payload if isinstance(payload, bytes)
            else json.dumps(payload).encode()
        )
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        trace = getattr(self, "_active_trace", None)
        if trace is not None:
            # echo the trace id so the caller can join its client-side
            # spans (and logs) to the server's trace record
            self.send_header(tracing.TRACE_HEADER, trace.trace_id)
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_error_json(self, code, exc, retry_after=None):
        headers = ()
        if retry_after is not None:
            headers = (("Retry-After", f"{retry_after:.3f}"),)
        self._send(
            code,
            {"error": type(exc).__name__, "detail": str(exc)},
            headers=headers,
        )

    def _is_loopback(self) -> bool:
        """Authenticated-enough for knob writes: the TCP peer must be
        the loopback interface.  Anything routed (including the pod
        network) is refused — runtime reconfiguration is an operator
        action taken ON the host, not a fleet API."""
        host = self.client_address[0]
        return host in ("127.0.0.1", "::1", "::ffff:127.0.0.1")

    def _endpoint_label(self) -> str:
        """Coarse endpoint label for the server-side error counter
        (the SL603 numerator)."""
        path = self.path.split("?", 1)[0].rstrip("/")
        if path.endswith("/suggest"):
            return "suggest"
        if path.endswith("/report"):
            return "report"
        if path == "/v1/studies" and self.command == "POST":
            return "create_study"
        return "other"

    def _dispatch(self, handler):
        try:
            handler()
        except NotOwner as e:
            # multi-replica routing: 307 + owner hint when the lease
            # holder has a live directory record (the client re-issues
            # the SAME body there — idempotency keys make that safe);
            # retryable 503 while the owner is unknown (mid-migration)
            if e.owner_url:
                path = self.path.split("?", 1)[0]
                self._send(
                    307,
                    {
                        "error": "NotOwner",
                        "detail": str(e),
                        "owner_url": e.owner_url,
                        "owner_id": e.owner_id,
                        "study_id": e.study_id,
                    },
                    headers=(
                        ("Location", e.owner_url.rstrip("/") + path),
                    ),
                )
            else:
                self._send_error_json(
                    503, e, retry_after=e.retry_after
                )
        except BackpressureError as e:
            self._send_error_json(429, e, retry_after=e.retry_after)
        except ServiceDraining as e:
            self._send_error_json(503, e, retry_after=e.retry_after)
        except StudyNotFound as e:
            self._send_error_json(404, e)
        except StudyExists as e:
            self._send_error_json(409, e)
        except StudyStopped as e:
            # terminal-but-reversible: the study's early-stop criterion
            # fired; 409 (not 404) because the study still exists and a
            # resume makes the same request valid again
            self._send_error_json(409, e)
        except TimeoutError as e:
            # a timed-out suggest is a failed request the SLO layer
            # must see (4xx client mistakes are not; 429s are counted
            # as rejections at the submit site)
            self.service.stats.record_error(self._endpoint_label())
            self._send_error_json(504, e)
        except (ValueError, KeyError, TypeError) as e:
            self._send_error_json(400, e)
        except BrokenPipeError:  # client went away mid-response
            pass
        except Exception as e:  # pragma: no cover - defensive
            logger.exception("unhandled service error")
            self.service.stats.record_error(self._endpoint_label())
            self._send_error_json(500, e)

    @property
    def service(self) -> OptimizationService:
        return self.server.service

    def _chaos_drop(self, route, key, when) -> bool:
        """Chaos connection-reset site: drop the connection without a
        response, either before any state change (``pre``) or after the
        journal+store commit (``post``).  Returns True when it fired —
        the caller must then send nothing."""
        monkey = _active_chaos()
        if monkey is None:
            return False
        if not monkey.should_reset_connection(route, key, when):
            return False
        logger.info("chaos: dropping connection (%s, %s)", route, when)
        self.close_connection = True
        return True

    def _chaos_partitioned(self) -> bool:
        """Asymmetric-partition chaos site: while a client↔replica
        partition window is open, EVERY request's connection is dropped
        without a response — but the replica's store-side heartbeats
        keep running (replica↔store alive), so its leases stay warm and
        no failover fires.  Exactly the scenario where redirects and
        client-side ring failover, not lease expiry, must carry the
        traffic."""
        monkey = _active_chaos()
        if monkey is None or self.service.replica_set is None:
            return False
        rid = self.service.replica_set.replica_id
        monkey.maybe_client_partition(rid)
        if not monkey.client_partitioned(rid):
            return False
        logger.info("chaos: client partition drop (replica %s)", rid)
        self.close_connection = True
        return True

    # -- routes --------------------------------------------------------
    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if self._chaos_partitioned():
            return

        def handle():
            if path == "/healthz":
                self._send(200, {"ok": True})
            elif path == "/readyz":
                ready = self.service.readiness()
                self._send(200 if ready["ready"] else 503, ready)
            elif path == "/metrics":
                self._send(
                    200,
                    self.service.metrics_text().encode(),
                    content_type="text/plain; version=0.0.4",
                )
            elif path == "/v1/status":
                self._send(200, self.service.service_status())
            elif path == "/v1/alerts":
                self._send(200, self.service.alerts())
            elif path == "/v1/warmup":
                self._send(200, self.service.warmup_status())
            elif path == "/v1/replicas":
                self._send(200, self.service.replica_status())
            elif path == "/v1/config":
                self._send(200, self.service.get_config())
            elif path == "/v1/studies":
                self._send(200, {"studies": self.service.list_studies()})
            elif path.startswith("/v1/studies/"):
                study_id = path[len("/v1/studies/"):]
                if "/" in study_id:
                    raise ValueError(f"bad path {self.path!r}")
                self._send(200, self.service.study_status(study_id))
            else:
                self._send(404, {"error": "NotFound", "detail": path})

        self._dispatch(handle)

    def do_POST(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        if self._chaos_partitioned():
            return

        def handle():
            # read the body FIRST on every route: an unread body left in
            # a keep-alive stream desyncs the next request's parse
            body = self._read_json()
            # client-generated idempotency key (exactly-once contract);
            # None keeps the pre-key at-most-once-per-connection behavior
            idem = body.get("idempotency_key")
            if idem is not None:
                idem = str(idem)
            if path == "/v1/studies":
                study_id = body["study_id"]
                # chaos rolls key on the idempotency key when present:
                # per-LOGICAL-request occurrence streams survive server
                # restarts (the injection-log replay restores hits) and
                # scale with traffic instead of with (route, study)
                if self._chaos_drop("create_study", idem or study_id,
                                    "pre"):
                    return
                out = self.service.create_study(
                    study_id,
                    decode_space(body["space_b64"]),
                    seed=int(body.get("seed", 0)),
                    algo=body.get("algo", "tpe"),
                    algo_params=body.get("algo_params") or None,
                    exist_ok=bool(body.get("exist_ok", False)),
                    early_stop=body.get("early_stop") or None,
                    idempotency_key=idem,
                )
                if self._chaos_drop("create_study", idem or study_id, "post"):
                    return
                # the canonical encoder: a replayed response must be
                # byte-identical to the original, so both serialize here
                self._send(200, canonical_json(out))
            elif path.startswith("/v1/studies/") and path.endswith("/suggest"):
                study_id = path[len("/v1/studies/"):-len("/suggest")]
                if self._chaos_drop("suggest", idem or study_id, "pre"):
                    return
                trials = self.service.suggest(
                    study_id, n=int(body.get("n", 1)),
                    idempotency_key=idem,
                )
                if self._chaos_drop("suggest", idem or study_id, "post"):
                    return
                self._send(200, canonical_json({"trials": trials}))
            elif path.startswith("/v1/studies/") and path.endswith("/report"):
                study_id = path[len("/v1/studies/"):-len("/report")]
                if self._chaos_drop("report", idem or study_id, "pre"):
                    return
                out = self.service.report(
                    study_id,
                    body["tid"],
                    loss=body.get("loss"),
                    status=body.get("status", STATUS_OK),
                    result=body.get("result"),
                    idempotency_key=idem,
                )
                if self._chaos_drop("report", idem or study_id, "post"):
                    return
                self._send(200, canonical_json(out))
            elif path.startswith("/v1/studies/") and path.endswith("/resume"):
                study_id = path[len("/v1/studies/"):-len("/resume")]
                self._send(200, self.service.resume_study(study_id))
            elif path == "/v1/config":
                if not self._is_loopback():
                    self._send(
                        403,
                        {
                            "error": "Forbidden",
                            "detail": "POST /v1/config is "
                                      "localhost-only (operator knob "
                                      "writes are not a fleet API)",
                        },
                    )
                    return
                self._send(
                    200,
                    self.service.set_config(
                        body,
                        source=f"api:{self.client_address[0]}",
                    ),
                )
            elif path == "/v1/shutdown":
                self._send(200, {"ok": True, "draining": True})
                # drain + stop off-thread: this handler must finish its
                # response before serve_forever is told to exit
                threading.Thread(
                    target=self.server._begin_shutdown, daemon=True
                ).start()
            else:
                self._send(404, {"error": "NotFound", "detail": path})

        # header contract: the study routes accept a caller-assigned
        # trace id via X-Hyperopt-Trace (one is assigned here when the
        # header is absent), bind it for the handler, and echo it back.
        # begin() returns None when tracing is disabled — every span
        # call downstream then no-ops (the sampling-off hot path).
        trace = None
        if path.startswith("/v1/studies"):
            trace = self.service.tracer.begin(
                self.headers.get(tracing.TRACE_HEADER)
            )
        self._active_trace = trace
        try:
            with tracing.use_trace(trace):
                self._dispatch(handle)
        finally:
            self._active_trace = None
            self.service.tracer.finish(trace)


class ServiceServer:
    """Owns the HTTP listener thread around an OptimizationService.

    ``port=0`` binds an ephemeral port (read it back from ``.port``).
    ``stop()`` is the graceful path: drain the scheduler (admitted
    suggests complete; new ones get 503), then stop the listener.  All
    study state is write-through, so a subsequent server on the same
    root recovers every study.
    """

    def __init__(self, service: OptimizationService = None,
                 host="127.0.0.1", port=0, **service_kwargs):
        self.service = (
            service if service is not None
            else OptimizationService(**service_kwargs)
        )
        self.httpd = _ServiceHTTPServer((host, port), _Handler)
        self.httpd.service = self.service
        self.httpd._begin_shutdown = self._begin_shutdown
        self.host, self.port = self.httpd.server_address[:2]
        self._thread = None
        self._stop_lock = threading.Lock()
        self._stopped = False  # guarded-by: _stop_lock

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="hyperopt-service-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self):
        """Foreground serving (the CLI path)."""
        self.httpd.serve_forever(poll_interval=0.1)

    def _begin_shutdown(self):
        self.stop(drain=True)

    def stop(self, drain=True, timeout=60.0):
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        # close() drains internally; a zero timeout skips the wait so a
        # wedged dispatch can't burn 2x the drain budget
        self.service.close(timeout=timeout if drain else 0.0)
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def free_port(host="127.0.0.1"):
    """An OS-assigned free TCP port (tests / loadgen convenience)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]
