"""hyperopt_tpu.service — the multi-study optimization service.

One long-lived server process owns the TPU and multiplexes many
concurrent studies onto it through a continuous-batching scheduler:
concurrent ``suggest`` requests are coalesced within a short window and
dispatched as ONE fused device program
(``tpe_device.multi_study_suggest_async``), with per-study durable
state (FileTrials), admission-control backpressure (HTTP 429), and
graceful drain.  See ``docs/service.md`` for the API and the batching /
determinism contracts.

Quick start::

    # server (one per host/pod; owns the device)
    python -m hyperopt_tpu.service --root /srv/hyperopt --port 8777

    # client
    from hyperopt_tpu import hp
    from hyperopt_tpu.service import ServiceClient
    client = ServiceClient("http://127.0.0.1:8777")
    client.minimize("my-study", objective,
                    {"x": hp.uniform("x", -5, 5)}, max_evals=100)
"""

from ..resilience.retry import CircuitOpenError
from .client import (
    ReplicaRedirect,
    ServiceClient,
    ServiceClientError,
    ServiceTransportError,
    parse_retry_after,
)
from .core import (
    BackpressureError,
    NotOwner,
    OptimizationService,
    ResponseJournal,
    ServiceDraining,
    Study,
    StudyExists,
    StudyNotFound,
    StudyRegistry,
    StudyStopped,
    SuggestScheduler,
    canonical_json,
    decode_space,
    encode_space,
)
from .replicas import (
    HashRing,
    OwnershipLost,
    ReplicaDirectory,
    ReplicaSet,
    StudyLeaseStore,
    read_discovery,
)
from .server import ServiceServer, free_port

__all__ = [
    "BackpressureError",
    "CircuitOpenError",
    "HashRing",
    "NotOwner",
    "OptimizationService",
    "OwnershipLost",
    "ReplicaDirectory",
    "ReplicaRedirect",
    "ReplicaSet",
    "ResponseJournal",
    "ServiceClient",
    "ServiceClientError",
    "ServiceDraining",
    "ServiceServer",
    "ServiceTransportError",
    "Study",
    "StudyExists",
    "StudyLeaseStore",
    "StudyNotFound",
    "StudyRegistry",
    "StudyStopped",
    "SuggestScheduler",
    "canonical_json",
    "decode_space",
    "encode_space",
    "free_port",
    "parse_retry_after",
    "read_discovery",
]
